"""Elastic worlds — survive rank loss, shrink the mesh, regrow on rejoin.

The reference dies with its first lost peer: MPI is the only control
plane, and a SIGKILLed rank aborts the world. This module (beyond-
reference scope — no PARITY row maps to it; see ROADMAP open item 3)
turns a rank loss into a *reconfiguration*:

1. **Detection — missed-heartbeat KV lease.** Every process beats a
   sequence counter into the coordination-service KV store
   (``hvd/elastic/g<gen>/hb/p<pid>``). A peer whose counter stops
   advancing for ``HVD_ELASTIC_LEASE_S`` (observed on the *reader's*
   clock — no cross-host clock comparison) hardens into a death
   verdict; ``NegotiationTimeout``/silent negotiation waits consult the
   same lease through :func:`coordinator.set_liveness_probe`, so a
   blocked engine round fails over in seconds instead of waiting out
   ``HVD_NEGOTIATION_TIMEOUT``. Survivors write a tombstone, dump the
   flight recorder with the attribution, and flag the world as changed.

2. **Shrink — in-process reconfiguration.** When the survivors of a
   death verdict are exactly this process's local chips, the world is
   rebuilt in place: the engine is drained (aborting in-flight
   negotiation; the response cache dies with its coordinator and the
   next incarnation starts at a fresh epoch), the poisoned runtime
   backend is *leaked* (its execution chain holds errors from
   collectives the dead peer never joined — destroying it would join
   threads blocked in dead sockets) and a fresh single-process backend
   is built, the 1-D ``'hvd'`` mesh is re-made over the surviving chips
   with re-densified ranks, and the trainer resumes from the newest
   checkpoint through the existing host-first ``broadcast_state``
   pattern — a recompile, not a crash. Multi-controller survivor sets
   (and worlds that would drop below ``HVD_ELASTIC_MIN_NP``) take the
   coordinated-restart path instead: exit with
   :data:`RESTART_EXIT_CODE` and let the supervisor relaunch the full
   world from the newest checkpoint (``run.py --elastic``).

3. **Regrow — blacklist-then-readmit.** The supervisor restarts dead
   children with capped backoff; a recovered rank is blacklisted for
   ``HVD_ELASTIC_BLACKLIST_S`` (flap protection) before the supervisor
   files a rejoin request. Survivors see the request at an epoch
   boundary, checkpoint, and exit for restart; the supervisor relaunches
   the full world at the next **world epoch**, which resumes from the
   newest checkpoint and verifies agreement with
   ``hvd.check_consistency``.

Every transition is observable: ``world.epoch`` / ``world.size`` /
``world.processes`` / ``world.degraded`` gauges, a ``RECONFIGURE``
span in the flight dump written per epoch change, and ``/healthz``
reporting the degraded world (core/sentinel.py).

State shared with the supervisor (join requests, restart votes, the
epoch journal) lives as files under ``HVD_ELASTIC_DIR`` — it must
survive the coordination service, whose host may itself be the casualty.
In-world state (heartbeats, tombstones) rides the existing KV store.

``HVD_ELASTIC`` unset/0 keeps today's fail-fast semantics bit-for-bit:
nothing here activates, the launcher kills the world on first death, and
``NegotiationTimeout`` raises untouched.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.core import faultline as _flt
from horovod_tpu.core import telemetry as _tele
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.sentinel import _env_float

LOG = logging.getLogger("horovod_tpu.elastic")

#: Exit code a member uses to vote for a coordinated full-world restart
#: (regrow at an epoch boundary, multi-survivor shrink, below-min-np).
#: The supervisor (run.py --elastic) relaunches the whole world when it
#: sees it; anything else keeps ordinary meaning.
RESTART_EXIT_CODE = 77


def enabled() -> bool:
    """HVD_ELASTIC=1 opts the process into elastic-world semantics."""
    return os.environ.get("HVD_ELASTIC", "0").lower() not in (
        "0", "", "false", "off")


def lease_s() -> float:
    """Missed-heartbeat lease: a peer silent this long is dead."""
    return _env_float("HVD_ELASTIC_LEASE_S", 3.0)


def grace_s() -> float:
    """Startup grace before a *never-heard-from* peer can be declared
    dead (covers launch/import skew across the cohort)."""
    return _env_float("HVD_ELASTIC_GRACE_S", 30.0)


def blacklist_s() -> float:
    """Readmission backoff for a recovered host (flap protection) — the
    supervisor waits this long after a death before filing the rejoin
    request; doubled per repeat death of the same rank."""
    return _env_float("HVD_ELASTIC_BLACKLIST_S", 5.0)


def kv_failover_s() -> float:
    """How long the coordination-service KV may stop answering before the
    heartbeat/lease plane cuts over to the HVD_ELASTIC_DIR file fallback
    (rank-0/coordination-host death then becomes an attributed verdict
    instead of an unattributed KVTimeout abort)."""
    return _env_float("HVD_ELASTIC_KV_FAILOVER_S", max(1.0, lease_s()))


def rebuild_timeout_s() -> float:
    """Budget for the in-place multi-survivor rebuild (root election,
    address rendezvous, new-backend bring-up); past it survivors fall
    back to the coordinated exit-77 restart."""
    return _env_float("HVD_ELASTIC_REBUILD_TIMEOUT_S", 60.0)


def min_np() -> int:
    """Smallest process count the world may shrink to in place
    (``run.py --elastic --min-np K`` exports it). Below it, survivors
    vote for a full-world restart instead of training degraded."""
    try:
        return max(1, int(os.environ.get("HVD_ELASTIC_MIN_NP", "1")))
    except ValueError:
        return 1


def generation() -> int:
    """Supervisor relaunch counter (0 for the first world)."""
    try:
        return int(os.environ.get("HVD_ELASTIC_GENERATION", "0"))
    except ValueError:
        return 0


def elastic_dir() -> Optional[str]:
    return os.environ.get("HVD_ELASTIC_DIR") or None


def checkpoint_dir() -> Optional[str]:
    """Where elastic training checkpoints live: HVD_CHECKPOINT_DIR, or
    ``<HVD_ELASTIC_DIR>/ckpt`` when a supervisor runs the world."""
    explicit = os.environ.get("HVD_CHECKPOINT_DIR")
    if explicit:
        return explicit
    d = elastic_dir()
    return os.path.join(d, "ckpt") if d else None


def verdict_wait_s() -> float:
    """How long a raised step should wait for a death verdict to explain
    it: two leases (the runtime error usually beats the heartbeat), plus
    the KV-failover window when a file plane exists — a rank-0 death
    must first time the primary plane out before its file-plane lease
    can expire."""
    extra = kv_failover_s() if elastic_dir() else 0.0
    return 2 * lease_s() + extra


class KVPlaneTimeout(Exception):
    """A primary-KV operation exceeded the probe deadline. The dead
    coordination service's failure mode is a HANG, not an error
    (measured: blocked key_value RPCs never return once the host dies),
    so 'not answering' must be detected by deadline, and this exception
    feeds the failover clock exactly like an RPC error."""


class _AbandonableWorker:
    """Runs closures on a worker thread with a deadline. A timed-out
    call leaves the worker BUSY (its thread may be blocked forever
    inside a dead service's RPC); further calls fail fast with
    KVPlaneTimeout — the plane is still unanswering — WITHOUT stacking
    more blocked calls, so a permanently hung plane costs one parked
    thread, not one per tick. If the blocked RPC eventually returns
    (the service was merely slow), the late result is drained on the
    next call and probing resumes on the same thread."""

    def __init__(self):
        import queue as _q

        self._req: "object" = _q.Queue()
        self._res: "object" = _q.Queue()
        self._empty = _q.Empty
        self._busy = False  # a call timed out and is still outstanding
        t = threading.Thread(target=self._loop,
                             name="hvd-elastic-kvprobe", daemon=True)
        t.start()

    def _loop(self):
        while True:
            fn = self._req.get()
            try:
                self._res.put(("ok", fn()))
            except BaseException as exc:
                self._res.put(("exc", exc))

    def call(self, fn, timeout_s: float):
        if self._busy:
            try:
                self._res.get_nowait()  # stale result of the timed-out
                self._busy = False      # call: the thread came back
            except self._empty:
                raise KVPlaneTimeout(
                    "previous primary KV op is still blocked (plane "
                    "unanswering or wedged)") from None
        self._req.put(fn)
        try:
            kind, val = self._res.get(timeout=timeout_s)
        except self._empty:
            self._busy = True
            raise KVPlaneTimeout(
                f"primary KV op exceeded {timeout_s:.1f}s (plane "
                "unanswering or wedged)") from None
        if kind == "exc":
            raise val
        return val


class WorldChanged(Exception):
    """A death verdict landed: the current mesh is gone; reconfigure."""


class ElasticRestartRequired(Exception):
    """This transition needs a supervisor-coordinated full-world restart
    (multi-survivor shrink, below-min-np world, rejoin admission)."""


def _write_json_atomic(path: str, payload: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class FileKV:
    """Atomic-rename file KV — the fallback coordination plane under
    ``HVD_ELASTIC_DIR/kv`` (shared storage is already the supervisor's
    assumption). Survivors cut the heartbeat/lease/tombstone namespace
    over to it when the coordination-service KV stops answering within
    :func:`kv_failover_s` — so losing the KV host (rank 0) yields an
    attributed verdict through THIS plane instead of every survivor
    waiting out ``KVTimeout`` into an unattributed abort. Also the
    rendezvous plane for the in-place multi-survivor rebuild (the
    coordination service being rebuilt cannot host its own election).

    Unlike the TSL KV, writes are overwrite-in-place (rename), so beats
    need no delete+insert dance; readers never observe a torn value."""

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        # Keys are slash-namespaced; files are flat ('~' never appears
        # in our key grammar).
        return os.path.join(self._dir, key.replace("/", "~"))

    def set(self, key: str, value: str, durable: bool = True):
        """``durable=False`` skips the fsync: os.replace alone already
        guarantees readers an untorn value, and ephemeral keys written
        every tick (heartbeat mirrors) must not put a synchronous fsync
        in the control loop — a beat lost to a power failure is
        indistinguishable from one missed tick. Control records
        (tombstones, rendezvous, done marks) stay durable."""
        path = self._path(key)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            fh.write(value)
            if durable:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)

    def try_get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as fh:
                return fh.read()
        except OSError:
            return None

    def get(self, key: str, timeout_s: float) -> Optional[str]:
        """Poll until the key exists; None on timeout (rendezvous
        callers treat absence as 'fall back to the restart path')."""
        deadline = time.monotonic() + timeout_s
        pause = 0.05
        while True:
            val = self.try_get(key)
            if val is not None:
                return val
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(pause, remaining))
            pause = min(pause * 1.5, 0.5)

    def delete(self, key: str):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass


def _on_coordination_error(*args):
    """Replacement for the jax distributed client's process-terminating
    failure callback (missed heartbeats AND polled service errors route
    here). Called from a C++ thread: never raise, never block — just
    record the evidence; the heartbeat lease owns the verdict."""
    try:
        LOG.error(
            "coordination service reported a fatal error (%s) — "
            "suppressed: elastic worlds survive the KV host; the "
            "heartbeat lease / file-plane failover attributes what "
            "actually died", " ".join(str(a) for a in args) or "n/a")
        _tele.REGISTRY.counter("world.coordination_errors").inc()
    except Exception:
        pass


def _rebuild_host() -> str:
    """Reachable host for an elected root's fresh coordination service:
    explicit ``HVD_ELASTIC_REBUILD_HOST``, else the original
    coordinator's host when it is a loopback (the local-launcher world —
    any root is reachable there), else this host's own name (multi-host
    deployments with shared ``HVD_ELASTIC_DIR``)."""
    explicit = os.environ.get("HVD_ELASTIC_REBUILD_HOST")
    if explicit:
        return explicit
    old = os.environ.get("HVD_COORDINATOR_ADDRESS", "")
    host = old.rsplit(":", 1)[0] if ":" in old else ""
    if host in ("127.0.0.1", "localhost", "::1", "[::1]"):
        return host
    import socket

    return socket.gethostname()


def bring_up_distributed(coordinator_address: str, num_processes: int,
                         process_id: int,
                         init_timeout_s: Optional[float] = None):
    """Elastic-mode jax.distributed bring-up.

    The stock ``jax.distributed.initialize`` arms the coordination
    service's own failure detector: ~100 s after a peer stops
    heartbeating, the service propagates a fatal error and every
    surviving client **terminates the process** (LOG(QFATAL) in
    xla/pjrt/distributed/client.h) — the exact opposite of surviving.
    Elastic worlds therefore own the bring-up: the service is created
    with an effectively infinite missed-heartbeat budget (death
    detection is THIS module's KV lease, not the service's), and the
    client skips the shutdown barrier at destruction (it can never pass
    with a dead member). The populated ``global_state`` is the same one
    the rest of jax reads, so everything downstream is unchanged."""
    import jax  # noqa: F401  (backend flags must be settable later)
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension as _xe

    gs = _dist.global_state
    if gs.client is not None:
        return
    bind = "[::]:" + coordinator_address.rsplit(":", 1)[1]
    if process_id == 0 and gs.service is None:
        gs.service = _xe.get_distributed_runtime_service(
            bind, num_processes,
            heartbeat_interval=10, max_missing_heartbeats=1_000_000)
    if init_timeout_s is None:
        init_timeout_s = _env_float("HVD_ELASTIC_INIT_TIMEOUT", 120.0)
    # The client's OWN failure detector must be disarmed too: when the
    # coordination-service HOST dies, every surviving client's
    # PollForError long-poll fails instantly ("Socket closed") and the
    # default callback LOG(FATAL)-terminates the survivor
    # (xla/pjrt/distributed/client.h) — measured as a SIGABRT within
    # ~1 ms of rank 0's SIGKILL. Missed-heartbeat deaths route through
    # the same callback. Replacing it disarms the fatal — but on this
    # jaxlib the binding cannot convert the callback's absl::Status
    # argument to Python, so the invocation throws a C++ cast error
    # that unwinds the agent thread into std::terminate. The termshield
    # (core/native/termshield.cc) parks such threads instead of dying —
    # the leak-the-wedged-thread doctrine this module already applies
    # to backends and dispatch workers. Only with the shield installed
    # is the replacement callback safe; without a toolchain we keep the
    # stock fatal (the supervisor's relaunch then covers KV-host death).
    kwargs = {}
    try:
        from horovod_tpu.core import native as _native

        _native.load_termshield()
        kwargs["missed_heartbeat_callback"] = _on_coordination_error
    except Exception as exc:
        LOG.warning(
            "termshield unavailable (%s): coordination-HOST death will "
            "terminate survivors (stock jax client behavior); the "
            "supervisor relaunch remains the recovery path", exc)
    gs.client = _xe.get_distributed_runtime_client(
        coordinator_address, process_id,
        init_timeout=max(1, int(init_timeout_s)),
        shutdown_on_destruction=False, **kwargs)
    gs.client.connect()
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator_address
    LOG.info("elastic distributed world up: %d process(es), this is %d",
             num_processes, process_id)


class ElasticWorld:
    """Per-process elastic state machine (singleton via
    :func:`get_world`). Inert until :meth:`on_init` sees a live
    topology with elastic enabled."""

    def __init__(self):
        self.active = False
        self.epoch = 0
        self.pid = 0             # process index in the CURRENT world
        self.nproc = 1
        self.initial_np = 1
        self.live: List[int] = []
        self.dead: Dict[int, str] = {}
        self.generation = generation()
        self._changed = threading.Event()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kv = None
        self._seq = 0
        self._started_at = time.monotonic()
        # KV-plane failover state: the file fallback plane (lazy), the
        # first monotonic instant the primary KV stopped answering
        # (None while healthy), and whether the lease plane has cut
        # over to files for good.
        self._file_kv: Optional[FileKV] = None
        self._kv_err_since: Optional[float] = None
        self._failed_over = False
        # Deadline-probed primary-plane access (see _AbandonableWorker).
        # The lock serializes callers: the beat thread and the main
        # thread (announce_done/announce_active) share one worker whose
        # queues carry no call correlation — two concurrent calls would
        # cross-deliver each other's results.
        self._kv_worker: Optional[_AbandonableWorker] = None
        self._kv_worker_lock = threading.Lock()
        # Reconfiguration in progress: the beat loop idles (it must not
        # judge leases over a world being rebuilt) and topology.init's
        # on_init callback must not clobber the state the rebuild is
        # computing.
        self._reconfiguring = False
        # peer -> (last value seen, monotonic time it last CHANGED):
        # liveness is judged by the counter advancing on OUR clock, so
        # cross-host wall-clock skew can never fake a death.
        self._beats: Dict[int, tuple] = {}
        # Peers with a standing announce_done mark (no verdicts for
        # them until they announce_active again).
        self._done_peers: set = set()
        # Backend objects deliberately kept alive forever after a
        # shrink: destroying a runtime whose execution chain still holds
        # threads blocked in a dead peer's sockets is undefined.
        self._leaked: list = []

    # -- lifecycle -----------------------------------------------------------

    def on_init(self, num_processes: int, process_index: int):
        """Called from ``topology.init`` once the world is known."""
        if not enabled():
            return
        if self._reconfiguring:
            # Mid-rebuild re-entry (reconfigure calls topo.init): the
            # rebuild function owns every field it is about to set —
            # adopting jax's re-densified process index here would
            # clobber the stable launch-rank identity the lease/death-
            # note/journal plane keys on.
            return
        self.active = True
        self.pid = process_index
        self.nproc = num_processes
        if not self.live:
            self.initial_np = num_processes
            self.live = list(range(num_processes))
        self.generation = generation()
        self._load_journal()
        self._publish_gauges()
        from horovod_tpu.core import coordinator as _coord

        _coord.set_world_epoch(self.epoch)
        _coord.set_liveness_probe(self.peer_is_dead)
        if num_processes > 1 and (self._thread is None
                                  or not self._thread.is_alive()):
            # is_alive check: the loop self-terminates when a shrink
            # drops the world to one controller — a later re-entry into
            # a multi-process world must get a FRESH lease thread, not
            # a dead handle.
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._beat_loop, name="hvd-elastic-heartbeat",
                daemon=True)
            self._thread.start()
        if self.pid == 0 and elastic_dir() and self.epoch == 0 \
                and self.generation == 0:
            self._write_journal("init")

    def _load_journal(self):
        """Adopt the epoch journal (monotonic across supervisor
        generations): a relaunched generation continues the epoch
        sequence instead of restarting it at 0."""
        d = elastic_dir()
        if not d:
            return
        try:
            with open(os.path.join(d, "epoch.json")) as fh:
                rec = json.load(fh)
            prev = int(rec.get("epoch", 0))
        except (OSError, ValueError):
            return
        if self.generation > int(rec.get("generation", 0)) \
                or rec.get("restart_pending"):
            # This is the relaunched world after a coordinated restart:
            # the regrow/restart transition is the epoch bump.
            self.epoch = prev + 1
            if self.pid == 0:
                self._write_journal("regrow")
        else:
            self.epoch = max(self.epoch, prev)

    def _write_journal(self, kind: str, **extra):
        d = elastic_dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            _write_json_atomic(os.path.join(d, "epoch.json"), {
                "epoch": self.epoch, "kind": kind, "np": self.nproc,
                "generation": self.generation,
                "dead": sorted(self.dead),
                "wall": round(time.time(), 3), **extra})
        except OSError as exc:
            LOG.warning("cannot write elastic epoch journal: %s", exc)

    def _publish_gauges(self):
        """world.* gauges — /healthz, utils/stats and telemetry_report
        all read these."""
        try:
            from horovod_tpu.common import topology as topo

            size = topo.size() if topo.is_initialized() else 0
        except Exception:
            size = 0
        _tele.REGISTRY.gauge("world.epoch").set(self.epoch)
        _tele.REGISTRY.gauge("world.size").set(size)
        _tele.REGISTRY.gauge("world.processes").set(self.nproc)
        _tele.REGISTRY.gauge("world.initial_processes").set(self.initial_np)
        _tele.REGISTRY.gauge("world.degraded").set(
            1 if self.nproc < self.initial_np else 0)
        _tele.REGISTRY.gauge("world.kv_plane").set(
            1 if self._failed_over else 0)

    # -- heartbeat lease ------------------------------------------------------

    def _ns(self) -> str:
        # Epoch-scoped past epoch 0: an in-place shrink re-densifies
        # ranks, and the FILE plane's keys survive the transition — a
        # fresh namespace keeps the new world's beats from colliding
        # with the old world's (the journal makes the epoch agreed
        # across members before any beat lands in the new namespace).
        base = f"hvd/elastic/g{self.generation}"
        return base if self.epoch == 0 else f"{base}/e{self.epoch}"

    def _hb_key(self, p: int) -> str:
        return f"{self._ns()}/hb/p{p}"

    def _done_key(self, p: int) -> str:
        return f"{self._ns()}/done/p{p}"

    def _tomb_key(self, p: int) -> str:
        return f"{self._ns()}/dead/p{p}"

    def _get_kv(self):
        if self._kv is None:
            from horovod_tpu.core import coordinator as _coord

            self._kv = _coord.JaxKV()
        return self._kv

    def _get_file_kv(self) -> Optional[FileKV]:
        if self._file_kv is None:
            d = elastic_dir()
            if d:
                try:
                    self._file_kv = FileKV(os.path.join(d, "kv"))
                except OSError:
                    return None
        return self._file_kv

    def _kv_probe_timeout_s(self) -> float:
        return max(0.2, min(lease_s() / 2.0, kv_failover_s() / 2.0))

    def _primary_call(self, fn):
        """Run a primary-plane KV op under a deadline; a hang counts as
        the plane not answering (KVPlaneTimeout feeds the failover
        clock) and the wedged worker is abandoned. Serialized: the
        worker's queues have no call correlation, so exactly one call
        may be in flight (callers are the beat thread and the main
        thread's announce_* — both bounded by the probe deadline)."""
        with self._kv_worker_lock:
            w = self._kv_worker
            if w is None:
                w = self._kv_worker = _AbandonableWorker()
            # A timed-out worker stays — marked busy — and later calls
            # fail fast until its blocked RPC returns (or never): a
            # permanently hung plane costs ONE parked thread total,
            # while a transient stall resumes probing on the same one.
            return w.call(fn, self._kv_probe_timeout_s())

    def _beat_loop(self):
        interval = max(0.1, lease_s() / 4.0)
        while not self._stop.wait(interval):
            try:
                if not self._beat_once():
                    return
            except Exception:
                # The lease MUST keep running: a surprise here would
                # silently kill liveness detection for the whole world
                # (we'd publish no beats — peers verdict us — and judge
                # none — we'd never detect a real death).
                LOG.warning("heartbeat tick failed; lease continues",
                            exc_info=True)

    def _note_kv_failure(self, exc):
        """A primary-KV operation failed: start (or continue) the
        failover clock; cut over once the plane has been unanswering for
        a full :func:`kv_failover_s` and a file plane exists."""
        now = time.monotonic()
        if self._kv_err_since is None:
            self._kv_err_since = now
            return
        if self._failed_over or now - self._kv_err_since < kv_failover_s():
            return
        fkv = self._get_file_kv()
        if fkv is None:
            return  # no fallback plane: supervisor territory (as before)
        self._failed_over = True
        self._kv = None  # never touch the dead client again from here
        down_s = now - self._kv_err_since
        # Fresh leases on the file plane: every still-live peer has been
        # mirroring beats there all along, but judge from NOW so the
        # primary outage itself cannot be double-counted as peer
        # silence. A peer that is genuinely gone (the KV host) will
        # never beat again on ANY plane and expires one lease later.
        for p in list(self._beats):
            self._beats[p] = (self._beats[p][0], now)
        _tele.REGISTRY.counter("world.kv_failovers").inc()
        _tele.REGISTRY.gauge("world.kv_plane").set(1)
        reason = (f"KV-plane failover: coordination KV unanswering for "
                  f"{down_s:.1f}s (> {kv_failover_s():.1f}s; last error: "
                  f"{str(exc)[:200]}); heartbeat lease now rides the "
                  f"file plane under {elastic_dir()}")
        LOG.error(reason)
        self._dump(reason)

    def _publish_beat(self, kv, value: str, vanish: bool,
                      file_plane: bool):
        if file_plane:
            # Atomic-rename writes overwrite in place — no delete+insert
            # dance, and readers never see a gap. Non-durable: a beat is
            # an ephemeral counter, not a control record.
            if vanish:
                kv.delete(self._hb_key(self.pid))
            else:
                kv.set(self._hb_key(self.pid), value, durable=False)
            return
        # The coordination-service KV is INSERT-ONLY (a second set of
        # the same key fails ALREADY_EXISTS): each beat deletes then
        # re-inserts. A reader landing in the gap sees a missing key for
        # one tick, which deliberately does NOT advance any verdict.
        kv.delete(self._hb_key(self.pid))
        if not vanish:
            kv.set(self._hb_key(self.pid), value)

    def _beat_once(self) -> bool:
        """One heartbeat tick: publish our counter, judge each peer's.
        Returns False when the loop should stop (lone controller)."""
        with self._lock:
            if self.nproc <= 1:
                return False  # shrunk to a lone controller: no lease
            if self._reconfiguring:
                return True  # world mid-rebuild: no publishes, no verdicts
            peers = [p for p in self.live
                     if p != self.pid and p not in self.dead]
        fkv = self._get_file_kv()
        # Fault site hb.beat (core/faultline.py): skip/freeze stop the
        # counter advancing (a process that is alive but not beating —
        # the case the lease must distinguish from death), vanish
        # deletes the key outright.
        fault_mode = _flt.heartbeat()
        if fault_mode not in ("skip", "freeze"):
            self._seq += 1
        beat_val = str(self._seq)
        if fault_mode != "skip":
            # Mirror every beat to the file plane while the primary is
            # healthy: failover is then just "stop asking the dead
            # service" — the fallback plane is already warm.
            if fkv is not None:
                try:
                    self._publish_beat(fkv, beat_val,
                                       fault_mode == "vanish",
                                       file_plane=True)
                except OSError as exc:
                    LOG.warning("file-plane beat failed: %s", exc)
            if not self._failed_over:
                try:
                    # Deadline-probed: a dead service HANGS these RPCs
                    # rather than erroring them (measured) — the probe
                    # turns the hang into failover-clock evidence.
                    self._primary_call(lambda: self._publish_beat(
                        self._get_kv(), beat_val, fault_mode == "vanish",
                        file_plane=False))
                    self._kv_err_since = None
                except Exception as exc:
                    # Coordination service not up yet, or down for good
                    # (rank 0 died): the failover clock decides which.
                    self._note_kv_failure(exc)
                    if not self._failed_over:
                        return True
        reads: Dict[int, tuple] = {}
        if self._failed_over:
            if fkv is None:
                return True
            for p in peers:
                reads[p] = (fkv.try_get(self._hb_key(p)),
                            fkv.try_get(self._tomb_key(p)),
                            fkv.try_get(self._done_key(p)))
        else:
            def _read_all():
                kv = self._get_kv()
                return {p: (kv.try_get(self._hb_key(p)),
                            kv.try_get(self._tomb_key(p)),
                            kv.try_get(self._done_key(p)))
                        for p in peers}

            try:
                reads = self._primary_call(_read_all)
            except Exception as exc:
                self._note_kv_failure(exc)
                return True
        now = time.monotonic()
        for p in peers:
            val, tomb, done = reads[p]
            if done is not None:
                # The peer ANNOUNCED completion (announce_done) before
                # going silent: that is a finished rank, not a casualty
                # — no verdict while the mark stands. (Without this,
                # the first rank to finish a job would be "dead" to any
                # slower peer.) The mark is revocable: announce_active
                # (a later fit) deletes the key and normal leasing
                # resumes, so the beat clock keeps updating below.
                if p not in self._done_peers:
                    self._done_peers.add(p)
                    LOG.info("elastic: process %d announced completion",
                             p)
                if val is not None:
                    last = self._beats.get(p)
                    if last is None or last[0] != val:
                        self._beats[p] = (val, now)
                continue
            if p in self._done_peers:
                # Mark revoked (announce_active): grant a fresh lease —
                # the clock may have run out while the mark stood, and
                # an instant verdict on revocation would punish a peer
                # for having finished politely.
                self._done_peers.discard(p)
                if val is not None:
                    self._beats[p] = (val, now)
            if tomb is not None:
                self._declare_dead(p, "peer tombstone: " + str(tomb)[:200])
                continue
            if val is None:
                # Never-heard-from peer past the startup grace is dead.
                # A peer we HAVE seen is usually just mid delete->set
                # gap — but a key missing for a whole lease means the
                # peer died INSIDE its gap and will never re-insert.
                last = self._beats.get(p)
                if last is None:
                    if now - self._started_at > grace_s():
                        self._declare_dead(
                            p, f"no heartbeat within the "
                               f"{grace_s():.0f}s startup grace")
                elif now - last[1] > lease_s():
                    self._declare_dead(
                        p, f"heartbeat key vanished and stayed gone "
                           f"({now - last[1]:.1f}s > "
                           f"{lease_s():.1f}s lease)")
                continue
            last = self._beats.get(p)
            if last is None or last[0] != val:
                self._beats[p] = (val, now)
            elif now - last[1] > lease_s():
                self._declare_dead(
                    p, f"heartbeat lease expired "
                       f"({now - last[1]:.1f}s > "
                       f"{lease_s():.1f}s without a beat)")
        return True

    def _declare_dead(self, p: int, reason: str):
        if self._failed_over:
            # The verdict was reached through the fallback plane — the
            # attribution must say so (and name the likely first cause:
            # the KV host going down IS how we got here).
            reason += (" [attributed via the fallback file KV plane; "
                       "the coordination KV is down — its host may be "
                       "the casualty]")
        with self._lock:
            if p in self.dead:
                return
            self.dead[p] = reason
        LOG.error("elastic death verdict: process %d is dead (%s); "
                  "world epoch %d will reconfigure", p, reason, self.epoch)
        _tele.REGISTRY.counter("world.deaths").inc()
        tomb = json.dumps({"by": self.pid, "reason": reason})
        if not self._failed_over:
            try:  # probed: a dead service hangs rather than errors
                self._primary_call(
                    lambda: self._get_kv().set(self._tomb_key(p), tomb))
            except Exception:
                pass
        fkv = self._get_file_kv()
        if fkv is not None:
            try:  # mirrored: peers already failed over must see it too
                fkv.set(self._tomb_key(p), tomb)
            except OSError:
                pass
        d = elastic_dir()
        if d:
            try:
                os.makedirs(os.path.join(d, "death"), exist_ok=True)
                _write_json_atomic(
                    os.path.join(d, "death", f"p{p}.json"),
                    {"process": p, "reason": reason, "by": self.pid,
                     "generation": self.generation, "epoch": self.epoch,
                     "wall": round(time.time(), 3)})
            except OSError:
                pass
        # The attributed post-mortem, while the engine ring still holds
        # the rounds that stalled on the dead peer.
        self._dump(f"death verdict: process {p} ({reason}); "
                   f"world epoch {self.epoch} reconfiguring")
        self._changed.set()

    def _dump(self, reason: str):
        try:
            fdir = os.environ.get("HVD_FLIGHT_DIR")
            if fdir:
                os.makedirs(fdir, exist_ok=True)
            events = []
            from horovod_tpu.core import engine as _eng

            e = _eng._engine
            if e is not None:
                if hasattr(e, "recent_events"):
                    events = list(e.recent_events())
                else:
                    events = list(e.timeline.recent())
            last_ts = events[-1].get("ts") if events else 0
            base = int(last_ts) if isinstance(last_ts, (int, float)) else 0
            # The RECONFIGURE span: trace-merge-compatible events framing
            # the transition next to the rounds that led to it.
            events.append({"name": "RECONFIGURE", "ph": "B",
                           "ts": base + 1, "args": {"reason": reason,
                                                    "epoch": self.epoch}})
            events.append({"name": "RECONFIGURE", "ph": "E",
                           "ts": base + 2})
            tl.dump_and_warn(events, reason, tl._process_index(), LOG)
        except Exception:
            LOG.warning("elastic flight dump failed", exc_info=True)

    # -- verdict surface ------------------------------------------------------

    def peer_is_dead(self, p: int) -> Optional[str]:
        """Liveness probe (also wired into coordinator._read_peer): the
        death reason when process ``p`` has a verdict, else None."""
        with self._lock:
            return self.dead.get(p)

    def world_changed(self) -> bool:
        return self._changed.is_set()

    def dead_peers(self) -> Dict[int, str]:
        with self._lock:
            return dict(self.dead)

    def await_verdict(self, timeout_s: float) -> bool:
        """Wait briefly for a death verdict — used when a step raised and
        the caller needs to know whether a dying peer explains it."""
        return self._changed.wait(timeout_s)

    # -- reconfiguration ------------------------------------------------------

    def reconfigure(self):
        """Act on the death verdict: shrink the world in place — to this
        lone controller's chips, or (multi-survivor) to a rebuilt
        multi-process backend over the survivor set rendezvoused through
        the surviving file plane — else raise
        :class:`ElasticRestartRequired` for the supervisor path. Returns
        the new world epoch."""
        with self._lock:
            dead = dict(self.dead)
            survivors = sorted(p for p in self.live if p not in dead)
        if not dead:
            return self.epoch
        if len(survivors) < min_np():
            raise ElasticRestartRequired(
                f"{len(survivors)} survivor(s) < --min-np {min_np()}; "
                "waiting for the supervisor to regrow the world")
        self._reconfiguring = True  # beat loop idles; on_init defers
        try:
            if survivors == [self.pid]:
                return self._shrink_local(dead)
            return self._shrink_multi(dead, survivors)
        finally:
            self._reconfiguring = False

    def _shrink_local(self, dead: Dict[int, str]):
        """The lone-survivor path: rebuild a single-process backend over
        this controller's chips (PR 9 semantics, unchanged)."""
        t0 = time.monotonic()
        old_epoch, old_np = self.epoch, self.nproc
        self._mark_reconfigure_on_timeline()
        self._abandon_engine_if_wedged()
        self._quiesce_engine_bounded()
        from horovod_tpu.common import topology as topo

        LOG.warning("elastic shrink: draining the engine and tearing "
                    "down world epoch %d", old_epoch)
        topo.shutdown()  # drains the engine; aborts in-flight negotiation
        LOG.warning("elastic shrink: old world down; rebuilding a "
                    "single-controller backend over the local chips")
        devs = self._rebuild_local_backend()
        topo.init(devices=devs)
        with self._lock:
            self.epoch = old_epoch + 1
            self.nproc = 1
            self.pid = 0  # ranks re-densified: the lone controller is 0
            self.live = [0]
            self._changed.clear()
            self.dead = {}
            dead_list = sorted(dead)
        from horovod_tpu.core import coordinator as _coord

        _coord.set_world_epoch(self.epoch)
        self._write_journal("shrink", lost=dead_list)
        self._publish_gauges()
        self._clear_draining_marker()
        _tele.REGISTRY.counter("world.reconfigures").inc()
        reason = (f"RECONFIGURE: world epoch {old_epoch} -> {self.epoch}; "
                  f"lost process(es) {dead_list} "
                  f"({'; '.join(dead[p] for p in dead_list)}); "
                  f"continuing with 1/{old_np} controller(s), "
                  f"{len(devs)} rank(s), after "
                  f"{time.monotonic() - t0:.1f}s")
        LOG.warning(reason)
        self._dump(reason)
        return self.epoch

    def _shrink_multi(self, dead: Dict[int, str], survivors: List[int]):
        """In-place multi-survivor shrink: the survivors elect the
        lowest live rank as re-densification root, rendezvous a fresh
        coordination service through the surviving file plane (the
        coordination KV being rebuilt cannot host its own election),
        rebuild a multi-process backend over the survivor set at
        epoch+1 — no supervisor relaunch — and the caller resumes from
        the newest checkpoint exactly as the single-survivor path does.
        Any election/rebuild timeout falls back to the coordinated
        exit-77 restart via :class:`ElasticRestartRequired`."""
        fkv = self._get_file_kv()
        if fkv is None:
            raise ElasticRestartRequired(
                f"survivors {survivors} span multiple controllers and "
                "no HVD_ELASTIC_DIR file plane exists for the rebuild "
                "rendezvous; coordinated restart")
        t0 = time.monotonic()
        old_epoch, old_np = self.epoch, self.nproc
        new_epoch = old_epoch + 1
        root = survivors[0]  # election: lowest live rank, deterministic
        my_new_pid = survivors.index(self.pid)
        ns = f"hvd/elastic/g{self.generation}/rebuild/e{new_epoch}"
        LOG.warning(
            "elastic multi-survivor shrink: survivors %s elect root %d; "
            "world epoch %d -> %d rebuilding in place", survivors, root,
            old_epoch, new_epoch)
        if self.pid == root:
            addr = f"{_rebuild_host()}:{_free_port()}"
            rec = {"addr": addr, "survivors": survivors,
                   "epoch": new_epoch, "root": root,
                   "wall": round(time.time(), 3)}
            try:
                fkv.set(f"{ns}/addr", json.dumps(rec))
            except OSError as exc:
                raise ElasticRestartRequired(
                    f"cannot publish the rebuild rendezvous: {exc}")
        else:
            raw = fkv.get(f"{ns}/addr", rebuild_timeout_s())
            if raw is None:
                raise ElasticRestartRequired(
                    f"rebuild rendezvous timed out after "
                    f"{rebuild_timeout_s():.0f}s waiting for root "
                    f"{root}'s coordinator address")
            try:
                rec = json.loads(raw)
            except ValueError as exc:
                raise ElasticRestartRequired(
                    f"torn rebuild rendezvous record: {exc}")
            if rec.get("survivors") != survivors:
                raise ElasticRestartRequired(
                    f"survivor sets diverged: root published "
                    f"{rec.get('survivors')}, this process sees "
                    f"{survivors}; a coordinated restart resolves it")
            addr = rec["addr"]
        self._mark_reconfigure_on_timeline()
        self._abandon_engine_if_wedged()
        self._quiesce_engine_bounded()
        from horovod_tpu.common import topology as topo

        LOG.warning("elastic shrink: draining the engine and tearing "
                    "down world epoch %d", old_epoch)
        topo.shutdown()  # drains the engine; aborts in-flight rounds
        try:
            devs = self._rebuild_multi_backend(
                addr, len(survivors), my_new_pid)
        except Exception as exc:
            raise ElasticRestartRequired(
                f"multi-survivor backend rebuild failed ({exc}); "
                "falling back to the coordinated restart")
        topo.init()
        with self._lock:
            self.epoch = int(rec["epoch"])
            self.nproc = len(survivors)
            # live/pid keep the ORIGINAL launch ranks: the lease plane,
            # death notes and the supervisor all key on them; only jax's
            # own process ids re-densify (my_new_pid).
            self.live = list(survivors)
            self._changed.clear()
            self.dead = {}
            self._beats.clear()
            self._done_peers.clear()
            dead_list = sorted(dead)
        self._started_at = time.monotonic()  # fresh grace on the new ns
        self._failed_over = False  # the NEW coordination service is up
        self._kv_err_since = None
        self._kv = None  # lazily rebuilt over the new client
        from horovod_tpu.core import coordinator as _coord

        _coord.set_world_epoch(self.epoch)
        if my_new_pid == 0:
            self._write_journal("shrink_multi", lost=dead_list,
                                survivors=survivors)
        self._publish_gauges()
        self._clear_draining_marker()
        _tele.REGISTRY.counter("world.reconfigures").inc()
        reason = (f"RECONFIGURE: world epoch {old_epoch} -> {self.epoch};"
                  f" lost process(es) {dead_list} "
                  f"({'; '.join(dead[p] for p in dead_list)}); "
                  f"continuing IN PLACE with {len(survivors)}/{old_np} "
                  f"controller(s) {survivors} (root {root}), "
                  f"{len(devs)} rank(s), after "
                  f"{time.monotonic() - t0:.1f}s")
        LOG.warning(reason)
        self._dump(reason)
        return self.epoch

    def _rebuild_multi_backend(self, addr: str, num_processes: int,
                               process_id: int):
        """Swap the poisoned runtime for a fresh multi-process backend
        over the survivor set: leak the old client (and old service, if
        this process hosted one — threads may be wedged inside the dead
        peer's sockets), detach jax.distributed, clear backends, and
        bring up a NEW coordination service + client at ``addr`` (the
        elected root hosts the service)."""
        import jax
        from jax._src import distributed as _dist

        gs = _dist.global_state
        try:
            self._leaked.append(jax.local_devices()[0].client)
        except Exception:
            pass
        self._leaked.append(gs.client)
        if getattr(gs, "service", None) is not None:
            # This process hosted the OLD coordination service (a
            # non-zero rank died while rank 0 survived): it still owns
            # its port and threads — leak it, never destroy.
            self._leaked.append(gs.service)
        gs.client = None
        gs.service = None
        try:
            if jax.default_backend() == "cpu":
                # The fresh CPU client must re-wire gloo over the NEW
                # world's store, not the dead one's.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        try:
            jax.clear_backends()
        except AttributeError:  # removed from the jax namespace in 0.4.36
            from jax._src import api as _api

            _api.clear_backends()
        jax.clear_caches()
        bring_up_distributed(addr, num_processes, process_id,
                             init_timeout_s=rebuild_timeout_s())
        return jax.devices()

    def _quiesce_engine_bounded(self):
        """Politeness drain before the shrink teardown (the quiesce
        plane, core/engine.py): close admission so nothing new rides
        into a world being torn down, give in-flight work one bounded
        chance to finish, and log what drained vs what was wedged
        behind the dead peer. No-op when the engine was already
        abandoned (its singleton is gone)."""
        from horovod_tpu.core import engine as _eng

        rep = _eng.quiesce_engine(1.0, reason="elastic shrink")
        if rep is not None:
            LOG.info("elastic shrink: engine quiesce report: %s", rep)

    def _clear_draining_marker(self):
        """A shrink SURVIVES: the quiesce above marked this process
        draining (/healthz non-200), but the successor world is live —
        clear the marker so the degraded-world signal (world.degraded)
        is the only health downgrade left standing."""
        try:
            from horovod_tpu.core import sentinel as _sentinel

            _sentinel.note_draining(None)
            _tele.REGISTRY.gauge("engine.draining").set(0)
        except Exception:
            pass

    def _abandon_engine_if_wedged(self):
        """After a KV-plane failover the engine's control plane is
        wedged inside the dead coordination service (blocked RPCs never
        return — measured): a normal drain would JOIN those threads
        forever. Abandon the engine instead (threads parked, object
        leaked), so topology.shutdown's engine teardown is a no-op."""
        if not self._failed_over:
            return
        from horovod_tpu.core import engine as _eng

        e = _eng.abandon_engine()
        if e is not None:
            LOG.warning("elastic: abandoned the engine (control plane "
                        "wedged in the dead KV service)")
            self._leaked.append(e)

    def _mark_reconfigure_on_timeline(self):
        """Best-effort RECONFIGURE instant on the live engine timeline
        before it is torn down — per-rank traces then carry the
        transition, not just the flight dumps."""
        try:
            from horovod_tpu.core import engine as _eng

            e = _eng._engine
            if e is None:
                return
            if hasattr(e, "_lib") and getattr(e, "_ptr", None):
                e._lib.hvd_engine_timeline_instant(
                    e._ptr, b"world", b"RECONFIGURE",
                    f'"epoch":{self.epoch}'.encode())
            elif hasattr(e, "timeline"):
                e.timeline.instant("world", "RECONFIGURE",
                                   {"epoch": self.epoch})
        except Exception:
            pass

    def _rebuild_local_backend(self):
        """Swap in a fresh single-process runtime.

        The old backend's collective-execution chain is poisoned: the
        program in flight when the peer died eventually fails with a
        socket error, and every execution enqueued after it inherits the
        error forever. The old client (and the arrays living on it) is
        LEAKED — its destructor would join threads still blocked inside
        the dead peer's sockets — and a new backend is created with the
        distributed client detached, so it comes up single-process with
        in-process collectives only."""
        import jax
        from jax._src import distributed as _dist

        gs = _dist.global_state
        try:
            self._leaked.append(jax.local_devices()[0].client)
        except Exception:
            pass
        kv_client = gs.client
        self._leaked.append(kv_client)
        gs.client = None
        gs.num_processes = 1
        gs.process_id = 0
        try:
            if jax.default_backend() == "cpu":
                # The fresh CPU client must not re-wire gloo over the
                # dead world's store.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "none")
        except Exception:
            pass
        try:
            jax.clear_backends()
        except AttributeError:  # removed from the jax namespace in 0.4.36
            from jax._src import api as _api

            _api.clear_backends()
        jax.clear_caches()
        # (topology.shutdown — already run by reconfigure — cleared the
        # mesh-keyed program and zero-tree caches.)
        devs = jax.devices()
        # The KV plane stays reachable (tombstone reads, debugging);
        # jax's own world-size view remains 1.
        gs.client = kv_client
        return devs

    # -- supervisor protocol (files under HVD_ELASTIC_DIR) -------------------

    def restart_requested(self) -> Optional[str]:
        """A pending coordinated-restart request (rejoin admission filed
        by the supervisor, or a member's restart vote), or None."""
        d = elastic_dir()
        if not d:
            return None
        try:
            rejoin = os.path.join(d, "rejoin")
            if os.path.isdir(rejoin):
                reqs = [f for f in os.listdir(rejoin)
                        if f.endswith(".json")]
                if reqs:
                    return f"rejoin request(s) pending: {sorted(reqs)}"
            if os.path.exists(os.path.join(d, "restart.json")):
                with open(os.path.join(d, "restart.json")) as fh:
                    return json.load(fh).get("reason", "restart requested")
        except (OSError, ValueError):
            return None
        return None

    def request_restart(self, reason: str):
        d = elastic_dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            _write_json_atomic(os.path.join(d, "restart.json"),
                               {"reason": reason, "by": self.pid,
                                "generation": self.generation,
                                "wall": round(time.time(), 3)})
        except OSError as exc:
            LOG.warning("cannot file restart request: %s", exc)

    def exit_for_restart(self, reason: str):
        """Leave the process with the supervisor's restart exit code.
        ``os._exit``: interpreter teardown would hang in the distributed
        client/backend destructors of a world with dead members."""
        # A restart voter going silent must read as a PLANNED exit, not
        # a casualty: without the done mark, peers still mid-epoch
        # lease-verdict this rank and shrink pointlessly before
        # honoring the same restart request themselves.
        self.announce_done()
        self._write_journal("restart_pending", restart_pending=True,
                            reason=reason)
        LOG.warning("elastic coordinated restart: %s (exiting with "
                    "code %d for the supervisor)", reason,
                    RESTART_EXIT_CODE)
        self._dump(f"RECONFIGURE: coordinated restart ({reason})")
        try:
            import sys

            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(RESTART_EXIT_CODE)

    def park(self, obj):
        """Keep ``obj`` alive for the rest of the process (the public
        face of the leak list): state donated into a wedged old-world
        execution must never run its destructor — it can block inside
        the dead runtime."""
        self._leaked.append(obj)

    def announce_done(self):
        """Tell the cohort this process finished its training work
        CLEANLY (``Trainer.fit`` calls it at train end; custom loops
        should too, before their final barriers, while the whole cohort
        is still up): silent-after-done peers get no death verdict —
        the last ranks of a finishing job must not shrink the world out
        from under each other. Revoked by :meth:`announce_active`."""
        if not self.active or self.nproc <= 1:
            return
        stamp = str(round(time.time(), 3))
        if not self._failed_over:
            def _mark():
                kv = self._get_kv()
                kv.delete(self._done_key(self.pid))  # insert-only store
                kv.set(self._done_key(self.pid), stamp)

            try:  # probed: must not wedge the exiting main thread
                self._primary_call(_mark)
            except Exception:
                pass
        fkv = self._get_file_kv()
        if fkv is not None:
            try:  # mirrored: a failed-over peer must see the mark too
                fkv.set(self._done_key(self.pid), stamp)
            except OSError:
                pass

    def announce_active(self):
        """Revoke a standing completion mark (a later ``fit`` on the
        same world): peers resume leasing this process normally."""
        if not self.active or self.nproc <= 1:
            return
        if not self._failed_over:
            try:
                self._primary_call(
                    lambda: self._get_kv().delete(
                        self._done_key(self.pid)))
            except Exception:
                pass
        fkv = self._get_file_kv()
        if fkv is not None:
            fkv.delete(self._done_key(self.pid))

    def shutdown(self):
        self._stop.set()

    # -- reporting ------------------------------------------------------------

    def summary(self) -> Optional[dict]:
        if not self.active:
            return None
        try:
            from horovod_tpu.common import topology as topo

            size = topo.size() if topo.is_initialized() else 0
        except Exception:
            size = 0
        with self._lock:
            return {"epoch": self.epoch, "generation": self.generation,
                    "size": size, "processes": self.nproc,
                    "initial_processes": self.initial_np,
                    "degraded": self.nproc < self.initial_np,
                    "kv_plane": ("file" if self._failed_over
                                 else "coordination-service"),
                    "dead": dict(self.dead)}


_world: Optional[ElasticWorld] = None
_world_lock = threading.Lock()


def get_world() -> ElasticWorld:
    global _world
    with _world_lock:
        if _world is None:
            _world = ElasticWorld()
        return _world


def reset_world():
    """Tests only: drop the singleton so a fresh env is re-read."""
    global _world
    with _world_lock:
        if _world is not None:
            _world.shutdown()
        _world = None


def active() -> bool:
    return enabled() and get_world().active


def world_summary() -> Optional[dict]:
    """The /healthz ``world`` section (None when elastic is off)."""
    if not enabled() or _world is None:
        return None
    return _world.summary()


def maybe_restore(trainer, x_sample) -> int:
    """Resume a Trainer from the newest elastic checkpoint; returns the
    epoch to resume AT (0 when there is nothing to restore). The restore
    broadcasts from root — the host-first pattern — so every member of a
    regrown world starts bitwise-identical."""
    from horovod_tpu.utils import checkpoint as _ckpt

    d = checkpoint_dir()
    if not d:
        return 0
    path = _ckpt.latest_checkpoint(d)
    if not path:
        return 0
    trainer.load(path, x_sample)
    trainer.broadcast_state()
    LOG.info("elastic resume: restored %s (epoch %d)", path,
             trainer._epoch)
    return int(trainer._epoch) + 1
