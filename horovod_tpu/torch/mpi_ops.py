"""Torch collective ops through the async engine (reference:
horovod/torch/mpi_ops.py — same sync/async/in-place surface, same handle
poll/synchronize model, same autograd gradient registrations).

Torch has no TPU backend here; tensors live on host and collectives stage
through the XLA mesh — the same architecture as the reference's CudaOnCPU
staging path (reference: torch/mpi_ops_v2.cc:78-110), with the engine's
background thread playing the role of the C++ comm thread.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import torch

from horovod_tpu.common.topology import rank, size
from horovod_tpu.core import get_engine
from horovod_tpu.torch.compression import Compression

# Keep tensor references alive while the engine owns the request, exactly
# like the reference's _handle_map (reference: torch/mpi_ops.py:51-54).
_handle_map = {}
_handle_lock = threading.Lock()
_name_counter = 0


def _auto_name(prefix: str, name: Optional[str]) -> str:
    global _name_counter
    if name is not None:
        return name
    with _handle_lock:
        _name_counter += 1
        return f"{prefix}.noname.{_name_counter}"


def _np_of(tensor: torch.Tensor) -> np.ndarray:
    if tensor.dtype == torch.bfloat16:
        # numpy has no bf16; ride ml_dtypes so the wire stays bf16.
        # torch bf16 and ml_dtypes bf16 share the bit layout, so the
        # handoff is a zero-copy reinterpret through int16 (uint16
        # torch dtypes only exist from torch 2.3; int16 views give
        # identical bits on any torch). VERDICT r3 weak #6: the old
        # path round-tripped through f32 — two full conversion copies
        # per tensor on the host leg. The python engine snapshots at
        # submit (engine.py allreduce_async), so handing over a live
        # view is safe there too.
        import ml_dtypes

        return (tensor.detach().cpu().contiguous()
                .view(torch.int16).numpy().view(ml_dtypes.bfloat16))
    return tensor.detach().cpu().contiguous().numpy()


def _torch_of(result: np.ndarray, like: Optional[torch.Tensor]) -> torch.Tensor:
    import ml_dtypes

    if result.dtype == ml_dtypes.bfloat16:
        # Same bit-reinterpret on the way back; the single .copy() is
        # required because collective results are read-only views of
        # device buffers and torch requires writable memory (same-size
        # dtype views are valid on any layout, so no extra pass).
        t = torch.from_numpy(
            result.view(np.int16).copy()).view(torch.bfloat16)
    else:
        # np.array copies: collective results are read-only views of device
        # buffers, and torch requires writable memory.
        t = torch.from_numpy(np.array(result))
    if like is not None and t.dtype != like.dtype and like.dtype == torch.bfloat16:
        t = t.to(like.dtype)
    return t


def _register(handle: int, inputs, output: Optional[torch.Tensor]):
    with _handle_lock:
        _handle_map[handle] = (inputs, output)


def poll(handle: int) -> bool:
    """True once the collective finished; synchronize() will not block
    (reference: torch/mpi_ops.py:406-421)."""
    return get_engine().poll(handle)


def synchronize(handle: int) -> torch.Tensor:
    """Block until completion and return the output tensor (reference:
    torch/mpi_ops.py:422-438). In-place variants copy into the input."""
    with _handle_lock:
        inputs, output = _handle_map.pop(handle, (None, None))
    result = get_engine().synchronize(handle)  # raises EngineError on failure
    like = inputs if isinstance(inputs, torch.Tensor) else None
    t = _torch_of(result, like)
    if output is not None:
        # Raw storage write, like the reference's C++ adapters (autograd
        # must not see the in-place copy on leaf Parameters).
        with torch.no_grad():
            if output.shape != t.shape:
                output.resize_(t.shape)
            output.copy_(t.to(output.dtype))
        return output
    return t


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None,
                    compression: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    # `compression` here is the per-request ENGINE wire-format name
    # ('int8'/'fp8' — a Compressor's .engine_wire); cast compressors are
    # applied by the caller around the collective as in the reference.
    # `donate=True` hands the tensor's host buffer to the engine — the
    # submit snapshot is skipped and the engine references it in place
    # (read-only) until completion. The numpy side is flagged
    # unwriteable, but torch can still write through its own reference:
    # mutating a donated tensor before synchronize() is undefined
    # behavior, the caller's promise to keep (see docs/running.md).
    # `deadline_ms` bounds the wait: an overdue request fails its waiter
    # with an attributed CollectiveTimeout (overrides the engine-wide
    # HVD_COLLECTIVE_DEADLINE_S default). `priority`
    # ('high'/'normal'/'low') is the serving-plane scheduling class —
    # higher classes drain first and own their admission budget
    # (overrides the engine-wide HVD_PRIORITY default).
    out = torch.empty_like(tensor)
    h = get_engine().allreduce_async(
        _auto_name("allreduce", name), _np_of(tensor), average,
        compression=compression, donate=donate, deadline_ms=deadline_ms,
        priority=priority
    )
    _register(h, tensor, out)
    return h


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None,
                     compression: Optional[str] = None,
                     donate: bool = False,
                     deadline_ms: Optional[float] = None,
                     priority: Optional[str] = None) -> int:
    # In-place + donation (PR 13 follow-up): the engine references the
    # tensor's host buffer in place and only READS it — the reduced
    # result lands in engine-pooled buffers and is copied back into the
    # tensor at synchronize(), AFTER the engine dropped its reference,
    # so the in-place write-back never races the zero-copy read. The
    # contract is the same read-only/frozen-view one as the out-of-place
    # variant: the numpy view is flagged unwriteable, and a caller that
    # writes through the torch reference before completion breaks its
    # own donation (documented UB, docs/running.md).
    h = get_engine().allreduce_async(
        _auto_name("allreduce", name), _np_of(tensor), average,
        compression=compression, donate=donate, deadline_ms=deadline_ms,
        priority=priority
    )
    _register(h, tensor, tensor)
    return h


def allreduce_batch_async_(named_tensors, average: bool = True,
                           compressions=None,
                           priority: Optional[str] = None) -> list:
    """Batched in-place allreduce: ONE engine call (``submit_n`` /
    ``hvd_engine_enqueue_n``) for a whole bucket of gradients — one GIL
    crossing, one snapshot pass over name-bound pool slabs, one engine
    wakeup, instead of per-tensor submit costs. ``named_tensors`` is an
    iterable of ``(name, tensor)``; ``compressions`` optionally aligns
    per-member engine wire names with it. Results are copied back into
    each tensor at its ``synchronize`` (same in-place contract as
    :func:`allreduce_async_`)."""
    from horovod_tpu.core.engine import SubmitRequest

    items = list(named_tensors)
    comps = (list(compressions) if compressions is not None
             else [None] * len(items))
    reqs = [SubmitRequest(_auto_name("allreduce", name), _np_of(t),
                          average=average, compression=c,
                          priority=priority)
            for (name, t), c in zip(items, comps)]
    handles = get_engine().submit_n("allreduce", reqs)
    for h, (_, t) in zip(handles, items):
        _register(h, t, t)
    return handles


def broadcast_batch_async_(named_tensors, root_rank: int) -> list:
    """Batched in-place broadcast — the state-sync twin of
    :func:`allreduce_batch_async_` (``broadcast_parameters`` /
    ``broadcast_optimizer_state`` hand their whole (name, tensor) list
    over in one engine call)."""
    from horovod_tpu.core.engine import SubmitRequest

    items = list(named_tensors)
    reqs = [SubmitRequest(_auto_name("broadcast", name), _np_of(t),
                          root_rank=root_rank)
            for name, t in items]
    handles = get_engine().submit_n("broadcast", reqs)
    for h, (_, t) in zip(handles, items):
        _register(h, t, t)
    return handles


class HorovodAllreduce(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, wire=None):
        ctx.average = average
        return synchronize(allreduce_async(tensor, average, name, wire))

    @staticmethod
    def backward(ctx, grad_output):
        return allreduce(grad_output, ctx.average), None, None, None


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None, compression=Compression.none) -> torch.Tensor:
    from horovod_tpu.jax.compression import for_tensor as _for_tensor

    compression = _for_tensor(Compression.resolve(compression), name)
    compressed, ctx = compression.compress(tensor)
    out = HorovodAllreduce.apply(compressed, average, name,
                                 getattr(compression, "engine_wire", None))
    return compression.decompress(out, ctx)


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None,
               donate: bool = False) -> torch.Tensor:
    # donate=True is safe here even for an impatient caller: this
    # blocking variant cannot touch the tensor between submit and
    # synchronize by construction.
    return synchronize(allreduce_async_(tensor, average, name,
                                        donate=donate))


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    h = get_engine().allgather_async(_auto_name("allgather", name),
                                     _np_of(tensor), donate=donate,
                                     deadline_ms=deadline_ms,
                                     priority=priority)
    _register(h, tensor, None)
    return h


class HorovodAllgather(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim = tensor.shape[0]
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        # Sum the gathered gradient across ranks, then slice this rank's rows
        # (reference: torch/mpi_ops.py:246-254).
        grad_reduced = allreduce(grad_output, average=False)
        dims = allgather(torch.tensor([ctx.dim], dtype=torch.int32)).view(size())
        r = rank()
        offset = int(dims.narrow(0, 0, r).sum().item()) if r != 0 else 0
        return grad_reduced.narrow(0, offset, ctx.dim), None


def allgather(tensor: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    return HorovodAllgather.apply(tensor, name)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    out = torch.empty_like(tensor)
    h = get_engine().broadcast_async(
        _auto_name("broadcast", name), _np_of(tensor), root_rank,
        donate=donate, deadline_ms=deadline_ms, priority=priority
    )
    _register(h, tensor, out)
    return h


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None,
                     donate: bool = False,
                     deadline_ms: Optional[float] = None,
                     priority: Optional[str] = None) -> int:
    # Same in-place donation contract as allreduce_async_: zero-copy
    # read by the engine, result written back at synchronize().
    h = get_engine().broadcast_async(
        _auto_name("broadcast", name), _np_of(tensor), root_rank,
        donate=donate, deadline_ms=deadline_ms, priority=priority
    )
    _register(h, tensor, tensor)
    return h


class HorovodBroadcast(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name))

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = allreduce(grad_output, average=False)
        if rank() != ctx.root_rank:
            grad_reduced = grad_reduced * 0
        return grad_reduced, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None,
               donate: bool = False) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name,
                                        donate=donate))
