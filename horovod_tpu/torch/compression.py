"""Gradient compression for torch tensors (reference:
horovod/torch/compression.py — same surface, plus TPU-native bf16 and
the block-scaled quantized engine-wire policies).

Cast policies (fp16/bf16) wrap the collective as in the reference. The
quantized policies (``int8``/``fp8`` — jax/quantize.py) are identity at
the torch layer and tag the request with ``engine_wire``: the engine's
shared data plane quantizes per execution chunk (summing int8 payloads
through a plain allreduce would saturate). ``Compression.resolve`` fails
fast with rank attribution on unknown spellings."""

from __future__ import annotations

import torch

from horovod_tpu.jax.compression import resolve_in, select_in


class Compressor:
    engine_wire = None

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: torch.dtype = None

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != cls.wire_dtype:
            return tensor.to(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = torch.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = torch.bfloat16


class Int8Compressor(NoneCompressor):
    """Block-scaled int8 on the engine wire (jax/quantize.py): identity
    at the torch layer, quantized per execution chunk in the data
    plane."""

    engine_wire = "int8"


class FP8Compressor(NoneCompressor):
    """Block-scaled fp8 (e4m3) on the engine wire."""

    engine_wire = "fp8"


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor

    _registry = {"none": NoneCompressor, "fp16": FP16Compressor,
                 "bf16": BF16Compressor, "int8": Int8Compressor,
                 "fp8": FP8Compressor}

    @classmethod
    def resolve(cls, spec, where: str = "compression"):
        return resolve_in(cls._registry, spec, where)

    @classmethod
    def select(cls, default="none", **overrides):
        """Name-based per-tensor policy (fnmatch on the parameter name;
        first keyword match wins). Members are explicit: a ``'none'``
        entry pins full width even under an HVD_COMPRESSION default."""
        return select_in(cls.resolve, default, overrides)
