"""PyTorch frontend (reference: horovod/torch/__init__.py).

Torch compute stays on host CPU (no torch-TPU backend exists in this stack);
collectives run through the async engine onto the XLA mesh. The training
integration is identical to the reference's: per-parameter hooks fire
asynchronous allreduces as gradients materialize, and ``step()`` drains them.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import torch

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    cross_size,
    cross_rank,
    num_processes,
    process_index,
    mpi_threads_supported,
)
from horovod_tpu.core.engine import DuplicateNameError, EngineError  # noqa: F401
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    allreduce_batch_async_,
    allgather,
    allgather_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    broadcast_batch_async_,
    poll,
    synchronize,
)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixed into the user's optimizer class dynamically (reference:
    horovod/torch/__init__.py:42-182). Gradient hooks use torch's
    post-accumulate-grad hook — the modern form of the reference's
    grad-accumulator expand_as trick (reference: :80-89)."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, v in enumerate(
                    p for group in self.param_groups for p in group["params"]
                )
            ]
        if len({name for name, _ in named_parameters}) < len(named_parameters):
            raise ValueError("namespace of named_parameters is not unique")
        all_params = {
            id(p) for group in self.param_groups for p in group["params"]
        }
        unnamed = all_params - {id(p) for _, p in named_parameters}
        if unnamed:
            raise ValueError(
                "named_parameters was specified but did not cover all "
                f"optimizer parameters ({len(unnamed)} missing)"
            )
        self._parameter_names = {id(p): name for name, p in named_parameters}
        self.backward_passes_per_step = backward_passes_per_step
        self._allreduce_delay = {}
        self._handles = {}
        self._hook_handles = []
        self._register_hooks()

    def set_backward_passes_per_step(self, passes: int):
        """Reference: torch/__init__.py:75-78."""
        self.backward_passes_per_step = passes
        for p in self._allreduce_delay:
            self._allreduce_delay[p] = passes

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._allreduce_delay[id(p)] = self.backward_passes_per_step
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(self._make_hook())
                    )

    def _make_hook(self):
        def hook(p):
            if id(p) in self._handles:
                raise AssertionError(
                    "Gradient was computed more than backward_passes_per_step "
                    "times before step(); increase backward_passes_per_step "
                    "or call synchronize()"
                )
            self._allreduce_delay[id(p)] -= 1
            if self._allreduce_delay[id(p)] == 0:
                self._handles[id(p)] = (p, self._allreduce_grad_async(p))

        return hook

    def _allreduce_grad_async(self, p):
        from horovod_tpu.jax.compression import for_tensor as _for_tensor

        name = self._parameter_names[id(p)]
        comp = _for_tensor(self._compression, name)
        compressed, ctx = comp.compress(p.grad)
        handle = allreduce_async_(
            compressed, average=True, name=name,
            compression=getattr(comp, "engine_wire", None))
        return handle, comp, compressed, ctx

    def synchronize(self):
        """Drain outstanding gradient reductions (reference:
        torch/__init__.py:117-136)."""
        missed = [p for group in self.param_groups
                  for p in group["params"]
                  if p.requires_grad and id(p) not in self._handles
                  and p.grad is not None]
        if len(missed) == 1:
            # Parameter whose hook did not fire this step (e.g. after
            # manual backward wiring): reduce it now.
            p = missed[0]
            self._handles[id(p)] = (p, self._allreduce_grad_async(p))
        elif missed:
            # Hooks fired for none of these (manual backward wiring
            # reduces the whole bucket here): compress each, then hand
            # the bucket to the engine as ONE batched submit.
            from horovod_tpu.jax.compression import for_tensor as _for_tensor

            metas, named, wires = [], [], []
            for p in missed:
                name = self._parameter_names[id(p)]
                comp = _for_tensor(self._compression, name)
                compressed, cctx = comp.compress(p.grad)
                named.append((name, compressed))
                wires.append(getattr(comp, "engine_wire", None))
                metas.append((p, comp, compressed, cctx))
            handles = allreduce_batch_async_(named, average=True,
                                             compressions=wires)
            for h, (p, comp, compressed, cctx) in zip(handles, metas):
                self._handles[id(p)] = (p, (h, comp, compressed, cctx))
        for pid, (p, (handle, comp, compressed, ctx)) in list(
                self._handles.items()):
            out = synchronize(handle)
            self._allreduce_delay[pid] = self.backward_passes_per_step
            p.grad.copy_(comp.decompress(out, ctx).to(p.grad.dtype))
        self._handles.clear()

    def step(self, closure=None):
        self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterator[Tuple[str, torch.Tensor]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1):
    """Wrap a torch optimizer with distributed gradient averaging
    (reference: horovod/torch/__init__.py:139-182 — same dynamic-subclass
    construction so isinstance(user_optimizer_cls) keeps working).

    ``compression`` accepts a registry name (``'int8'``/``'fp8'`` engine
    wire formats, ``'bf16'``/``'fp16'`` casts) or a compressor; unknown
    spellings fail fast HERE, naming the rank."""
    compression = Compression.resolve(compression)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a state_dict or list of (name, tensor) from root
    (reference: horovod/torch/__init__.py:185-214)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
        for it in items:
            if not (isinstance(it, tuple) and len(it) == 2
                    and isinstance(it[0], str)):
                raise ValueError(
                    "params must be a state_dict or an iterable of "
                    "(name, tensor) pairs (e.g. model.named_parameters()); "
                    f"got item of type {type(it).__name__}"
                )
    batch = []
    for name, p in items:
        if p is None:
            continue
        if torch.is_tensor(p):
            batch.append((name, p))
        else:
            raise ValueError(
                f"cannot broadcast non-tensor value for '{name}' "
                f"(type {type(p).__name__})"
            )
    # The whole state_dict rides ONE batched engine call — the state
    # sync costs one GIL crossing and one wakeup, not one per tensor.
    handles = broadcast_batch_async_(batch, root_rank) if batch else []
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0):
    """Broadcast optimizer state from root (reference:
    horovod/torch/__init__.py:217-333). Scalar hyperparameters are
    tensor-ized for the wire and reconstructed with their original python
    types, as in the reference's callback scheme."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    callbacks = []
    batch = []

    def _broadcast_value(container, key, value, name):
        if torch.is_tensor(value):
            batch.append((name, value))
            return
        if isinstance(value, bool):
            t = torch.tensor(int(value), dtype=torch.int64)
            restore = lambda x: bool(x.item())  # noqa: E731
        elif isinstance(value, int):
            t = torch.tensor(value, dtype=torch.int64)
            restore = lambda x: int(x.item())  # noqa: E731
        elif isinstance(value, float):
            t = torch.tensor(value, dtype=torch.float64)
            restore = lambda x: float(x.item())  # noqa: E731
        else:
            return  # non-numeric options (None, str) assumed identical
        batch.append((name, t))
        callbacks.append(lambda c=container, k=key, x=t, r=restore: c.__setitem__(k, r(x)))

    for index, group in enumerate(state_dict["param_groups"]):
        for option_key, option_value in group.items():
            if option_key == "params":
                continue
            _broadcast_value(group, option_key, option_value,
                             f"optimizer.group.{index}.{option_key}")
    for pid, param_state in state_dict["state"].items():
        for name, value in param_state.items():
            _broadcast_value(param_state, name, value,
                             f"optimizer.state.{pid}.{name}")

    # One batched engine call for the whole optimizer state.
    handles = broadcast_batch_async_(batch, root_rank) if batch else []
    for h in handles:
        synchronize(h)
    for cb in callbacks:
        cb()
    optimizer.load_state_dict(state_dict)
