"""Distributed metric averaging.

The reference provides this twice: a Keras callback allreducing epoch-end
metrics (reference: horovod/_keras/callbacks.py:33-67) and a hand-rolled
``Metric`` class in the examples (reference:
examples/pytorch_imagenet_resnet50.py:255-268). Both shapes are here.

Metric-averaging collectives flow through :mod:`horovod_tpu.core.telemetry`
like every other eager collective, plus a dedicated ``metrics.*`` counter
family so "how much of my eager traffic is metrics" is answerable.
"""

from __future__ import annotations

import jax.numpy as jnp

from horovod_tpu.core import telemetry as _tele
from horovod_tpu.ops import collectives as _C


class Metric:
    """Running average whose value is allreduce-averaged across ranks
    (reference: examples/pytorch_imagenet_resnet50.py:255-268).

    ``avg`` is memoized per ``(sum, n)``: reading the property twice
    without an intervening ``update`` fires ONE eager allreduce, not one
    per read (a logging loop printing ``m.avg`` in two places used to pay
    a full collective for each). Memoization is single-controller only:
    in a multi-controller world whether the collective fires must not
    depend on LOCAL state — with an uneven last batch, rank 0's extra
    ``update`` would change its cache key while rank 1 serves its cache,
    leaving a mismatched collective and a deadlocked world — so there
    every read keeps firing (the pre-memoization contract: equal read
    counts suffice)."""

    def __init__(self, name: str):
        self.name = name
        self.sum = 0.0
        self.n = 0
        self._cache = None  # ((sum, n), value) of the last collective

    def update(self, value):
        self.sum += float(value)
        self.n += 1

    @property
    def avg(self) -> float:
        if self.n == 0:
            return 0.0
        memoizable = _C._topo._require_init().num_processes == 1
        if (memoizable and self._cache is not None
                and self._cache[0] == (self.sum, self.n)):
            return self._cache[1]
        local = self.sum / self.n
        _tele.REGISTRY.counter("metrics.allreduces").inc()
        val = float(_C.allreduce(jnp.asarray(local), average=True))
        self._cache = ((self.sum, self.n), val) if memoizable else None
        return val


def MetricAverage(values: dict) -> dict:
    """Allreduce-average a dict of scalars across ranks in one fused
    collective (reference: _keras/callbacks.py:52-67 does it one allreduce
    per metric)."""
    if not values:
        return {}
    keys = sorted(values)
    _tele.REGISTRY.counter("metrics.averages").inc()
    _tele.REGISTRY.counter("metrics.averaged_values").inc(len(keys))
    stacked = jnp.asarray([float(values[k]) for k in keys], jnp.float32)
    avg = _C.allreduce(stacked, average=True)
    return {k: float(avg[i]) for i, k in enumerate(keys)}
