"""Distributed metric averaging.

The reference provides this twice: a Keras callback allreducing epoch-end
metrics (reference: horovod/_keras/callbacks.py:33-67) and a hand-rolled
``Metric`` class in the examples (reference:
examples/pytorch_imagenet_resnet50.py:255-268). Both shapes are here.
"""

from __future__ import annotations

import jax.numpy as jnp

from horovod_tpu.ops import collectives as _C


class Metric:
    """Running average whose value is allreduce-averaged across ranks
    (reference: examples/pytorch_imagenet_resnet50.py:255-268)."""

    def __init__(self, name: str):
        self.name = name
        self.sum = 0.0
        self.n = 0

    def update(self, value):
        self.sum += float(value)
        self.n += 1

    @property
    def avg(self) -> float:
        if self.n == 0:
            return 0.0
        local = self.sum / self.n
        return float(_C.allreduce(jnp.asarray(local), average=True))


def MetricAverage(values: dict) -> dict:
    """Allreduce-average a dict of scalars across ranks in one fused
    collective (reference: _keras/callbacks.py:52-67 does it one allreduce
    per metric)."""
    if not values:
        return {}
    keys = sorted(values)
    stacked = jnp.asarray([float(values[k]) for k in keys], jnp.float32)
    avg = _C.allreduce(stacked, average=True)
    return {k: float(avg[i]) for i, k in enumerate(keys)}
