"""Distributed metric averaging.

The reference provides this twice: a Keras callback allreducing epoch-end
metrics (reference: horovod/_keras/callbacks.py:33-67) and a hand-rolled
``Metric`` class in the examples (reference:
examples/pytorch_imagenet_resnet50.py:255-268). Both shapes are here.

Metric-averaging collectives flow through :mod:`horovod_tpu.core.telemetry`
like every other eager collective, plus a dedicated ``metrics.*`` counter
family so "how much of my eager traffic is metrics" is answerable.
"""

from __future__ import annotations

import logging
import math

import jax.numpy as jnp

from horovod_tpu.core import telemetry as _tele
from horovod_tpu.ops import collectives as _C

LOG = logging.getLogger("horovod_tpu.metrics")


class Metric:
    """Running average whose value is allreduce-averaged across ranks
    (reference: examples/pytorch_imagenet_resnet50.py:255-268).

    ``avg`` is memoized per ``(sum, n)``: reading the property twice
    without an intervening ``update`` fires ONE eager allreduce, not one
    per read (a logging loop printing ``m.avg`` in two places used to pay
    a full collective for each). Memoization is single-controller only:
    in a multi-controller world whether the collective fires must not
    depend on LOCAL state — with an uneven last batch, rank 0's extra
    ``update`` would change its cache key while rank 1 serves its cache,
    leaving a mismatched collective and a deadlocked world — so there
    every read keeps firing (the pre-memoization contract: equal read
    counts suffice)."""

    def __init__(self, name: str):
        self.name = name
        self.sum = 0.0
        self.n = 0
        self._cache = None  # ((sum, n), value) of the last collective

    def update(self, value):
        self.sum += float(value)
        self.n += 1

    @property
    def avg(self) -> float:
        if self.n == 0:
            return 0.0
        memoizable = _C._topo._require_init().num_processes == 1
        if (memoizable and self._cache is not None
                and self._cache[0] == (self.sum, self.n)):
            return self._cache[1]
        local = self.sum / self.n
        _tele.REGISTRY.counter("metrics.allreduces").inc()
        val = float(_C.allreduce(jnp.asarray(local), average=True))
        self._cache = ((self.sum, self.n), val) if memoizable else None
        return val


def MetricAverage(values: dict) -> dict:
    """Allreduce-average a dict of scalars across ranks in one fused
    collective (reference: _keras/callbacks.py:52-67 does it one allreduce
    per metric).

    Nonfinite contributions are EXCLUDED instead of silently poisoning
    the cross-rank average (one rank's NaN loss used to NaN the metric
    on every rank): each rank ships ``(masked value, finite flag)`` in
    the same single collective and the average divides by the finite
    count — flagged by the ``metrics.nonfinite_skipped`` counter and one
    warning naming the keys. A key nonfinite on EVERY rank has no finite
    contribution and stays NaN (there is no honest number to report).
    The masking is shape-uniform across ranks, so a rank-local NaN can
    never desynchronize the fused collective."""
    if not values:
        return {}
    keys = sorted(values)
    _tele.REGISTRY.counter("metrics.averages").inc()
    _tele.REGISTRY.counter("metrics.averaged_values").inc(len(keys))
    local = [float(values[k]) for k in keys]
    bad = [k for k, v in zip(keys, local) if not math.isfinite(v)]
    if bad:
        _tele.REGISTRY.counter("metrics.nonfinite_skipped").inc(len(bad))
        LOG.warning(
            "MetricAverage: nonfinite local value(s) for %s excluded "
            "from the cross-rank average", bad)
    # Row 0: values with nonfinite entries zeroed; row 1: finite flags.
    # One fused SUM collective carries both, every rank contributes the
    # same shape regardless of where the NaN is.
    masked = [v if math.isfinite(v) else 0.0 for v in local]
    flags = [1.0 if math.isfinite(v) else 0.0 for v in local]
    stacked = jnp.asarray([masked, flags], jnp.float32)
    summed = _C.allreduce(stacked, average=False)
    out = {}
    for i, k in enumerate(keys):
        n = float(summed[1, i])
        out[k] = float(summed[0, i]) / n if n > 0 else float("nan")
    return out
