"""Distributed-consistent checkpointing.

The reference delegates checkpoint IO to the framework and contributes the
*consistency* protocol: rank 0 writes, everyone restores, restored state is
broadcast so ranks agree (reference: examples/pytorch_imagenet_resnet50.py:
70-80,135-143, horovod/torch/__init__.py:217-333, SURVEY.md §5). Same
protocol here over flax msgpack serialization: ``save_checkpoint`` writes on
process 0 only; ``load_checkpoint`` reads everywhere and broadcasts the
result from root so a restored run starts bitwise-identical on every rank.

Mixed-precision layouts (``state_dtype='bf16'``, HBM diet round 2) round-
trip through the same path: the optimizer state carries the f32 master
buffers, so serializing it persists full-precision weights alongside the
bf16 residents; :func:`rebuild_resident_params` re-derives the residents
from the restored masters so ``resident == cast(master)`` holds bitwise
after a restore (a resident saved mid-drift would otherwise diverge from
its master by an ulp and perturb the restored trajectory).
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import flax.serialization

from horovod_tpu.common import topology as _topo
from horovod_tpu.core import faultline as _flt


def _ckpt_path(directory: str, step: int, prefix: str) -> str:
    return os.path.join(directory, f"{prefix}{step}.msgpack")


def _globalize(target: Any) -> Any:
    """Materialize cross-process-sharded leaves as full host values.

    A ``shard_update`` optimizer state lays its buffers out ``P('hvd')``;
    in a multi-controller world process 0 holds only its own 1/N shards
    and cannot fetch the rest directly. ``fetch`` allgathers those leaves
    — a COLLECTIVE, so every process must pass through here (and does:
    ``save_checkpoint`` globalizes before its root-only early return).
    Addressable leaves (replicated arrays, host numpy, scalars) pass
    through untouched; the addressability predicate is a property of the
    global sharding, identical on every process, so the collective order
    stays rank-consistent."""
    import jax

    from horovod_tpu.ops.collectives import fetch

    def one(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return fetch(leaf)
        return leaf

    return jax.tree_util.tree_map(one, target)


def save_checkpoint(directory: str, target: Any, step: int,
                    prefix: str = "checkpoint_") -> Optional[str]:
    """Serialize ``target`` (any flax-serializable pytree) on process 0.
    Returns the path written, or None on non-root processes (which still
    participate in the shard allgather of cross-process-sharded state —
    call on EVERY process, as Trainer.save does)."""
    st = _topo._require_init()
    target = _globalize(target)
    if st.process_index != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step, prefix)
    tmp = path + ".tmp"
    data = flax.serialization.to_bytes(target)
    # Crash-atomic: tmp + fsync + rename. The fsync matters — a rename
    # can land on disk before the data it points at, so a host dying
    # right after save could still resurrect a truncated "newest"
    # checkpoint that elastic resume then loads. The fault site
    # ckpt.write ('torn', core/faultline.py) simulates a rank dying
    # mid-write: half the payload lands in the tmp, the rename never
    # runs, and latest_checkpoint must keep pointing at the previous
    # good file (pinned in tests/test_faultline.py).
    fault = _flt.ckpt_write()
    with open(tmp, "wb") as f:
        if fault is not None and fault.mode == "torn":
            f.write(data[: len(data) // 2])
            f.flush()
            os.fsync(f.fileno())
            raise _flt.FaultInjected(
                fault.describe() + f" path={path}")
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:  # persist the rename itself (directory entry), best-effort
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return path


def latest_checkpoint(directory: str,
                      prefix: str = "checkpoint_") -> Optional[str]:
    """Newest checkpoint path by step number, or None (the resume-from-epoch
    scan of the reference examples, pytorch_imagenet_resnet50.py:70-80)."""
    if not os.path.isdir(directory):
        return None
    pat = re.compile(re.escape(prefix) + r"(\d+)\.msgpack$")
    best = None
    for name in os.listdir(directory):
        m = pat.match(name)
        if m:
            step = int(m.group(1))
            if best is None or step > best[0]:
                best = (step, os.path.join(directory, name))
    return best[1] if best else None


def rebuild_resident_params(state_dict: dict, params_key: str = "params",
                            opt_key: str = "opt_state") -> dict:
    """Re-derive the reduced-precision resident params of a restored
    trainer ``state_dict`` from its f32 master buffers (shard_update's
    ``state_dtype`` layout). No-op when the optimizer state carries no
    masters, so restore paths can call it unconditionally."""
    from horovod_tpu.jax.sharded import (has_master_shards,
                                         resident_from_masters)

    opt_state = state_dict.get(opt_key)
    if not has_master_shards(opt_state):
        return state_dict
    out = dict(state_dict)
    out[params_key] = resident_from_masters(opt_state,
                                            state_dict[params_key])
    return out


def load_checkpoint(path: str, target: Any, broadcast: bool = True,
                    root_rank: int = 0) -> Any:
    """Restore ``target``-shaped state from ``path``; broadcast from
    ``root_rank`` so all ranks agree even if local files diverged."""
    with open(path, "rb") as f:
        restored = flax.serialization.from_bytes(target, f.read())
    if broadcast and _topo._require_init().size > 1:
        from horovod_tpu.ops.collectives import broadcast_pytree

        restored = broadcast_pytree(restored, root_rank=root_rank)
    return restored
