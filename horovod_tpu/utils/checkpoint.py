"""Distributed-consistent checkpointing.

The reference delegates checkpoint IO to the framework and contributes the
*consistency* protocol: rank 0 writes, everyone restores, restored state is
broadcast so ranks agree (reference: examples/pytorch_imagenet_resnet50.py:
70-80,135-143, horovod/torch/__init__.py:217-333, SURVEY.md §5). Same
protocol here over flax msgpack serialization: ``save_checkpoint`` writes on
process 0 only; ``load_checkpoint`` reads everywhere and broadcasts the
result from root so a restored run starts bitwise-identical on every rank.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import flax.serialization

from horovod_tpu.common import topology as _topo


def _ckpt_path(directory: str, step: int, prefix: str) -> str:
    return os.path.join(directory, f"{prefix}{step}.msgpack")


def save_checkpoint(directory: str, target: Any, step: int,
                    prefix: str = "checkpoint_") -> Optional[str]:
    """Serialize ``target`` (any flax-serializable pytree) on process 0.
    Returns the path written, or None on non-root processes."""
    st = _topo._require_init()
    if st.process_index != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step, prefix)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(flax.serialization.to_bytes(target))
    os.replace(tmp, path)  # atomic: no torn checkpoints on preemption
    return path


def latest_checkpoint(directory: str,
                      prefix: str = "checkpoint_") -> Optional[str]:
    """Newest checkpoint path by step number, or None (the resume-from-epoch
    scan of the reference examples, pytorch_imagenet_resnet50.py:70-80)."""
    if not os.path.isdir(directory):
        return None
    pat = re.compile(re.escape(prefix) + r"(\d+)\.msgpack$")
    best = None
    for name in os.listdir(directory):
        m = pat.match(name)
        if m:
            step = int(m.group(1))
            if best is None or step > best[0]:
                best = (step, os.path.join(directory, name))
    return best[1] if best else None


def load_checkpoint(path: str, target: Any, broadcast: bool = True,
                    root_rank: int = 0) -> Any:
    """Restore ``target``-shaped state from ``path``; broadcast from
    ``root_rank`` so all ranks agree even if local files diverged."""
    with open(path, "rb") as f:
        restored = flax.serialization.from_bytes(target, f.read())
    if broadcast and _topo._require_init().size > 1:
        from horovod_tpu.ops.collectives import broadcast_pytree

        restored = broadcast_pytree(restored, root_rank=root_rank)
    return restored
