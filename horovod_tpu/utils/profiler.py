"""XLA profile capture for the compiled hot path.

The reference times device-side work with CUDA events feeding the
timeline (reference: horovod/common/operations.cc:671-695 RECORD_EVENT /
WAIT_FOR_EVENTS); on TPU the compiled step is one fused XLA program, so
device-side spans come from the XLA profiler instead. This module makes
that a one-liner (and ``python bench.py --profile DIR`` a one-command
capture):

    from horovod_tpu.utils import profiler
    with profiler.profile("/tmp/prof"):
        for _ in range(3):
            loss = train_step(...)
        float(np.asarray(loss))   # real barrier INSIDE the trace

View with ``tensorboard --logdir /tmp/prof`` (profile plugin / xprof) or
convert the contained ``*.xplane.pb`` with Perfetto tooling. Collective
time appears inside the fused step program — on the hot path
communication is compiler-scheduled and overlapped with compute, which is
exactly what the trace shows.
"""

from __future__ import annotations

import contextlib
import glob
import os
from typing import Callable, Optional


class CaptureError(RuntimeError):
    """A profiler capture completed but produced no ``*.xplane.pb`` —
    raised loudly instead of letting callers iterate a silently empty
    ``trace_files()`` list (a missing trace read as "zero traffic" is
    worse than a crashed capture)."""


@contextlib.contextmanager
def profile(logdir: str):
    """Context manager capturing an XLA profiler trace into ``logdir``."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(str(logdir)):
        yield


def capture(fn: Callable, *args, logdir: str, iters: int = 3,
            barrier: Optional[Callable] = None) -> str:
    """Run ``fn(*args)`` ``iters`` times under the profiler and return the
    logdir. ``barrier`` (default: numpy-fetch the last output's first
    leaf) forces execution to finish inside the trace window —
    ``block_until_ready`` is not a reliable barrier on the tunneled axon
    platform (see bench.py).

    Raises :class:`CaptureError` when the capture lands no new
    ``*.xplane.pb`` under ``logdir`` (profiler plugin missing, a
    concurrent trace already active, or the runtime wrote nothing):
    every downstream consumer (xplane attribution, perf.jsonl records)
    would otherwise silently report an empty profile."""
    import jax
    import numpy as np

    before = set(trace_files(logdir)) if os.path.isdir(logdir) else set()
    out = None
    with profile(logdir):
        for _ in range(max(1, iters)):
            out = fn(*args)
        if barrier is not None:
            barrier(out)
        elif out is not None:
            leaf = jax.tree_util.tree_leaves(out)
            if leaf:
                # Slice ON DEVICE, then fetch: pulling a whole weight
                # array through the tunnel inside the trace window would
                # pollute the captured profile.
                first = leaf[0]
                if hasattr(first, "ravel"):
                    first = first.ravel()[:1]
                np.asarray(first)
    new = [f for f in trace_files(logdir) if f not in before]
    if not new:
        raise CaptureError(
            f"profiler capture produced no *.xplane.pb under {logdir!r} "
            "(is another trace already active? is the profiler plugin "
            "available on this platform?) — refusing to return an empty "
            "capture")
    return logdir


def trace_files(logdir: str) -> list:
    """The captured xplane protobufs (empty list = capture failed)."""
    return sorted(glob.glob(os.path.join(
        logdir, "**", "*.xplane.pb"), recursive=True))
