"""Measured practical HBM bandwidth — the stream microbenchmark.

docs/benchmarks.md's roofline argument needs the chip's *practical* peak
HBM bandwidth, not the datasheet figure (VERDICT r3 weak #2: the ceiling
claim rested on an underived x0.5 discount of XLA's bytes-accessed
counter). This module measures it directly, STREAM-style (copy and
triad), with three hardenings this rig demands — each one was observed
to corrupt a naive measurement by 2-15x:

1. **Slope fit, not absolute time.** The tunneled platform charges a
   ~70-130 ms fixed host round trip per dispatch; timing one call mixes
   that into the bandwidth. Each kernel scans N iterations for several
   N and the bandwidth comes from the fitted ms/iteration slope —
   the fixed overhead lands in the intercept and cancels.
2. **Arrays must dwarf VMEM.** A v5e core has ~128 MB of VMEM; a 64 MB
   scan carry never leaves it and "measures" >2 TB/s. Buffers here are
   256 MB+ so every iteration is forced through HBM.
3. **The update must survive the dtype.** ``x * 1.0000001`` rounds to
   ``x * 1.0`` in bf16 and XLA elides the whole loop (observed: 10.7
   "TB/s"). The scalars used here are exact in bf16 and change the
   value every iteration.

Run: ``python -m horovod_tpu.utils.membw`` (one JSON line; on the real
chip add ``PYTHONPATH=/root/.axon_site``). Reference analogue: the
reference quotes NCCL bus bandwidth from nccl-tests for the same role —
an independently measured transport ceiling under its model numbers
(reference: docs/benchmarks.md).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Iterable

DEFAULT_ARRAY_MB = 256
DEFAULT_ITERS = (16, 64, 144)


def _slope_ms(times_by_iters: Dict[int, float]) -> float:
    """Least-squares ms/iteration from {iters: seconds}."""
    import numpy as np

    ks = np.array(sorted(times_by_iters), dtype=float)
    ds = np.array([times_by_iters[int(k)] for k in ks])
    a = np.vstack([ks, np.ones_like(ks)]).T
    slope, _ = np.linalg.lstsq(a, ds, rcond=None)[0]
    return float(slope) * 1e3


def measure(kind: str = "triad", array_mb: int = DEFAULT_ARRAY_MB,
            iters: Iterable[int] = DEFAULT_ITERS, dtype=None,
            repeats: int = 3) -> Dict[str, float]:
    """Return {"gbps": ..., "slope_ms_per_iter": ..., "traffic_mb_per_iter"}.

    kind="copy":  c <- c * 1.5      (reads N, writes N  -> 2N bytes/iter)
    kind="triad": c <- c + 0.5 * y  (reads 2N, writes N -> 3N bytes/iter)

    The multiplicative constants are exact in bf16/f32 so the loop can't
    be folded away (hardening #3). The inputs are deliberately NOT
    donated — each timing repeat re-calls with the same arrays — so the
    device footprint is ~2x ``array_mb`` for copy (input + carry) and
    ~3x for triad; keep ``array_mb`` well under a quarter of HBM.
    """
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    nbytes = array_mb * 2 ** 20
    n = nbytes // jnp.dtype(dtype).itemsize
    x = jnp.ones((n,), dtype)
    y = jnp.full((n,), 0.5, dtype)
    per_iter = {"copy": 2, "triad": 3}[kind] * nbytes

    times: Dict[int, float] = {}
    for length in iters:
        if kind == "copy":

            @jax.jit
            def fn(x, _length=length):
                def body(c, _):
                    return c * dtype(1.5), ()

                c, _ = jax.lax.scan(body, x, None, length=_length)
                return c[0]

            args = (x,)
        else:

            @jax.jit
            def fn(x, y, _length=length):
                def body(c, _):
                    return c + dtype(0.5) * y, ()

                c, _ = jax.lax.scan(body, x, None, length=_length)
                return c[0]

            args = (x, y)

        # float(...) is a real device->host fetch — the only execution
        # barrier the tunneled platform respects (bench.py contract).
        float(fn(*args))  # compile + warm
        best = min(_timed(fn, args) for _ in range(repeats))
        times[length] = best

    slope = _slope_ms(times)
    return {
        "kind": kind,
        "dtype": jnp.dtype(dtype).name,
        "array_mb": array_mb,
        "slope_ms_per_iter": round(slope, 4),
        "traffic_mb_per_iter": per_iter / 2 ** 20,
        "gbps": round(per_iter / (slope * 1e-3) / 1e9, 1),
    }


def _timed(fn, args) -> float:
    t0 = time.perf_counter()
    float(fn(*args))
    return time.perf_counter() - t0


def practical_peak(array_mb: int = DEFAULT_ARRAY_MB) -> Dict[str, object]:
    """Copy + triad sweep; the headline practical peak is the max —
    a kernel cannot sustainably beat its own access pattern's best."""
    results = [measure("copy", array_mb), measure("triad", array_mb)]
    import jax

    from horovod_tpu.utils import hardware as hw

    dev = jax.devices()[0]
    spec = hw.peak_hbm_bw(dev)
    peak = max(r["gbps"] for r in results)
    return {
        "metric": "hbm_practical_peak_gbps",
        "value": peak,
        "unit": "GB/s",
        "spec_gbps": spec / 1e9 if spec else None,
        "fraction_of_spec": round(peak / (spec / 1e9), 3) if spec else None,
        "device": getattr(dev, "device_kind", str(dev)),
        "kernels": results,
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="Measure practical HBM bandwidth (STREAM-style)")
    ap.add_argument("--array-mb", type=int, default=DEFAULT_ARRAY_MB,
                    help="buffer size; must dwarf VMEM (~128 MB) or the "
                         "carry never touches HBM")
    args = ap.parse_args(argv)
    print(json.dumps(practical_peak(args.array_mb)))


if __name__ == "__main__":
    main()
