"""Per-chip hardware peaks, keyed by jax ``device_kind`` substring.

Used by the benchmarks to report MFU (model FLOPs utilization) and HBM
bandwidth pressure next to raw throughput, so a physically impossible
number is self-evident (the honesty contract of bench.py). Public
figures: TPU v4 275 TFLOPS bf16 / 1.23 TB/s; v5e 197 / 0.82; v5p 459 /
2.77; v6e (Trillium) 918 / 1.64.
"""

from __future__ import annotations

# Peak dense bf16 TFLOPS per chip.
PEAK_BF16_FLOPS = {
    "v5 lite": 197e12,   # TPU v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # Trillium
    "v6e": 918e12,
}

# Peak HBM bandwidth per chip (bytes/s).
PEAK_HBM_BW = {
    "v5 lite": 819e9,    # TPU v5e
    "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,   # Trillium
    "v6e": 1640e9,
}


def _by_device_kind(device, table) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    return 0.0  # unknown platform (e.g. CPU) -> callers report null


def peak_flops(device) -> float:
    return _by_device_kind(device, PEAK_BF16_FLOPS)


def peak_hbm_bw(device) -> float:
    return _by_device_kind(device, PEAK_HBM_BW)


def scan_cost_analysis_steps(steps_per_call: int, unroll: int) -> int:
    """How many *steps* XLA's cost analysis counts for a
    ``lax.scan(body, length=steps_per_call, unroll=unroll)`` program.

    The while body is counted ONCE (verified on chip, see bench.py) and
    holds ``unroll`` steps; jax peels a remainder of
    ``steps_per_call % unroll`` steps outside the loop (also counted
    once). When ``unroll >= steps_per_call`` there is no while loop at
    all — the program is just ``steps_per_call`` peeled steps
    (jax _scan_impl: num_trips, remainder = divmod(length, unroll)).
    """
    spc = max(1, steps_per_call)
    if spc == 1:
        return 1  # no scan emitted by the callers in that case
    unroll = max(1, unroll)
    num_trips, remainder = divmod(spc, unroll)
    if num_trips == 0:
        return remainder
    return unroll + remainder
