"""Per-chip hardware peaks, keyed by jax ``device_kind`` substring.

Used by the benchmarks to report MFU (model FLOPs utilization) and HBM
bandwidth pressure next to raw throughput, so a physically impossible
number is self-evident (the honesty contract of bench.py). Public
figures: TPU v4 275 TFLOPS bf16 / 1.23 TB/s; v5e 197 / 0.82; v5p 459 /
2.77; v6e (Trillium) 918 / 1.64.
"""

from __future__ import annotations

# Peak dense bf16 TFLOPS per chip.
PEAK_BF16_FLOPS = {
    "v5 lite": 197e12,   # TPU v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # Trillium
    "v6e": 918e12,
}

# Peak HBM bandwidth per chip (bytes/s).
PEAK_HBM_BW = {
    "v5 lite": 819e9,    # TPU v5e
    "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,   # Trillium
    "v6e": 1640e9,
}


def _by_device_kind(device, table) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    return 0.0  # unknown platform (e.g. CPU) -> callers report null


def peak_flops(device) -> float:
    return _by_device_kind(device, PEAK_BF16_FLOPS)


def peak_hbm_bw(device) -> float:
    return _by_device_kind(device, PEAK_HBM_BW)
