"""Perf regression gate + trend CLI over the bench / perf.jsonl history.

ROADMAP item 2 asks for a regression gate so HBM traffic (and the img/s
headline) "can't silently creep back" now that the bench is
bandwidth-bound. This module is that gate, **stdlib-only** by contract:
``bench.py --check`` must be able to run it without jax or the
framework (the ``--dry`` CI guard proves argparse paths never pay those
imports), and CI boxes without a TPU must be able to gate a recorded
line.

Inputs it understands (:func:`load_record` / :func:`load_records`):

- a driver history file (``BENCH_r05.json``: ``{"n", "tail",
  "parsed": {...}}`` — the iteration spread is parsed out of the tail's
  ``spread LO-HI img/sec`` line and becomes the noise bound);
- a raw bench JSON line (what ``python bench.py`` prints — one object
  with ``metric``/``value``/``hbm_gb_per_step``/...);
- a ``perf.jsonl`` health log (one record per auto-capture, written by
  :mod:`horovod_tpu.core.sentinel`) — every line loads, the last one
  gates;
- ``BASELINE.json`` (metadata only today — carried for the trend
  header, never a numeric reference while ``published`` is empty).

Gate arithmetic (:func:`gate`): the current record is compared against
the newest same-metric history record. The allowed img/s drop is
noise-aware — ``max(spread_cur, spread_ref, MIN_NOISE) × NOISE_MULT``
(r05's recorded spread is ~1.1%, the 2% floor + 1.5× multiplier admits
run-to-run wobble and rejects a real regression: −10% fails, a rerun of
r05 passes). HBM creep fails when ``hbm_gb_per_step`` exceeds the
reference by more than ``HBM_TOL`` (5% — the measured figure was stable
to the hundredth of a GB across r04/r05). Null fields skip their check
(a CPU run with no measured HBM must not fail the throughput gate).

CLI::

    python -m horovod_tpu.utils.perfwatch                  # trend table
    python -m horovod_tpu.utils.perfwatch RECORD --check   # gate RECORD
    python -m horovod_tpu.utils.perfwatch --history DIR --json

Exit codes: 0 pass/trend, 1 usage/IO error, 2 gate FAILED.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

#: Noise floor for the img/s drop bound when no spread is recorded
#: (2% — wider than any recorded same-config spread so far).
MIN_NOISE = 0.02
#: Safety multiplier on the noise bound (spread is a range observed in
#: ONE run; two runs can land on opposite edges).
NOISE_MULT = 1.5
#: Allowed hbm_gb_per_step creep over the reference.
HBM_TOL = 0.05

_SPREAD_RE = re.compile(
    r"spread\s+(\d+(?:\.\d+)?)-(\d+(?:\.\d+)?)\s+img/sec")

#: The normalized record fields every loader emits (missing -> None).
#: final_loss rides perf.jsonl auto-capture records (the sentinel stamps
#: the Trainer's last epoch loss) — a convergence column next to the
#: throughput ones; histories without it simply show "-" in the trend
#: table and are never gated on it.
FIELDS = ("metric", "value", "step_time_ms", "gflops_per_step", "mfu",
          "hbm_gb_per_step", "membw_util", "final_loss")


def _normalize(parsed: dict, label: str,
               spread_frac: Optional[float] = None) -> dict:
    rec = {k: parsed.get(k) for k in FIELDS}
    rec["label"] = label
    if spread_frac is None and parsed.get("spread_pct") is not None:
        spread_frac = float(parsed["spread_pct"]) / 100.0
    rec["spread_frac"] = spread_frac
    return rec


def spread_frac_from_tail(tail: str, value) -> Optional[float]:
    """(hi - lo) / value from a driver tail's ``spread LO-HI img/sec``
    line — the recorded iteration spread the noise bound derives from."""
    if not tail or not value:
        return None
    m = _SPREAD_RE.search(tail)
    if not m:
        return None
    lo, hi = float(m.group(1)), float(m.group(2))
    if hi < lo or value <= 0:
        return None
    return (hi - lo) / float(value)


def record_from_bench(result: dict, label: str = "current") -> dict:
    """Normalize a bench.py result dict (the one JSON line) in-process —
    what ``bench.py --check`` hands the gate. The noise bound derives
    from the line's own ``spread_pct`` field (one definition of the
    iteration spread for the JSON line and the gate alike)."""
    return _normalize(result, label)


def load_records(path: str) -> List[dict]:
    """Every record a file holds, normalized and in file order. Raises
    ``ValueError`` on unrecognized content, ``OSError`` on IO."""
    with open(path) as fh:
        text = fh.read()
    base = os.path.basename(path)
    label = re.sub(r"\.jsonl?$", "", base)
    # perf.jsonl: one JSON object per line.
    if path.endswith(".jsonl"):
        out = []
        for i, line in enumerate(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            out.append(_normalize(rec, f"{label}#{i}"))
        return out
    data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "reference_repo" in data or "configs" in data:  # BASELINE.json
        return []  # metadata only — nothing numeric to gate against
    if "parsed" in data:  # driver history wrapper (BENCH_r*.json)
        n = data.get("n")
        lab = f"r{int(n):02d}" if isinstance(n, int) else label
        parsed = data.get("parsed") or {}
        return [_normalize(
            parsed, lab,
            spread_frac=spread_frac_from_tail(data.get("tail", ""),
                                              parsed.get("value")))]
    if "metric" in data:  # a raw bench JSON line saved to a file
        return [_normalize(data, label)]
    raise ValueError(f"{path}: not a bench record, driver history file, "
                     "or perf.jsonl")


def load_record(path: str) -> Optional[dict]:
    """The gate-able record of a file: its last record (perf.jsonl
    appends newest-last), or None for metadata-only files."""
    recs = load_records(path)
    return recs[-1] if recs else None


def load_history(directory: str) -> List[dict]:
    """All ``BENCH_r*.json`` records in ``directory``, oldest first."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json"))):
        try:
            out.extend(load_records(path))
        except (OSError, ValueError, json.JSONDecodeError):
            # An unrecognized or unreadable sibling (a directory that
            # happens to match the glob, a permissions mishap) must not
            # kill the gate — the remaining history still gates.
            continue
    return out


def pick_reference(history: List[dict], current: dict) -> Optional[dict]:
    """The newest comparable history record — regressions are judged
    against where the repo last WAS, not its all-time best (an
    optimization that later regressed should fail against the record
    that landed it, which this picks). Comparable means the metric
    names AGREE — including the unnamed case: a perf.jsonl capture
    (no ``metric`` field) must never gate against the named bench
    history (an arbitrary training loop vs the ResNet line), only
    against other unnamed capture records."""
    cm = current.get("metric")
    for rec in reversed(history):
        rm = rec.get("metric")
        if (cm or rm) and cm != rm:
            continue
        if rec.get("value") is None and rec.get("hbm_gb_per_step") is None:
            continue  # nothing to compare against
        return rec
    return None


def gate(current: dict, reference: Optional[dict], *,
         min_noise: float = MIN_NOISE, noise_mult: float = NOISE_MULT,
         hbm_tol: float = HBM_TOL) -> dict:
    """Noise-aware comparison of ``current`` against ``reference``.

    Returns ``{"status": "pass"|"fail"|"skip", "reference", "checks"}``
    where each check is ``{"field", "current", "reference", "bound",
    "ok"}``. ``skip``: no reference, or nothing comparable."""
    if reference is None:
        return {"status": "skip", "reference": None, "checks": [],
                "note": "no comparable history record"}
    checks = []
    # Throughput floor (higher is better).
    cv, rv = current.get("value"), reference.get("value")
    if cv is not None and rv is not None:
        noise = max(current.get("spread_frac") or 0.0,
                    reference.get("spread_frac") or 0.0,
                    min_noise) * noise_mult
        bound = rv * (1.0 - noise)
        checks.append({"field": "value", "current": cv, "reference": rv,
                       "bound": round(bound, 2), "ok": cv >= bound})
    # HBM-traffic ceiling (lower is better) — the creep gate.
    ch, rh = current.get("hbm_gb_per_step"), reference.get(
        "hbm_gb_per_step")
    if ch is not None and rh is not None:
        bound = rh * (1.0 + hbm_tol)
        checks.append({"field": "hbm_gb_per_step", "current": ch,
                       "reference": rh, "bound": round(bound, 3),
                       "ok": ch <= bound})
    if not checks:
        return {"status": "skip", "reference": reference.get("label"),
                "checks": [], "note": "no comparable fields"}
    status = "pass" if all(c["ok"] for c in checks) else "fail"
    return {"status": status, "reference": reference.get("label"),
            "checks": checks}


def gate_line(result: dict) -> str:
    """One human line for a gate result (stderr companion of the JSON)."""
    if result["status"] == "skip":
        return f"perfwatch: gate skipped ({result.get('note', '')})"
    parts = []
    for c in result["checks"]:
        op = ">=" if c["field"] == "value" else "<="
        parts.append(f"{c['field']} {c['current']} {op} {c['bound']} "
                     f"[{'ok' if c['ok'] else 'FAIL'}]")
    return (f"perfwatch: {result['status'].upper()} vs "
            f"{result['reference']}: " + ", ".join(parts))


# ---------------------------------------------------------------------------
# Trend table
# ---------------------------------------------------------------------------

_COLS = (("value", "img/s", "{:.0f}"), ("step_time_ms", "step ms",
                                        "{:.2f}"),
         ("mfu", "mfu", "{:.3f}"), ("hbm_gb_per_step", "hbm GB",
                                    "{:.2f}"),
         ("membw_util", "membw", "{:.3f}"),
         # Convergence next to throughput (numerics observatory): only
         # perf.jsonl records carry it — older histories refuse the
         # column with "-" rather than crashing or faking a number.
         ("final_loss", "loss", "{:.4g}"))


def trend_table(records: List[dict]) -> str:
    """Human trend over a record list: one row per record, Δ% on the
    headline AND on hbm_gb_per_step vs the previous non-null row (the
    byte-diet axis: an img/s win bought by byte creep — or a byte cut
    like state_dtype='bf16' — is visible in the same table)."""
    if not records:
        return "perfwatch: no records"
    rows = [["record"] + [h for _, h, _ in _COLS] + ["Δ%", "hbmΔ%"]]
    prev = None
    prev_hbm = None
    for rec in records:
        row = [rec.get("label") or "?"]
        for key, _, fmt in _COLS:
            v = rec.get(key)
            row.append(fmt.format(v) if isinstance(v, (int, float))
                       else "-")
        delta = "-"
        v = rec.get("value")
        if isinstance(v, (int, float)):
            if prev:
                delta = f"{(v / prev - 1) * 100:+.1f}"
            prev = v
        row.append(delta)
        hdelta = "-"
        h = rec.get("hbm_gb_per_step")
        if isinstance(h, (int, float)):
            if prev_hbm:
                hdelta = f"{(h / prev_hbm - 1) * 100:+.1f}"
            prev_hbm = h
        row.append(hdelta)
        rows.append(row)
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return "\n".join(
        "  ".join(c.rjust(w) if i else c.ljust(w)
                  for i, (c, w) in enumerate(zip(r, widths)))
        for r in rows)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.perfwatch",
        description="Perf trend + regression gate over bench history "
                    "(BENCH_r*.json) and perf.jsonl health logs.")
    ap.add_argument("record", nargs="?", default=None,
                    help="record to inspect/gate: a bench JSON line "
                         "file, a BENCH_r*.json, or a perf.jsonl (last "
                         "record gates)")
    ap.add_argument("--history", metavar="DIR", default=".",
                    help="directory holding BENCH_r*.json (default: .)")
    ap.add_argument("--check", action="store_true",
                    help="gate RECORD against the history; exit 2 on "
                         "regression (img/s drop beyond the noise "
                         "bound, or hbm_gb_per_step creep)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    history = load_history(args.history)
    if args.record is None:
        if args.check:
            ap.error("--check needs a RECORD to gate")
        records = history
        if args.json:
            print(json.dumps(records))
        else:
            print(trend_table(records))
        return 0

    try:
        records = load_records(args.record)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"perfwatch: cannot load {args.record}: {exc}",
              file=sys.stderr)
        return 1
    if not records:
        print(f"perfwatch: {args.record} holds no gate-able record",
              file=sys.stderr)
        return 1
    current = records[-1]
    # A multi-record file (perf.jsonl) carries its own history: the
    # newest capture gates/trends against the EARLIER captures of the
    # same log (pick_reference additionally refuses to cross between
    # named bench metrics and unnamed capture records).
    if len(records) > 1:
        history = history + records[:-1]

    if not args.check:
        rows = history + [current]
        if args.json:
            print(json.dumps(rows))
        else:
            print(trend_table(rows))
        return 0

    result = gate(current, pick_reference(history, current))
    if args.json:
        print(json.dumps(result))
    else:
        print(gate_line(result))
    return 2 if result["status"] == "fail" else 0


if __name__ == "__main__":
    raise SystemExit(main())
