"""Shared utilities: checkpointing, metrics."""

from horovod_tpu.utils.checkpoint import (  # noqa: F401
    save_checkpoint,
    load_checkpoint,
    latest_checkpoint,
)
from horovod_tpu.utils.metrics import Metric, MetricAverage  # noqa: F401
