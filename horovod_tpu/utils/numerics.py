"""Numerics CLI — the focused query tool over the numerics observatory.

    python -m horovod_tpu.utils.numerics <target> [--json]

``target`` is one of:

- a Prometheus-style exposition file written by ``HVD_TELEMETRY_FILE``
  — the ``hvd_numerics_*`` / ``hvd_sentinel_verdict_*`` family is
  filtered out and rendered (the general-purpose view of everything
  else is ``python -m horovod_tpu.utils.stats``);
- an ``http://host:port`` endpoint (``HVD_TELEMETRY_PORT``) — the same
  metric filter over ``/metrics``, PLUS the ``/healthz`` numerics
  section (policy, live verdicts, drift, consistency) which only the
  live process can serve;
- ``live`` — :func:`horovod_tpu.core.numerics.report` of the current
  process (code/REPL use).

``--json`` keeps the ``utils.stats`` envelope shape for file targets
(``{"source", "target", "samples"}``) and emits the structured health/
report document for http/live targets — the machine-readable form of
what the table shows.

Exit codes: 0 healthy/no-data, 1 usage/IO error, 3 when a ``nonfinite``
or ``diverged`` verdict is visible in the target (scriptable: a CI
convergence job can fail on numerics trouble without parsing tables).
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Tuple

from horovod_tpu.utils.stats import (
    _envelope,
    _is_http,
    fetch_http,
    parse_prometheus,
    render,
)

#: Exposition-name prefixes that belong to the numerics observatory.
_PREFIXES = ("hvd_numerics_", "hvd_sentinel_verdict_nonfinite",
             "hvd_sentinel_verdict_diverged",
             "hvd_metrics_nonfinite_skipped")


def numerics_samples(samples: List[Tuple[str, Dict[str, str], float]]
                     ) -> List[Tuple[str, Dict[str, str], float]]:
    return [s for s in samples if s[0].startswith(_PREFIXES)]


def _verdict_visible(samples, health: dict = None) -> bool:
    """True when the target shows a nonfinite/diverged event (the exit-3
    signal)."""
    for name, _, value in samples:
        if value and name.startswith(("hvd_numerics_nonfinite_events",
                                      "hvd_numerics_diverged_events",
                                      "hvd_sentinel_verdict_nonfinite",
                                      "hvd_sentinel_verdict_diverged")):
            return True
    if health:
        num = health.get("numerics") or {}
        if num.get("verdicts"):
            return True
        v = (health.get("verdict") or {}).get("verdict")
        if v in ("nonfinite", "diverged"):
            return True
    return False


def _render_health(health: dict) -> str:
    num = health.get("numerics") or {}
    lines = [f"policy      {num.get('policy', '?')}",
             f"status      {health.get('status', '?')} "
             f"(rank {health.get('rank')})"]
    if num.get("verdicts"):
        lines.append(f"verdicts    {', '.join(num['verdicts'])}")
    drift = num.get("drift")
    if drift:
        ulp = " ".join(f"{k}={v}" for k, v in
                       sorted((drift.get("ulp") or {}).items()))
        lines.append(f"drift_ulp   {ulp} (step {drift.get('step')})")
    if num.get("consistency_ok") is not None:
        lines.append(f"consistency {'ok' if num['consistency_ok'] else 'DIVERGED'}")
    v = health.get("verdict")
    if v and v.get("verdict") in ("nonfinite", "diverged"):
        who = v.get("ranks") or v.get("processes")
        lines.append(
            f"last        {v['verdict']} at step {v.get('step')}"
            + (f", bucket(s) {sorted(v['buckets'])}"
               if v.get("buckets") else "")
            + (f", rank(s) {who}" if who else "")
            + (f", dump {v.get('dump')}" if v.get("dump") else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.numerics",
        description="Numerics observatory view: gradient health, "
                    "nonfinite/divergence verdicts, bf16 drift gauges "
                    "and the consistency digest — from an exposition "
                    "file, an http://host:port endpoint, or 'live'.")
    ap.add_argument("target",
                    help="exposition file | http://host:port | 'live'")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.target == "live":
        from horovod_tpu.core import numerics as _num

        rep = _num.report()
        if args.json:
            print(json.dumps(rep, default=str))
        else:
            print(f"policy      {rep['policy']} "
                  f"(every {rep['check_every']} steps)")
            if rep["verdicts"]:
                print(f"verdicts    {', '.join(sorted(rep['verdicts']))}")
            if rep["drift"]:
                print(f"drift_ulp   {rep['drift'].get('ulp')}")
            if rep["consistency"] is not None:
                print(f"consistency "
                      f"{'ok' if rep['consistency']['ok'] else 'DIVERGED'}")
            for k, v in sorted(rep["metrics"].items()):
                print(f"{k:44s} {v}")
        return 3 if rep["verdicts"] else 0

    health = None
    if _is_http(args.target):
        try:
            text = fetch_http(args.target)
            hz = fetch_http(args.target.rstrip("/") + "/healthz")
            health = json.loads(hz) if hz.lstrip().startswith("{") \
                else None
        except Exception as exc:
            print(f"cannot fetch {args.target}: {exc}", file=sys.stderr)
            return 1
        source = "http"
    else:
        try:
            with open(args.target) as fh:
                text = fh.read()
        except OSError as exc:
            print(f"cannot read {args.target}: {exc}", file=sys.stderr)
            return 1
        source = "file"

    samples = numerics_samples(parse_prometheus(text))
    if args.json:
        env = _envelope(source, args.target, samples)
        if health is not None:
            env["healthz"] = health
        print(json.dumps(env))
    else:
        if health is not None:
            print(_render_health(health))
            print()
        print(render(samples) if samples
              else "no numerics samples (is HVD_NUMERICS off, or has "
                   "no step run yet?)")
    return 3 if _verdict_visible(samples, health) else 0


if __name__ == "__main__":
    raise SystemExit(main())
