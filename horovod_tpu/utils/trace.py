"""Trace-analysis CLI over the per-rank distributed timelines.

    python -m horovod_tpu.utils.trace merge|skew|critical-path|stats <dir>

``HVD_TIMELINE=<dir>`` makes every controller process write
``timeline.rank{N}.json`` (core/timeline.py); each file embeds an
``HVD_CLOCK`` metadata event mapping its timeline clock onto a common
time base (rank 0's wall↔monotonic bridge, exchanged Cristian-style
through the negotiation KV store — the recorded ``rtt_us`` bounds the
estimate's error; same-host processes share CLOCK_MONOTONIC, making the
alignment exact).

Subcommands:

- ``merge``   — one Perfetto/chrome-tracing file: pid = rank, tid =
  tensor lane, all ranks on the common time base. The reference's
  timeline showed ONE process; this is the cross-rank view the TPU-pod
  scaling failure mode (cross-rank skew, arxiv 1909.09756) needs.
- ``skew``    — per-tensor negotiate skew reconstructed from the
  RANK_READY instants: who announced late, and how much wait each
  process imposed on the world (cross-checkable against the telemetry
  straggler report — ``--prom`` compares against an
  ``HVD_TELEMETRY_FILE`` exposition).
- ``critical-path`` — per-phase time shares through
  QUEUE→NEGOTIATE→MEMCPY→ALLREDUCE→MEMCPY_OUT and the slowest tensor
  instances' phase chains.
- ``stats``   — per-rank event counts, activity durations, clock info.

Every reader is **truncation-tolerant**: a rank killed mid-write leaves
a file with no closing bracket — possibly cut mid-event — and it still
loads (the writers are separator-first, one event per line). Flight-
recorder dumps (``hvd_flight.rank*.json``) are accepted wherever a
trace file is: their ``events`` list uses the same shape with a
``tensor`` field instead of a lane pid.

This module's own code is stdlib-only with no intra-package imports;
note that running it as ``python -m horovod_tpu.utils.trace`` still
imports the ``horovod_tpu`` package (and therefore jax) — on a machine
without jax, copy this one file out and run it directly.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

CLOCK_EVENT = "HVD_CLOCK"
RANK_READY = "RANK_READY"
_COLLECTIVES = ("ALLREDUCE", "ALLGATHER", "BROADCAST")
# Phase display order for critical-path output. MEMCPY is the submit-time
# snapshot copy (nested at the head of QUEUE); its END args carry the
# zero-copy attribution ({"pooled": bool} / {"donated": true}).
_PHASE_ORDER = ("NEGOTIATE", "MEMCPY", "MEMCPY_IN_FUSION_BUFFER",
                "WAIT_FOR_DATA", "COLLECTIVE", "MEMCPY_OUT_FUSION_BUFFER",
                "OTHER")
_MEMCPY_PHASES = ("MEMCPY", "MEMCPY_IN_FUSION_BUFFER",
                  "MEMCPY_OUT_FUSION_BUFFER")

_RANK_FILE_RE = re.compile(r"(?:timeline|hvd_flight)\.rank(\d+)[.\w]*\.json$")


# ---------------------------------------------------------------------------
# Truncation-tolerant loading
# ---------------------------------------------------------------------------


def load_events(path: str) -> List[dict]:
    """Load a chrome-trace JSON array (or a flight-recorder dump),
    tolerating any truncation a killed writer can produce: missing ']',
    trailing comma, or a final line cut mid-event."""
    with open(path) as fh:
        raw = fh.read()
    for candidate in (raw, raw.rstrip().rstrip(",") + "\n]"):
        try:
            data = json.loads(candidate)
            break
        except ValueError:
            continue
    else:
        # Cut mid-event: drop trailing lines until the prefix parses
        # (the writers emit one event per line, separator-first).
        lines = raw.splitlines()
        data = []
        while lines:
            lines.pop()
            body = "\n".join(lines).rstrip().rstrip(",")
            if body.strip() in ("", "["):
                break
            try:
                data = json.loads(body + "\n]")
                break
            except ValueError:
                continue
    if isinstance(data, dict):  # flight-recorder dump
        data = data.get("events", [])
    return [ev for ev in data if isinstance(ev, dict)]


def rank_files(target: str) -> List[str]:
    """Per-rank trace files under a directory (sorted by rank), or the
    single file itself. A directory holding only flight-recorder dumps
    (the SIGUSR1 / stall post-mortem recipe) is analyzable too: the
    newest ``hvd_flight.rank{N}.*.json`` per rank stands in for the
    rank's trace."""
    if not os.path.isdir(target):
        return [target]
    files = glob.glob(os.path.join(target, "timeline.rank*.json"))
    if files:
        return sorted(files, key=lambda f: _file_rank(f) or 0)
    newest: Dict[int, str] = {}
    for f in glob.glob(os.path.join(target, "hvd_flight.rank*.json")):
        r = _file_rank(f)
        if r is None:
            continue
        if r not in newest or os.path.getmtime(f) > \
                os.path.getmtime(newest[r]):
            newest[r] = f
    return [newest[r] for r in sorted(newest)]


def _file_rank(path: str) -> Optional[int]:
    m = _RANK_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


class RankTrace:
    """One rank's loaded trace: events, tensor-lane names, and the clock
    mapping onto the common time base."""

    def __init__(self, path: str):
        self.path = path
        self.events = load_events(path)
        self.lanes: Dict[int, str] = {}
        self.clock: dict = {}
        clock_ranks = set()
        for ev in self.events:
            if ev.get("ph") != "M":
                continue
            if ev.get("name") == "process_name" and "pid" in ev:
                self.lanes[ev["pid"]] = ev.get("args", {}).get("name", "")
            elif ev.get("name") == CLOCK_EVENT:
                self.clock = dict(ev.get("args", {}))  # LAST one wins
                clock_ranks.add(self.clock.get("rank"))
        if len(clock_ranks) > 1:
            # Clock records from SEVERAL ranks in one file ⇒ this is
            # merge's own output (already rebased). Re-analyzing it
            # would silently double-shift every timestamp and collapse
            # the ranks — refuse with directions instead.
            raise ValueError(
                f"{path} is a MERGED trace (clock records from ranks "
                f"{sorted(clock_ranks)}); point the CLI at the per-rank "
                "directory instead")
        rank = self.clock.get("rank")
        if rank is None:
            rank = _file_rank(path)
        self.rank = 0 if rank is None else int(rank)

    def tensor_of(self, ev: dict) -> Optional[str]:
        if "tensor" in ev:  # flight-recorder shape
            return ev["tensor"]
        return self.lanes.get(ev.get("pid"))

    def common_ts(self, ts: int) -> int:
        """Map a trace-local timestamp onto the common base:
        epoch_wall_us + ts − offset_us (see core/timeline.py HVD_CLOCK).
        Traces without clock metadata stay in their own frame."""
        return (int(self.clock.get("epoch_wall_us", 0)) + int(ts)
                - int(self.clock.get("offset_us", 0)))


def load_traces(target: str) -> List[RankTrace]:
    traces = [RankTrace(f) for f in rank_files(target)]
    if not traces:
        raise FileNotFoundError(
            f"no timeline.rank*.json under {target!r} (and it is not a "
            "trace file)")
    return traces


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------


def merge(target: str, out: Optional[str] = None) -> dict:
    """Merge per-rank traces into one Perfetto-loadable file: pid = rank
    (process_name "rank N"), tid = tensor lane (thread_name = tensor),
    all timestamps rebased onto the common time base. Returns
    {"path", "files", "events", "ranks"}."""
    traces = load_traces(target)
    if out is None:
        out = (os.path.join(target, "timeline.merged.json")
               if os.path.isdir(target)
               else os.path.splitext(target)[0] + ".merged.json")
    bases = [t.common_ts(ev.get("ts", 0))
             for t in traces for ev in t.events if ev.get("ph") != "M"]
    base = min(bases) if bases else 0
    merged: List[dict] = []
    nevents = 0
    for t in traces:
        merged.append({"name": "process_name", "ph": "M", "pid": t.rank,
                       "args": {"name": f"rank {t.rank}"}})
        if t.clock:
            merged.append({"name": CLOCK_EVENT, "ph": "M", "pid": t.rank,
                           "args": t.clock})
        tids: Dict[str, int] = {}
        for ev in t.events:
            if ev.get("ph") == "M":
                continue
            tensor = t.tensor_of(ev) or "?"
            if tensor not in tids:
                tids[tensor] = len(tids) + 1
                merged.append({"name": "thread_name", "ph": "M",
                               "pid": t.rank, "tid": tids[tensor],
                               "args": {"name": tensor}})
            one = {"name": ev.get("name"), "ph": ev.get("ph"),
                   "pid": t.rank, "tid": tids[tensor],
                   "ts": t.common_ts(ev.get("ts", 0)) - base}
            if ev.get("ph") == "i":
                one["s"] = "t"  # instant scope: thread (its tensor lane)
            if "args" in ev:
                one["args"] = ev["args"]
            merged.append(one)
            nevents += 1
    with open(out, "w") as fh:
        json.dump(merged, fh)
    return {"path": out, "files": len(traces), "events": nevents,
            "ranks": [t.rank for t in traces]}


# ---------------------------------------------------------------------------
# skew
# ---------------------------------------------------------------------------


def _self_marks(trace: RankTrace) -> Dict[str, List[int]]:
    """Per tensor, the common-base times at which THIS rank observed its
    own announcement (the RANK_READY instant with process == own rank) —
    the per-rank readiness series the negotiate-skew reconstruction
    pairs across ranks."""
    marks: Dict[str, List[int]] = {}
    for ev in trace.events:
        if ev.get("name") != RANK_READY or ev.get("ph") != "i":
            continue
        if ev.get("args", {}).get("process") != trace.rank:
            continue
        tensor = trace.tensor_of(ev)
        if tensor is None:
            continue
        marks.setdefault(tensor, []).append(trace.common_ts(ev["ts"]))
    for series in marks.values():
        series.sort()
    return marks


def skew_data(target: str) -> dict:
    """Reconstruct per-tensor negotiate skew across ranks. The k-th
    instance of a tensor pairs the k-th self-announcement of every rank;
    each instance charges rank r ``t_r − min(t)`` µs of imposed wait —
    the same quantity the telemetry straggler report accumulates from
    the round tables, here measured from the traces themselves."""
    traces = load_traces(target)
    per_rank_marks = {t.rank: _self_marks(t) for t in traces}
    negotiate_rounds = {}
    for t in traces:
        counts = {"cached": 0, "full": 0}
        for _, _, cached in _negotiate_rounds(
                _spans(t, with_end_args=True)):
            if cached is not None:
                counts["cached" if cached else "full"] += 1
        negotiate_rounds[t.rank] = counts
    ranks = sorted(per_rank_marks)
    wait_us: Dict[int, int] = {r: 0 for r in ranks}
    late_count: Dict[int, int] = {r: 0 for r in ranks}
    per_tensor: Dict[str, dict] = {}
    worst = None
    instances = 0
    tensors = sorted({n for m in per_rank_marks.values() for n in m})
    for name in tensors:
        series = {r: per_rank_marks[r].get(name, []) for r in ranks}
        covered = [r for r in ranks if series[r]]
        if len(covered) < 2:
            continue  # skew needs at least two ranks' announcements
        n = min(len(series[r]) for r in covered)
        tw: Dict[int, int] = {r: 0 for r in covered}
        for k in range(n):
            times = {r: series[r][k] for r in covered}
            t0 = min(times.values())
            late = max(times, key=times.get)
            instances += 1
            for r, t in times.items():
                tw[r] += t - t0
                wait_us[r] += t - t0
            skew = times[late] - t0
            if skew <= 0:
                continue  # a tie imposed no wait — blame nobody
            late_count[late] += 1
            if worst is None or skew > worst["skew_us"]:
                worst = {"tensor": name, "instance": k, "rank": late,
                         "skew_us": skew}
        per_tensor[name] = {
            "instances": n,
            "wait_us": tw,
            "worst_rank": max(tw, key=tw.get) if any(tw.values()) else None,
        }
    return {
        "ranks": ranks,
        "instances": instances,
        "wait_us": wait_us,
        "late_count": late_count,
        "per_tensor": per_tensor,
        "worst": worst,
        "negotiate_rounds": negotiate_rounds,
        "clock": {t.rank: t.clock for t in traces},
    }


_STRAGGLER_SAMPLE_RE = re.compile(
    r'^hvd_straggler_wait_microseconds\{process="(\d+)"\}\s+'
    r'([0-9.eE+\-]+)\s*$')


def parse_straggler_prom(path: str) -> Dict[int, int]:
    """Per-process imposed wait from an HVD_TELEMETRY_FILE exposition
    (hvd_straggler_wait_microseconds{process="N"}) — the cross-check
    target for the trace-reconstructed skew. Parsed inline (not via
    utils/stats.py) so this file stays runnable standalone."""
    out: Dict[int, int] = {}
    with open(path) as fh:
        for line in fh:
            m = _STRAGGLER_SAMPLE_RE.match(line.strip())
            if m:
                out[int(m.group(1))] = int(float(m.group(2)))
    return out


def skew_report(target: str, prom: Optional[str] = None) -> str:
    d = skew_data(target)
    lines = [f"trace skew over {len(d['ranks'])} rank(s), "
             f"{d['instances']} tensor instance(s)"]
    if not d["instances"]:
        lines.append("  (no multi-rank RANK_READY instants — single-rank "
                     "trace, or negotiation never ran)")
    tele = {}
    if prom is None and os.path.isdir(target):
        candidates = sorted(glob.glob(os.path.join(target, "*.prom")))
        prom = candidates[0] if candidates else None
    if prom:
        try:
            tele = parse_straggler_prom(prom)
        except OSError:
            tele = {}
    for r, us in sorted(d["wait_us"].items(), key=lambda kv: -kv[1]):
        line = (f"  process {r}: imposed wait {us / 1e6:.3f} s cumulative "
                f"(late on {d['late_count'][r]}/{d['instances']} instances)")
        if r in tele:
            line += f" [telemetry straggler report: {tele[r] / 1e6:.3f} s]"
        nr = d["negotiate_rounds"].get(r, {})
        if nr.get("cached") or nr.get("full"):
            line += (f" [negotiate spans: {nr['cached']} cached / "
                     f"{nr['full']} full]")
        lines.append(line)
    for name, pt in sorted(d["per_tensor"].items()):
        if pt["worst_rank"] is not None:
            lines.append(
                f"  {name}: slowest process {pt['worst_rank']} "
                f"(+{pt['wait_us'][pt['worst_rank']] / 1e3:.1f} ms over "
                f"{pt['instances']} instance(s))")
    if d["worst"]:
        w = d["worst"]
        lines.append(f"  worst instance: {w['tensor']}#{w['instance']} — "
                     f"process {w['rank']} announced "
                     f"{w['skew_us'] / 1e6:.3f} s after the first rank")
    for r, clk in sorted(d["clock"].items()):
        if clk:
            rtt = clk.get("rtt_us")
            lines.append(
                f"  clock rank {r}: offset {clk.get('offset_us', 0)} us"
                + (f", kv round-trip {rtt} us (skew error bound)"
                   if rtt is not None else " (no anchor exchange recorded)"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# critical path / phase shares
# ---------------------------------------------------------------------------


def _spans(trace: RankTrace, with_end_args: bool = False
           ) -> Dict[Tuple[str, str], List[tuple]]:
    """(tensor, activity) → [(begin, end)] in common time, from B/E
    pairs; ``with_end_args`` appends the span's args as a third element
    — the BEGIN event's args updated with the END event's (e.g. the
    `wire`/`wire_dcn` attribution stamped at span start, the `cached`
    attribution on NEGOTIATE span ends). Unbalanced begins (truncated
    trace) are dropped."""
    out: Dict[Tuple[str, str], List[tuple]] = {}
    open_spans: Dict[Tuple[str, str], List[tuple]] = {}
    for ev in trace.events:
        ph = ev.get("ph")
        if ph not in ("B", "E"):
            continue
        tensor = trace.tensor_of(ev)
        if tensor is None:
            continue
        key = (tensor, ev.get("name", ""))
        if ph == "B":
            open_spans.setdefault(key, []).append(
                (trace.common_ts(ev["ts"]), ev.get("args") or {}))
        else:
            stack = open_spans.get(key)
            if stack:
                ts0, bargs = stack.pop()
                span = (ts0, trace.common_ts(ev["ts"]))
                if with_end_args:
                    span += ({**bargs, **(ev.get("args") or {})},)
                out.setdefault(key, []).append(span)
    for v in out.values():
        v.sort(key=lambda s: s[:2])  # args dicts are not orderable
    return out


def _negotiate_rounds(spans: Dict[Tuple[str, str], List[tuple]]
                      ) -> List[Tuple[str, int, Optional[bool]]]:
    """Every completed NEGOTIATE_* span of an already-paired span dict
    (``_spans(trace, with_end_args=True)``) as (tensor, duration_us,
    cached) — ``cached`` is the response-cache attribution the engines
    stamp on the span END (only the resolving round knows whether it
    took the bitvector fast path); None for traces predating the arg."""
    out: List[Tuple[str, int, Optional[bool]]] = []
    for (tensor, act), sp in spans.items():
        if not act.startswith("NEGOTIATE_"):
            continue
        for b, e, args in sp:
            out.append((tensor, e - b, args.get("cached")))
    return out


def _span_stats(durs) -> dict:
    """count / total µs / median µs of a span-duration list (shared by
    the negotiate and memcpy attributions)."""
    if not durs:
        return {"count": 0, "us": 0, "median_us": None}
    durs = sorted(durs)
    return {"count": len(durs), "us": sum(durs),
            "median_us": durs[len(durs) // 2]}


def negotiate_attribution(span_dicts) -> dict:
    """Fast-vs-full attribution of negotiate time across ranks: counts,
    total µs and median µs of spans resolved by cached (bitvector)
    rounds vs full-table rounds. Takes the per-trace span dicts the
    caller already computed — no second pass over the events."""
    split = {"cached": [], "full": [], "unknown": []}
    for spans in span_dicts:
        for _, dur, cached in _negotiate_rounds(spans):
            bucket = ("unknown" if cached is None
                      else "cached" if cached else "full")
            split[bucket].append(dur)
    return {k: _span_stats(v) for k, v in split.items()}


def wire_attribution(span_dicts) -> dict:
    """Per-tier wire attribution of the collective phase: counts, total
    µs and median µs of collective spans split by route — ``flat``
    (full width, no wire arg), ``quantized`` (uniform wire policy, the
    `wire` span arg) or ``two_tier`` (hierarchical with a DCN-only
    policy, the `wire_dcn` span arg both engines stamp at span start).
    Same one-pass span-dict input as :func:`negotiate_attribution`."""
    split = {"flat": [], "quantized": [], "two_tier": []}
    for spans in span_dicts:
        for (tensor, act), sp in spans.items():
            if act not in _COLLECTIVES:
                continue
            for b, e, args in sp:
                if args.get("wire_dcn"):
                    bucket = "two_tier"
                elif args.get("wire"):
                    bucket = "quantized"
                else:
                    bucket = "flat"
                split[bucket].append(e - b)
    return {k: _span_stats(v) for k, v in split.items()}


def _phase_of(activity: str) -> Optional[str]:
    if activity.startswith("NEGOTIATE_"):
        return "NEGOTIATE"
    if activity in _COLLECTIVES:
        return "COLLECTIVE"
    if activity in ("MEMCPY", "MEMCPY_IN_FUSION_BUFFER", "WAIT_FOR_DATA",
                    "MEMCPY_OUT_FUSION_BUFFER"):
        return activity
    return None


def memcpy_attribution(span_dicts) -> dict:
    """Zero-copy attribution of the MEMCPY* phases: counts, total µs and
    median µs of copy spans split by how their submit/fusion copy was
    served — ``donated`` (ownership handoff, no copy), ``pooled``
    (pool-slab copy) or ``plain`` (fresh allocation / pre-pool traces).
    Same one-pass span-dict input as :func:`negotiate_attribution`."""
    split = {"donated": [], "pooled": [], "plain": []}
    for spans in span_dicts:
        for (tensor, act), sp in spans.items():
            if act not in _MEMCPY_PHASES:
                continue
            for b, e, args in sp:
                if args.get("donated"):
                    bucket = "donated"
                elif args.get("pooled"):
                    bucket = "pooled"
                else:
                    bucket = "plain"
                split[bucket].append(e - b)
    return {k: _span_stats(v) for k, v in split.items()}


def critical_path_data(target: str) -> dict:
    """Per-phase time decomposition of every completed tensor instance
    (one QUEUE span), aggregated into phase shares, plus the slowest
    instances' phase chains — the critical path through
    QUEUE→NEGOTIATE→MEMCPY→ALLREDUCE→MEMCPY_OUT."""
    traces = load_traces(target)
    phase_us = {p: 0 for p in _PHASE_ORDER}
    instances: List[dict] = []
    span_dicts = []  # reused for the negotiate attribution: ONE pass
    for t in traces:
        spans = _spans(t, with_end_args=True)
        span_dicts.append(spans)
        nested: Dict[str, List[Tuple[int, int, str]]] = {}
        for (tensor, act), sp in spans.items():
            phase = _phase_of(act)
            if phase is None:
                continue
            for b, e, _ in sp:
                nested.setdefault(tensor, []).append((b, e, phase))
        for (tensor, act), sp in spans.items():
            if act != "QUEUE":
                continue
            for b, e, qargs in sp:
                # A batched submit stamps batch_n on its QUEUE spans:
                # all N members share ONE wall-clock wait, so the
                # aggregate attributes each member's time at 1/N — a
                # 10k-member batch must not report 10k x the queue
                # interval as critical-path time. Per-instance numbers
                # stay unscaled (the slowest-instances view is about
                # that tensor's own experience).
                bn = max(1, int(qargs.get("batch_n", 1) or 1))
                inst = {"rank": t.rank, "tensor": tensor,
                        "total_us": e - b,
                        "phases": {p: 0 for p in _PHASE_ORDER}}
                if bn > 1:
                    inst["batch_n"] = bn
                for pb, pe, phase in nested.get(tensor, []):
                    if pb >= b and pe <= e:
                        inst["phases"][phase] += pe - pb
                accounted = sum(inst["phases"][p] for p in _PHASE_ORDER
                                if p != "OTHER")
                inst["phases"]["OTHER"] = max(0, inst["total_us"] - accounted)
                for p in _PHASE_ORDER:
                    phase_us[p] += inst["phases"][p] // bn
                instances.append(inst)
    total = sum(phase_us.values())
    shares = {p: (phase_us[p] / total if total else 0.0)
              for p in _PHASE_ORDER}
    instances.sort(key=lambda i: -i["total_us"])
    return {"instances": len(instances), "phase_us": phase_us,
            "shares": shares, "slowest": instances[:5],
            "negotiate": negotiate_attribution(span_dicts),
            "memcpy": memcpy_attribution(span_dicts),
            "wire": wire_attribution(span_dicts)}


def critical_path_report(target: str) -> str:
    d = critical_path_data(target)
    lines = [f"critical path over {d['instances']} completed tensor "
             "instance(s)", "phase shares of total in-flight time:"]
    for p in _PHASE_ORDER:
        lines.append(f"  {p:26s} {d['phase_us'][p] / 1e3:12.1f} ms "
                     f"{d['shares'][p] * 100:5.1f}%")
    neg = d.get("negotiate", {})
    if any(neg.get(k, {}).get("count") for k in ("cached", "full")):
        # Response-cache attribution: which negotiate rounds rode the
        # bitvector fast path vs full wire tables.
        parts = []
        for k in ("cached", "full"):
            s = neg.get(k, {"count": 0})
            if s["count"]:
                parts.append(f"{k} n={s['count']} "
                             f"median={s['median_us'] / 1e3:.2f} ms")
        lines.append("negotiate rounds (response cache): "
                     + " | ".join(parts))
    mem = d.get("memcpy", {})
    if any(mem.get(k, {}).get("count") for k in ("donated", "pooled")):
        # Zero-copy attribution: how the copy phases were served.
        parts = []
        for k in ("donated", "pooled", "plain"):
            s = mem.get(k, {"count": 0})
            if s["count"]:
                parts.append(f"{k} n={s['count']} "
                             f"median={s['median_us'] / 1e3:.3f} ms")
        lines.append("copy spans (buffer pool): " + " | ".join(parts))
    wire = d.get("wire", {})
    if any(wire.get(k, {}).get("count") for k in ("quantized", "two_tier")):
        # Wire-route attribution: which collective spans rode the
        # uniform quantized wire vs the hierarchical per-tier route.
        parts = []
        for k in ("flat", "quantized", "two_tier"):
            s = wire.get(k, {"count": 0})
            if s["count"]:
                parts.append(f"{k} n={s['count']} "
                             f"median={s['median_us'] / 1e3:.3f} ms")
        lines.append("collective spans (wire route): " + " | ".join(parts))
    if d["slowest"]:
        lines.append("slowest instances (the critical path):")
        for inst in d["slowest"]:
            chain = " -> ".join(
                f"{p}:{inst['phases'][p] / 1e3:.1f}ms"
                for p in _PHASE_ORDER if inst["phases"][p] > 0)
            lines.append(f"  rank {inst['rank']} {inst['tensor']}: "
                         f"{inst['total_us'] / 1e3:.1f} ms ({chain})")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------


def stats_data(target: str) -> dict:
    traces = load_traces(target)
    ranks = {}
    for t in traces:
        counts: Dict[str, int] = {}
        durations: Dict[str, int] = {}
        first = last = None
        for ev in t.events:
            if ev.get("ph") == "M":
                continue
            ts = t.common_ts(ev.get("ts", 0))
            first = ts if first is None else min(first, ts)
            last = ts if last is None else max(last, ts)
            counts[ev.get("name", "?")] = counts.get(ev.get("name", "?"),
                                                     0) + 1
        for (tensor, act), sp in _spans(t).items():
            durations[act] = durations.get(act, 0) + sum(
                e - b for b, e in sp)
        ranks[t.rank] = {
            "file": os.path.basename(t.path),
            "events": sum(counts.values()),
            "counts": counts,
            "span_duration_us": durations,
            "window_us": (last - first) if first is not None else 0,
            "clock": t.clock,
        }
    return {"ranks": ranks}


def stats_report(target: str) -> str:
    d = stats_data(target)
    lines = []
    for r, info in sorted(d["ranks"].items()):
        lines.append(f"rank {r} ({info['file']}): {info['events']} events "
                     f"over {info['window_us'] / 1e6:.3f} s")
        for act in sorted(info["counts"]):
            dur = info["span_duration_us"].get(act)
            lines.append(
                f"  {act:26s} x{info['counts'][act]:<6d}"
                + (f" {dur / 1e3:10.1f} ms total" if dur else ""))
        clk = info["clock"]
        if clk:
            lines.append(f"  clock: epoch_wall_us={clk.get('epoch_wall_us')}"
                         f" offset_us={clk.get('offset_us')}"
                         f" rtt_us={clk.get('rtt_us')}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.trace",
        description="Analyze per-rank HVD_TIMELINE traces: merge onto a "
                    "common clock, reconstruct cross-rank skew, "
                    "decompose the critical path.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("merge", help="merge per-rank files into one "
                                     "Perfetto trace (pid=rank)")
    p.add_argument("target")
    p.add_argument("-o", "--out", default=None)
    p = sub.add_parser("skew", help="per-tensor negotiate skew: who was "
                                    "late, imposed wait per process")
    p.add_argument("target")
    p.add_argument("--prom", default=None,
                   help="HVD_TELEMETRY_FILE exposition to cross-check "
                        "against (default: *.prom in the trace dir)")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser("critical-path", help="phase shares + slowest "
                                             "instances")
    p.add_argument("target")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser("stats", help="per-rank event counts and durations")
    p.add_argument("target")
    p.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        if args.cmd == "merge":
            info = merge(args.target, args.out)
            print(f"merged {info['files']} rank file(s), "
                  f"{info['events']} events -> {info['path']}")
        elif args.cmd == "skew":
            if args.json:
                d = skew_data(args.target)
                d["wait_us"] = {str(k): v for k, v in d["wait_us"].items()}
                d["late_count"] = {str(k): v
                                   for k, v in d["late_count"].items()}
                d["clock"] = {str(k): v for k, v in d["clock"].items()}
                print(json.dumps(d))
            else:
                print(skew_report(args.target, prom=args.prom))
        elif args.cmd == "critical-path":
            if args.json:
                print(json.dumps(critical_path_data(args.target)))
            else:
                print(critical_path_report(args.target))
        elif args.cmd == "stats":
            if args.json:
                d = stats_data(args.target)
                d["ranks"] = {str(k): v for k, v in d["ranks"].items()}
                print(json.dumps(d))
            else:
                print(stats_report(args.target))
    except (FileNotFoundError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
