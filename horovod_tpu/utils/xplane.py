"""XLA profile (xplane) summarizer — where does the step time go?

The reference answers "where did the time go" with its chrome-tracing
timeline of host-side engine phases (horovod/common/timeline.cc); on TPU
the compiled step is one fused XLA program, so the equivalent question is
answered from the XLA profiler's device plane. This module turns a
``jax.profiler.trace`` capture (``bench.py --profile DIR``,
``examples/bert_pretraining_benchmark.py --profile DIR``) into the
per-op-category breakdown used in docs/benchmarks.md:

    python -m horovod_tpu.utils.xplane /tmp/prof [--top 30]

It parses the ``*.xplane.pb`` protobuf with the proto bindings TF ships
(tensorflow.tsl.profiler.protobuf) — no tensorboard needed.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, List, Tuple


def _load_spaces(logdir: str, files=None):
    """Parse the capture's xplane protobufs. ``files`` restricts the
    parse to an explicit list — callers measuring ONE capture window in
    a reused logdir must pass the files that window produced, or prior
    captures in the same tree silently inflate every byte count."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    from horovod_tpu.utils.profiler import trace_files

    spaces = []
    for path in (trace_files(logdir) if files is None else files):
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        spaces.append(space)
    return spaces


def _device_lines(spaces, line_name):
    """Yield (plane, line) for every device-plane line named
    ``line_name`` — the one place the device-plane selection idiom
    lives (three metrics must not disagree over the same capture)."""
    for space in spaces:
        for plane in space.planes:
            if "/device:" not in plane.name and "TPU" not in plane.name:
                continue
            for line in plane.lines:
                if line.name == line_name:
                    yield plane, line


def device_op_times(logdir: str, line_name: str = "XLA Ops") -> Dict[str, float]:
    """Sum device-plane event durations (ms) by op/fusion name across all
    captured cores, from the ``line_name`` line only.

    The TPU device plane carries hierarchical lines — "Steps" and
    "XLA Modules" span whole steps, "Async XLA Ops" are DMA spans that
    overlap compute — so summing everything would double-count wildly.
    "XLA Ops" is the sequencer's occupancy: its events tile the step
    back-to-back (a copy-done there is the WAIT the scheduler failed to
    hide, not the copy itself), which is the decomposition
    docs/benchmarks.md's tables use."""
    totals: Dict[str, float] = collections.defaultdict(float)
    for plane, line in _device_lines(_load_spaces(logdir), line_name):
        meta = {i: m.name for i, m in plane.event_metadata.items()}
        for ev in line.events:
            name = meta.get(ev.metadata_id, str(ev.metadata_id))
            totals[name] += ev.duration_ps / 1e9  # ps -> ms
    return dict(totals)


_CATEGORIES: List[Tuple[str, str]] = [
    # (regex on op name, category label) — first match wins. The matmul
    # pattern sits BEFORE the generic fusion buckets because on TPU
    # nearly every matmul surfaces as a fusion op; when the fusion's
    # name carries its root ("%fusion.7 dot.42" / "loop_dot_fusion") it
    # is classified as matmul here. Anonymous "fusion.N" names give no
    # such signal and still land in the fusion buckets, so the matmul
    # row is a LOWER bound on MXU share — docs/benchmarks.md's MFU
    # numbers come from analytic FLOPs, not this table.
    # NB: no bare "conv" — it would swallow "%convert_*" names.
    (r"convolution|conv\d", "convolution"),
    (r"dot|einsum|matmul|gemm", "matmul"),
    (r"convert.*fusion|fusion.*convert", "convert/reduce fusion"),
    (r"multiply.*add.*fusion|scatter.*fusion", "multiply-add fusion"),
    (r"fusion", "other fusion"),
    (r"copy|slice|bitcast|transpose|reshape", "copy/layout"),
    (r"all-reduce|all-gather|reduce-scatter|collective|permute",
     "collective"),
    (r"select-and-scatter", "select-and-scatter"),
    (r"rng|random", "rng"),
    (r"infeed|outfeed|send|recv", "host transfer"),
]


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

_SHAPE_LAYOUT_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([\d,]*)\](?:\{([^}]*)\})?")


def _shape_bytes(dt: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES[dt]


def _first_shape_bytes(name: str) -> int:
    """Payload bytes of the FIRST shape literal in an HLO op string.

    Async-copy events are named with their full HLO text, e.g.
    ``%copy-start = (f32[16777216]{0:T(1024)S(1)}, ...)`` — the first
    shape is the destination buffer, i.e. the DMA payload. Returns 0
    when no shape is present (e.g. tuple-only or token ops).
    """
    m = _SHAPE_LAYOUT_RE.search(name)
    if not m:
        return 0
    dt, dims, _ = m.groups()
    return _shape_bytes(dt, dims)


def dma_bytes(logdir: str, line_name: str = "Async XLA Ops",
              spaces=None) -> Dict[str, float]:
    """Sum the DMA payload bytes moved by the async-copy engine.

    The TPU device plane's "Async XLA Ops" line carries one span per
    in-flight async copy (HBM<->VMEM staging; the copies the scheduler
    issues ahead of compute). Event stats hold no byte counts, but the
    event NAME is the HLO text whose first shape literal is the payload
    — that is what this sums. This measures the *prefetch-engine*
    traffic only: bytes a fusion loads/stores directly from HBM in its
    own loop never appear here, so the result is a LOWER bound on true
    HBM traffic for the capture window.

    Returns {"bytes": total payload bytes, "events": count,
    "busy_ms": summed span duration}.
    """
    total = 0.0
    nev = 0
    busy = 0.0
    if spaces is None:
        spaces = _load_spaces(logdir)
    for plane, line in _device_lines(spaces, line_name):
        meta = {i: m.name for i, m in plane.event_metadata.items()}
        for ev in line.events:
            name = meta.get(ev.metadata_id, "")
            b = _first_shape_bytes(name)
            if b:
                total += b
                nev += 1
                busy += ev.duration_ps / 1e9
    return {"bytes": total, "events": nev, "busy_ms": busy}


# Ops whose name-level operand lists alias or re-list buffers that other
# events already account for (while re-lists its whole carry tuple; GTEs
# are views; copy-done is the wait for a copy-start counted already).
_NO_TRAFFIC_OPS = frozenset({
    "while", "conditional", "call", "tuple", "get-tuple-element",
    "parameter", "bitcast", "constant", "copy-done", "after-all",
    "optimization-barrier",
})

_ID_ROOT_RE = re.compile(r"^%?([A-Za-z][\w.-]*?)(?:\.\d+)?(?:\s|=|$)")


def _op_root(name: str) -> str:
    """Op identifier root of an HLO text: "%while.2 = (...) while(...)"
    -> "while"; "%convert_reduce_fusion.1215 = ..." ->
    "convert_reduce_fusion". HLO ids default to the op type, so this is
    robust where an op-type regex is not (tuple output shapes contain
    nested parens that defeat simple matching)."""
    m = _ID_ROOT_RE.match(name)
    return m.group(1) if m else ""


def _hbm_shape_bytes_by_dtype(text: str) -> Dict[str, int]:
    """Bytes of every shape literal in ``text`` whose layout does NOT
    place it in a scoped memory space (``S(n)`` = VMEM/SMEM; unannotated
    layouts are HBM, space 0), split by element dtype — the bf16-vs-f32
    byte attribution the ``state_dtype`` policy (HBM diet round 2) is
    judged by: a mixed-precision regression shows up as f32 bytes
    creeping back into a class that should stream bf16."""
    out: Dict[str, int] = {}
    for dt, dims, layout in _SHAPE_LAYOUT_RE.findall(text):
        if layout and "S(" in layout:
            continue
        out[dt] = out.get(dt, 0) + _shape_bytes(dt, dims)
    return out


def _hbm_shape_bytes(text: str) -> int:
    """Total over :func:`_hbm_shape_bytes_by_dtype` — one accounting
    rule, so the per-dtype split can never desynchronize from the
    totals."""
    return sum(_hbm_shape_bytes_by_dtype(text).values())


def hbm_bytes(logdir: str, spaces=None) -> Dict[str, float]:
    """Per-capture HBM traffic derived from the COMPILED schedule.

    For every executed op on the sequencer's "XLA Ops" line, the event
    name is the scheduled HLO text: output + operand shape literals,
    each carrying its assigned memory space (``S(1)`` = VMEM; no ``S``
    = HBM). Summing the HBM-resident shapes over all executions counts
    the bytes each op moves to/from HBM — fusions' direct loads/stores
    included, which the async-DMA accounting (:func:`dma_bytes`) cannot
    see. Control-flow/aliasing ops (while, get-tuple-element, ...) are
    skipped — their names re-list buffers the real ops already count —
    and async copies are counted once at copy-start (copy-done is the
    wait). Known over-count: an in-place dynamic-update-slice is
    charged its full buffer. Returns {"bytes", "events"}.
    """
    total = 0.0
    nev = 0
    if spaces is None:
        spaces = _load_spaces(logdir)
    for plane, line in _device_lines(spaces, "XLA Ops"):
        meta = {i: m.name for i, m in plane.event_metadata.items()}
        # Per-op-name bytes memoized: 14k unique names, millions of events.
        cache: Dict[int, int] = {}
        for ev in line.events:
            b = cache.get(ev.metadata_id)
            if b is None:
                name = meta.get(ev.metadata_id, "")
                b = (0 if _op_root(name) in _NO_TRAFFIC_OPS
                     else _hbm_shape_bytes(name))
                cache[ev.metadata_id] = b
            if b:
                total += b
                nev += 1
    return {"bytes": total, "events": nev}


# Categories whose HBM byte counts are DIRECT streams (single-pass
# compute fusions — exact at the name level, unlike slice/copy ops
# whose names over-count their source buffers).
_DIRECT_CATS = ("conv+BN fusion", "wgrad+update fusion", "maxpool bwd",
                "elementwise fusion")


def _category_totals(spaces):
    """Per-category (sequencer ms, direct HBM bytes) over "XLA Ops"."""
    cat_ms: Dict[str, float] = collections.defaultdict(float)
    cat_b: Dict[str, float] = collections.defaultdict(float)
    for plane, line in _device_lines(spaces, "XLA Ops"):
        meta = {i: m.name for i, m in plane.event_metadata.items()}
        info: Dict[int, Tuple[str, int]] = {}
        for ev in line.events:
            mid = ev.metadata_id
            if mid not in info:
                name = meta.get(mid, "")
                op = _op_root(name)
                key = name.split(" = ")[0]
                if op in ("while", "conditional"):
                    cat = "while wrapper"
                elif "convert_reduce_fusion" in key:
                    cat = "conv+BN fusion"
                elif "multiply_add_fusion" in key:
                    cat = "wgrad+update fusion"
                elif "select-and-scatter" in key:
                    cat = "maxpool bwd"
                elif re.match(r"%(loop_)?fusion", key):
                    cat = "elementwise fusion"
                elif "start" in op or "done" in op or "copy" in key:
                    cat = "async copy waits"
                else:
                    cat = "other"
                b = (_hbm_shape_bytes(name)
                     if cat in _DIRECT_CATS and op not in _NO_TRAFFIC_OPS
                     else 0)
                info[mid] = (cat, b)
            cat, b = info[mid]
            cat_ms[cat] += ev.duration_ps / 1e9
            cat_b[cat] += b
    return cat_ms, cat_b


# Per-op-CLASS attribution (coarser than _category_totals' fusion-name
# buckets): where do the HBM bytes go — the wire, the optimizer, or the
# math? First match wins; roots in _NO_TRAFFIC_OPS are classed
# "control" with zero bytes (their names re-list buffers real ops own).
_OP_CLASSES: List[Tuple[str, str]] = [
    (r"all-reduce|all-gather|reduce-scatter|all-to-all|"
     r"collective-permute|collective", "collective"),
    # wgrad+momentum+param-apply fusions (TPU names them multiply_add /
    # scatter fusions; see _category_totals) — the traffic the sharded
    # weight update divides by N.
    (r"multiply[._-]?add.*fusion|scatter.*fusion", "optimizer"),
    (r"convolution|conv\d|dot|einsum|matmul|gemm|convert_reduce_fusion",
     "conv/matmul"),
    (r"copy|slice|bitcast|transpose|reshape|dynamic-update", "copy/layout"),
    (r"rng|random", "rng"),
    (r"infeed|outfeed|send|recv", "host transfer"),
    (r"fusion", "elementwise fusion"),
]


def _op_class(name: str) -> str:
    if _op_root(name) in _NO_TRAFFIC_OPS:
        return "control"
    low = name.lower()
    for pat, label in _OP_CLASSES:
        if re.search(pat, low):
            return label
    return "other"


def class_breakdown(logdir: str, steps: int = 1,
                    spaces=None) -> Dict[str, Dict[str, float]]:
    """Per-op-class sequencer time and schedule-derived HBM bytes over
    the "XLA Ops" line: ``{class: {"ms": .., "bytes": ..,
    "by_dtype": {dtype: bytes}}}`` (per step).

    This is the attribution table for traffic regressions: a jump in
    "collective" bytes means the wire (or a size-1 world failing to
    elide its collectives), "optimizer" the update fusions the sharded
    weight update divides by N, "conv/matmul" the math itself; the
    per-dtype split inside each class is the ``state_dtype`` policy's
    audit trail (f32 bytes reappearing in "optimizer" or "collective"
    means a full-width master/gradient buffer crept back). Bytes are
    name-level (each op's non-VMEM operand/result shapes — same
    accounting as :func:`hbm_bytes`), so copy/layout ops over-count
    their source buffers; "control" ops contribute time but no bytes.
    """
    out: Dict[str, Dict[str, float]] = collections.defaultdict(
        lambda: {"ms": 0.0, "bytes": 0.0,
                 "by_dtype": collections.defaultdict(float)})
    if spaces is None:
        spaces = _load_spaces(logdir)
    for plane, line in _device_lines(spaces, "XLA Ops"):
        meta = {i: m.name for i, m in plane.event_metadata.items()}
        info: Dict[int, Tuple[str, int, dict]] = {}
        for ev in line.events:
            mid = ev.metadata_id
            if mid not in info:
                name = meta.get(mid, "")
                cls = _op_class(name)
                if cls == "control":
                    info[mid] = (cls, 0, {})
                else:
                    bd = _hbm_shape_bytes_by_dtype(name)
                    info[mid] = (cls, sum(bd.values()), bd)
            cls, b, bd = info[mid]
            out[cls]["ms"] += ev.duration_ps / 1e9
            out[cls]["bytes"] += b
            for dt, db in bd.items():
                out[cls]["by_dtype"][dt] += db
    steps = max(steps, 1)
    return {c: {"ms": v["ms"] / steps, "bytes": v["bytes"] / steps,
                "by_dtype": {dt: db / steps
                             for dt, db in sorted(v["by_dtype"].items())}}
            for c, v in out.items()}


def _dtype_totals(classes: Dict[str, dict]) -> Dict[str, float]:
    """Capture-wide per-dtype byte totals summed over a
    :func:`class_breakdown` result — the one accounting rule behind both
    ``hbm_json``'s ``bytes_by_dtype_per_step`` and the CLI table's
    per-dtype columns, so the two can never disagree."""
    totals: Dict[str, float] = collections.defaultdict(float)
    for v in classes.values():
        for dt, db in v["by_dtype"].items():
            totals[dt] += db
    return dict(totals)


def fusion_direct_bytes(logdir: str, spaces=None) -> float:
    """Total bytes the compute fusions stream to/from HBM directly
    (their non-VMEM operand/output shapes) — the component of true HBM
    traffic the async-DMA accounting (:func:`dma_bytes`) cannot see.
    ``dma_bytes()["bytes"] + fusion_direct_bytes()`` is the measured
    true-traffic figure docs/benchmarks.md's roofline uses."""
    if spaces is None:
        spaces = _load_spaces(logdir)
    _, cat_b = _category_totals(spaces)
    return float(sum(cat_b.values()))


def hbm_json(logdir: str, steps: int = 1, spaces=None) -> dict:
    """Machine-readable form of the ``--hbm`` attribution (what
    ``--json`` prints and what bench tooling / the stats CLI consume
    instead of re-parsing the human table): per-op-class ms + bytes per
    step, the async-DMA payload, the fusion direct streams, and the
    true-traffic sum."""
    if spaces is None:
        spaces = _load_spaces(logdir)
    steps = max(steps, 1)
    dma = dma_bytes(logdir, spaces=spaces)
    direct = fusion_direct_bytes(logdir, spaces=spaces)
    classes = class_breakdown(logdir, steps=steps, spaces=spaces)
    by_dtype = _dtype_totals(classes)
    return {
        "steps": steps,
        "classes": classes,
        # Schedule-derived (name-level) bytes split by element dtype —
        # the bf16-vs-f32 audit column for the state_dtype policy.
        "bytes_by_dtype_per_step": dict(sorted(by_dtype.items())),
        "dma_bytes": dma["bytes"],
        "dma_events": dma["events"],
        "dma_busy_ms": dma["busy_ms"],
        "fusion_direct_bytes": direct,
        "true_hbm_bytes_per_step": (dma["bytes"] + direct) / steps,
        "module_ms": module_ms(logdir, spaces=spaces),
    }


def hbm_report(logdir: str, steps: int = 1, spaces=None) -> str:
    """The measured-roofline table (docs/benchmarks.md "The ceiling,
    measured"): per-category sequencer time, schedule-derived HBM bytes
    and achieved GB/s, plus the async-DMA payload and the true-traffic
    sum (DMA + fusion direct streams — disjoint by construction: a
    VMEM-resident operand is excluded from the fusion term).

    The scan's ``while`` wrapper is excluded — it spans the whole loop
    the inner ops already tile. Slice/copy -start/-done bytes are
    excluded from the direct-stream sum (their payloads are what the
    Async line counts; their name-level source shapes over-count)."""
    if spaces is None:
        spaces = _load_spaces(logdir)
    cat_ms, cat_b = _category_totals(spaces)
    dma = dma_bytes(logdir, spaces=spaces)
    inner = sum(ms for c, ms in cat_ms.items() if c != "while wrapper")
    if not inner:
        return (f"no device 'XLA Ops' events found under {logdir} "
                f"(empty or failed capture)")
    direct_gb = sum(cat_b.values()) / 1e9
    dma_gb = dma["bytes"] / 1e9
    out = [f"inner-op device time: {inner / steps:.2f} ms/step "
           f"({steps} steps)",
           f"{'category':22s} {'ms/step':>8s} {'share':>6s} "
           f"{'GB/step':>8s} {'GB/s':>6s}"]
    for c, ms in sorted(cat_ms.items(), key=lambda kv: -kv[1]):
        if c == "while wrapper":
            continue
        gbs = cat_b[c] / 1e9 / (ms / 1e3) if ms and cat_b[c] else 0
        out.append(f"{c:22s} {ms / steps:8.3f} {100 * ms / inner:5.1f}% "
                   f"{cat_b[c] / 1e9 / steps:8.2f} "
                   f"{gbs:6.0f}" if gbs else
                   f"{c:22s} {ms / steps:8.3f} {100 * ms / inner:5.1f}% "
                   f"{cat_b[c] / 1e9 / steps:8.2f} {'—':>6s}")
    out.append(f"async-DMA payload: {dma_gb / steps:.2f} GB/step "
               f"({dma['events'] // max(steps, 1)} copies/step)")
    total = (dma_gb + direct_gb) / steps
    out.append(f"true HBM traffic (DMA + direct streams): {total:.2f} "
               f"GB/step -> {total / (inner / steps / 1e3):.0f} GB/s "
               f"achieved over the device step")
    # Attribution: which op CLASS owns the bytes (collective wire vs
    # optimizer update vs the math) — the table that makes a traffic
    # regression attributable. Name-level accounting; "control" ops
    # (incl. the while wrapper, whose span covers the whole loop)
    # carry time but no bytes.
    classes = class_breakdown(logdir, steps=steps, spaces=spaces)
    # Per-dtype columns (bf16-vs-f32 split, HBM diet round 2): one
    # column per dtype carrying bytes anywhere in the capture, heaviest
    # first, so a full-width f32 buffer creeping back under a bf16
    # state policy is visible per class.
    dtotals = _dtype_totals(classes)
    dts = [d for d, _ in sorted(dtotals.items(), key=lambda kv: -kv[1])]
    out.append("per-op-class (schedule-derived bytes, name-level):")
    out.append(f"  {'class':20s} {'ms/step':>8s} {'GB/step':>8s}"
               + "".join(f" {('GB ' + d):>8s}" for d in dts))
    for c, v in sorted(classes.items(), key=lambda kv: -kv[1]["bytes"]):
        row = f"  {c:20s} {v['ms']:8.3f} {v['bytes'] / 1e9:8.2f}"
        for d in dts:
            row += f" {v['by_dtype'].get(d, 0.0) / 1e9:8.2f}"
        out.append(row)
    return "\n".join(out)


def categorize(name: str) -> str:
    low = name.lower()
    for pat, label in _CATEGORIES:
        if re.search(pat, low):
            return label
    return "other"


def module_ms(logdir: str, spaces=None) -> float:
    """Total device-occupancy of compiled modules (ms): the "XLA
    Modules" line spans whole executions, so this is the denominator for
    achieved-bandwidth numbers over a capture window."""
    if spaces is None:
        spaces = _load_spaces(logdir)
    return sum(ev.duration_ps / 1e9
               for _, line in _device_lines(spaces, "XLA Modules")
               for ev in line.events)


def summarize(logdir: str, top: int = 25, line_name: str = "XLA Ops") -> str:
    """Human-readable breakdown: per-category totals plus the `top`
    heaviest individual ops."""
    times = device_op_times(logdir, line_name=line_name)
    if not times:
        return f"no device-plane events found under {logdir}"
    total = sum(times.values())
    by_cat: Dict[str, float] = collections.defaultdict(float)
    by_cat_n: Dict[str, int] = collections.defaultdict(int)
    for name, ms in times.items():
        c = categorize(name)
        by_cat[c] += ms
        by_cat_n[c] += 1
    out = [f"device op time total: {total:.2f} ms (all cores, whole trace)",
           "", "by category:"]
    for cat, ms in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        out.append(f"  {ms:10.2f} ms  {100 * ms / total:5.1f}%  "
                   f"{cat}  (x{by_cat_n[cat]})")
    out.append("")
    out.append(f"top {top} ops:")
    for name, ms in sorted(times.items(), key=lambda kv: -kv[1])[:top]:
        out.append(f"  {ms:10.2f} ms  {100 * ms / total:5.1f}%  {name[:90]}")
    return "\n".join(out)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="Summarize a jax.profiler.trace capture by device op")
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--line", default="XLA Ops",
                    help="device-plane line to sum (e.g. 'Async XLA Ops' "
                         "for the overlapped DMA spans)")
    ap.add_argument("--dma", action="store_true",
                    help="report async-DMA payload bytes (a lower bound "
                         "on HBM traffic) and achieved GB/s over the "
                         "captured device time")
    ap.add_argument("--steps", type=int, default=None,
                    help="training steps in the capture window (with "
                         "--dma/--hbm: per-step figures)")
    ap.add_argument("--hbm", action="store_true",
                    help="measured-roofline table: per-category time + "
                         "HBM bytes + achieved GB/s, async-DMA payload, "
                         "true-traffic sum, and the per-op-class "
                         "attribution (collective vs optimizer vs "
                         "conv/matmul bytes) (docs/benchmarks.md)")
    ap.add_argument("--json", action="store_true",
                    help="with --hbm: machine-readable attribution "
                         "(what bench tooling and utils.stats consume)")
    args = ap.parse_args(argv)
    if args.hbm:
        import json as _json

        if args.json:
            print(_json.dumps(hbm_json(args.logdir, steps=args.steps or 1)))
        else:
            print(hbm_report(args.logdir, steps=args.steps or 1))
    elif args.dma:
        spaces = _load_spaces(args.logdir)  # parse the (large) pbs once
        d = dma_bytes(args.logdir, spaces=spaces)
        dev_ms = module_ms(args.logdir, spaces=spaces)
        if not dev_ms:
            print(f"no device module events found under {args.logdir} "
                  f"(empty or failed capture)")
            return
        out = [f"async-DMA payload: {d['bytes'] / 1e9:.2f} GB over "
               f"{d['events']} copies (engine busy {d['busy_ms']:.1f} ms)",
               f"device module time: {dev_ms:.1f} ms -> achieved "
               f"{d['bytes'] / 1e9 / (dev_ms / 1e3):.0f} GB/s "
               f"(prefetch engine only; lower bound on HBM traffic)"]
        if args.steps:
            out.append(f"per step ({args.steps}): "
                       f"{d['bytes'] / 1e9 / args.steps:.2f} GB")
        print("\n".join(out))
    else:
        print(summarize(args.logdir, top=args.top, line_name=args.line))


if __name__ == "__main__":
    main()
