"""XLA profile (xplane) summarizer — where does the step time go?

The reference answers "where did the time go" with its chrome-tracing
timeline of host-side engine phases (horovod/common/timeline.cc); on TPU
the compiled step is one fused XLA program, so the equivalent question is
answered from the XLA profiler's device plane. This module turns a
``jax.profiler.trace`` capture (``bench.py --profile DIR``,
``examples/bert_pretraining_benchmark.py --profile DIR``) into the
per-op-category breakdown used in docs/benchmarks.md:

    python -m horovod_tpu.utils.xplane /tmp/prof [--top 30]

It parses the ``*.xplane.pb`` protobuf with the proto bindings TF ships
(tensorflow.tsl.profiler.protobuf) — no tensorboard needed.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, List, Tuple


def _load_spaces(logdir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    from horovod_tpu.utils.profiler import trace_files

    spaces = []
    for path in trace_files(logdir):
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        spaces.append(space)
    return spaces


def device_op_times(logdir: str, line_name: str = "XLA Ops") -> Dict[str, float]:
    """Sum device-plane event durations (ms) by op/fusion name across all
    captured cores, from the ``line_name`` line only.

    The TPU device plane carries hierarchical lines — "Steps" and
    "XLA Modules" span whole steps, "Async XLA Ops" are DMA spans that
    overlap compute — so summing everything would double-count wildly.
    "XLA Ops" is the sequencer's occupancy: its events tile the step
    back-to-back (a copy-done there is the WAIT the scheduler failed to
    hide, not the copy itself), which is the decomposition
    docs/benchmarks.md's tables use."""
    totals: Dict[str, float] = collections.defaultdict(float)
    for space in _load_spaces(logdir):
        for plane in space.planes:
            if "/device:" not in plane.name and "TPU" not in plane.name:
                continue
            meta = {i: m.name for i, m in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != line_name:
                    continue
                for ev in line.events:
                    name = meta.get(ev.metadata_id, str(ev.metadata_id))
                    totals[name] += ev.duration_ps / 1e9  # ps -> ms
    return dict(totals)


_CATEGORIES: List[Tuple[str, str]] = [
    # (regex on op name, category label) — first match wins.
    (r"convolution|conv\d|%conv", "convolution"),
    (r"convert.*fusion|fusion.*convert", "convert/reduce fusion"),
    (r"multiply.*add.*fusion|scatter.*fusion", "multiply-add fusion"),
    (r"fusion", "other fusion"),
    (r"copy|slice|bitcast|transpose|reshape", "copy/layout"),
    (r"all-reduce|all-gather|reduce-scatter|collective|permute",
     "collective"),
    (r"dot|einsum|matmul", "matmul"),
    (r"select-and-scatter", "select-and-scatter"),
    (r"rng|random", "rng"),
    (r"infeed|outfeed|send|recv", "host transfer"),
]


def categorize(name: str) -> str:
    low = name.lower()
    for pat, label in _CATEGORIES:
        if re.search(pat, low):
            return label
    return "other"


def summarize(logdir: str, top: int = 25, line_name: str = "XLA Ops") -> str:
    """Human-readable breakdown: per-category totals plus the `top`
    heaviest individual ops."""
    times = device_op_times(logdir, line_name=line_name)
    if not times:
        return f"no device-plane events found under {logdir}"
    total = sum(times.values())
    by_cat: Dict[str, float] = collections.defaultdict(float)
    by_cat_n: Dict[str, int] = collections.defaultdict(int)
    for name, ms in times.items():
        c = categorize(name)
        by_cat[c] += ms
        by_cat_n[c] += 1
    out = [f"device op time total: {total:.2f} ms (all cores, whole trace)",
           "", "by category:"]
    for cat, ms in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        out.append(f"  {ms:10.2f} ms  {100 * ms / total:5.1f}%  "
                   f"{cat}  (x{by_cat_n[cat]})")
    out.append("")
    out.append(f"top {top} ops:")
    for name, ms in sorted(times.items(), key=lambda kv: -kv[1])[:top]:
        out.append(f"  {ms:10.2f} ms  {100 * ms / total:5.1f}%  {name[:90]}")
    return "\n".join(out)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="Summarize a jax.profiler.trace capture by device op")
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--line", default="XLA Ops",
                    help="device-plane line to sum (e.g. 'Async XLA Ops' "
                         "for the overlapped DMA spans)")
    args = ap.parse_args(argv)
    print(summarize(args.logdir, top=args.top, line_name=args.line))


if __name__ == "__main__":
    main()
