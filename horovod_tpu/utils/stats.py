"""Telemetry stats CLI — the query tool over the unified registry.

    python -m horovod_tpu.utils.stats <target> [--json] [--watch N]

``target`` is one of:

- a Prometheus-style text file written by ``HVD_TELEMETRY_FILE`` (see
  :mod:`horovod_tpu.core.telemetry`) — parsed and pretty-printed
  (``--watch N`` re-reads every N seconds, the poor-man's dashboard);
- an ``http://host:port`` (or full ``.../metrics``) URL served by
  ``HVD_TELEMETRY_PORT`` (:mod:`horovod_tpu.core.telemetry_http`) —
  fetched and rendered exactly like the file (``--watch`` re-fetches);
- an XLA profiler capture directory (``bench.py --profile DIR``) — the
  machine-readable HBM attribution (:func:`horovod_tpu.utils.xplane.
  hbm_json`, the same data ``xplane --hbm --json`` emits), so bench
  tooling never re-parses the human table;
- ``live`` — snapshot of the *current process's* registry (only useful
  from code/REPL in the process doing the work; cross-process use goes
  through the exposition file or the HTTP endpoint).

``--json`` emits ONE envelope shape regardless of source — ``{"source",
"target", "samples": [{"name", "labels", "value"}, ...]}`` — so a
dashboard script written against a file keeps working pointed at a live
``http://`` rank or a capture dir (xplane figures flatten into
``xplane_*`` samples with the op class as a label).

``--fleet <target>`` switches to the live world console: the merged
cross-rank rollup (:mod:`horovod_tpu.core.fleet`) rendered as a
step-time sparkline, per-op latency quantiles (p50/p99/p999 merged
exactly across ranks), deadline/cancel/ring-full counts, and a
per-rank heatmap with last-beat ages and STALE/DEAD marking. The
target is the rank-0 HTTP endpoint (``/fleet`` picked automatically),
a fleet KV directory (``HVD_FLEET_DIR`` — readable with no live
process), or a saved report JSON; ``--watch N`` redraws.

``--doctor <target>`` renders the hang doctor's attributed verdict
(:mod:`horovod_tpu.core.doctor`): the target is a live rank's HTTP
endpoint (``/doctor`` picked automatically — triggers an on-demand
diagnosis), a flight-dump directory (offline diagnosis over the
embedded inspect tables — works on a dead world), or a saved verdict /
single dump JSON file."""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Tuple

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{([^}]*)\})?\s+(-?[0-9.eE+\-infa]+)$")

# The hang doctor's classification vocabulary as this consumer renders
# it, in attribution-priority order. Machine-diffed against
# ``VERDICT_KINDS`` in core/doctor.py by hvdcheck rule ``parity-doctor``
# — a kind renamed on either side breaks the other's rendering, so the
# analysis names the skew instead of a dashboard showing "unknown".
_DOCTOR_KINDS = (
    "dead_peer",
    "draining",
    "overload",
    "missing_submitter",
    "metadata_mismatch",
    "slow_executor",
    "kv_degraded",
)


def parse_prometheus(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse exposition text into (name, labels, value) samples. Ignores
    comments/TYPE lines and anything unparseable (forward compatible)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        labels: Dict[str, str] = {}
        if labels_raw:
            for part in labels_raw.split(","):
                if "=" in part:
                    k, v = part.split("=", 1)
                    labels[k.strip()] = v.strip().strip('"')
        try:
            out.append((name, labels, float(value)))
        except ValueError:
            continue
    return out


def render(samples: List[Tuple[str, Dict[str, str], float]]) -> str:
    """Human table of parsed samples, histogram buckets folded to a
    count+mean line (the full distribution stays in the file)."""
    if not samples:
        return "no samples"
    rows = []
    hist: Dict[str, Dict[str, float]] = {}
    for name, labels, value in samples:
        if name.endswith("_bucket"):
            continue  # summarized via _sum/_count below
        if name.endswith(("_sum", "_count")):
            base = name.rsplit("_", 1)[0]
            hist.setdefault(base, {})[name.rsplit("_", 1)[1]] = value
            continue
        label = name
        if labels:
            label += "{" + ",".join(f"{k}={v}"
                                    for k, v in sorted(labels.items())) + "}"
        rows.append((label, f"{value:g}"))
    for base, parts in sorted(hist.items()):
        n = parts.get("count", 0)
        if "sum" not in parts:
            # Not a histogram pair: a Ring exports <name>_count (+ _last/
            # _mean gauges printed above) with no _sum — folding it into
            # a fake "mean=0" row would contradict the real mean beside
            # it.
            rows.append((base + "_count", f"{n:g}"))
            continue
        mean = parts["sum"] / n if n else 0.0
        rows.append((base, f"n={n:g} mean={mean:.6g}"))
    width = max(len(r[0]) for r in rows)
    return "\n".join(f"{label:{width}s} {value:>18s}"
                     for label, value in sorted(rows))


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 40) -> str:
    """Unicode block sparkline of the last ``width`` values (the
    step-time strip at the top of the fleet console)."""
    vals = [v for v in values[-width:] if isinstance(v, (int, float))]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * len(_SPARK)))] for v in vals)


def render_fleet(report: dict) -> str:
    """Human console of a fleet rollup (``hvd.fleet_report()`` /
    ``GET /fleet`` / ``core.fleet.report_from_dir``): world line,
    step-time sparkline, per-op latency quantiles, deadline/cancel
    counts, and the per-rank heatmap with last-beat ages and
    STALE/DEAD marking."""
    lines: List[str] = []
    marks = []
    if report.get("stale"):
        marks.append(f"STALE={report['stale']}")
    if report.get("dead"):
        marks.append(f"DEAD={report['dead']}")
    lines.append(
        f"world: size={report.get('size', 0)} "
        f"epoch={report.get('epoch', 0)} "
        f"generation={report.get('generation', 0)}"
        + (" " + " ".join(marks) if marks else ""))
    doc = report.get("doctor")
    if doc and doc.get("kind"):
        # The hang doctor's blamed-tensor line: verdict kind + the
        # tensor/ranks it attributed (core/doctor.py, folded through
        # the fleet snapshots).
        lines.append(
            f"doctor: {doc['kind']}"
            + (f" tensor='{doc['tensor']}'" if doc.get("tensor") else "")
            + (f" rank(s) {doc['ranks']}" if doc.get("ranks") else ""))
    step = report.get("step") or {}
    strip = sparkline(step.get("sparkline") or [])
    if strip:
        last = (step.get("sparkline") or [None])[-1]
        lines.append(f"step_s: {strip}  last={last:.4g}"
                     if isinstance(last, (int, float))
                     else f"step_s: {strip}")
    ops = report.get("ops") or {}
    if ops:
        lines.append("op          count     p50_us      p99_us     p999_us")
        for op, q in sorted(ops.items()):
            lines.append(
                f"{op:<10s} {q.get('count', 0):>6} "
                f"{_fmt_us(q.get('p50_us')):>10s} "
                f"{_fmt_us(q.get('p99_us')):>11s} "
                f"{_fmt_us(q.get('p999_us')):>11s}")
    phases = report.get("phases") or {}
    if phases:
        lines.append("phase: " + "  ".join(
            f"{name} p50={_fmt_us(q.get('p50_us'))}us"
            for name, q in sorted(phases.items())))
    classes = report.get("classes") or {}
    if classes:
        lines.append("class: " + "  ".join(
            f"{cls} p50={_fmt_us(q.get('p50_us'))}us "
            f"p99={_fmt_us(q.get('p99_us'))}us"
            for cls, q in sorted(classes.items())))
    dl = report.get("deadline") or {}
    lines.append(
        f"deadline: exceeded={dl.get('exceeded', 0):g} "
        f"cancelled={dl.get('cancelled', 0):g} "
        f"ring_full={dl.get('ring_full', 0):g}")
    adm = report.get("admission") or {}
    if adm:
        infl = adm.get("inflight") or {}
        sat = adm.get("saturated_ranks") or {}
        lines.append(
            f"admission: rejected={adm.get('rejected', 0):g} "
            f"shed={adm.get('shed', 0):g} inflight="
            + "/".join(f"{infl.get(c, 0):g}"
                       for c in ("high", "normal", "low"))
            + (" SATURATED=" + ",".join(
                f"rank{r}:{'+'.join(cls)}"
                for r, cls in sorted(sat.items(),
                                     key=lambda kv: int(kv[0])))
               if sat else ""))
    ranks = report.get("ranks") or {}
    if ranks:
        lines.append(
            "rank  state  beat_age   queue     step_s  health  numerics")
        for r, info in sorted(ranks.items(), key=lambda kv: int(kv[0])):
            verdicts = info.get("numerics")
            lines.append(
                f"{r:>4s}  {info.get('state', '?'):<5s} "
                f"{info.get('age_s', 0):>7.1f}s "
                f"{_fmt_us(info.get('queue_depth')):>7s} "
                f"{_fmt_us(info.get('step_s')):>10s}  "
                f"{str(info.get('health')):<6s}  "
                f"{','.join(verdicts) if verdicts else '-'}")
    return "\n".join(lines)


def _fmt_us(v) -> str:
    return "-" if v is None else f"{v:g}"


def _fleet_report_for(target: str) -> dict:
    """Resolve a ``--fleet`` target into a rollup dict: an ``http://``
    rank-0 endpoint (``/fleet`` is targeted automatically), a fleet KV
    directory (cold-scanned, no process needed), or a JSON report file
    (e.g. a saved ``curl .../fleet`` body)."""
    from urllib.parse import urlparse

    if _is_http(target):
        url = target
        if urlparse(target).path in ("", "/"):
            url = target.rstrip("/") + "/fleet"
        return json.loads(fetch_http(url))
    if os.path.isdir(target):
        from horovod_tpu.core import fleet

        return fleet.report_from_dir(target)
    with open(target) as fh:
        return json.loads(fh.read())


def _doctor_verdict_for(target: str) -> dict:
    """Resolve a ``--doctor`` target into a verdict dict: an ``http://``
    endpoint (``/doctor`` targeted automatically — triggers an on-demand
    diagnosis on that rank), a flight-dump directory (offline diagnosis
    over the embedded inspect tables), a saved verdict JSON, or a single
    flight-dump file."""
    from urllib.parse import urlparse

    if _is_http(target):
        url = target
        if urlparse(target).path in ("", "/"):
            url = target.rstrip("/") + "/doctor"
        return json.loads(fetch_http(url))
    from horovod_tpu.core import doctor

    if os.path.isdir(target):
        return doctor.diagnose_dumps(doctor.flight_dump_paths(target))
    with open(target) as fh:
        payload = json.loads(fh.read())
    if "findings" in payload:
        return payload  # a saved verdict (curl .../doctor body)
    if isinstance(payload.get("doctor"), dict):
        return payload["doctor"]  # a dump with an embedded verdict
    return doctor.diagnose_dumps([target])


def render_doctor(verdict: dict) -> str:
    """Human rendering of a doctor verdict: the attributed headline,
    then every finding grouped in ``_DOCTOR_KINDS`` priority order (a
    kind outside the vocabulary renders loudly as ``unknown-kind`` —
    the parity rule should have caught it first)."""
    lines: List[str] = []
    kind = verdict.get("kind")
    if kind is None:
        lines.append("doctor: no findings — nothing attributable "
                     f"(rank(s) reporting: "
                     f"{verdict.get('ranks_reporting', [])})")
        return "\n".join(lines)
    head = f"doctor: verdict={kind}"
    if verdict.get("tensor"):
        head += f" tensor='{verdict['tensor']}'"
    if verdict.get("ranks"):
        head += f" rank(s) {verdict['ranks']}"
    lines.append(head)
    lines.append(f"  reporting: rank(s) "
                 f"{verdict.get('ranks_reporting', [])} of "
                 f"{verdict.get('nproc', '?')}")
    order = {k: i for i, k in enumerate(_DOCTOR_KINDS)}
    findings = sorted(
        verdict.get("findings") or [],
        key=lambda f: order.get(f.get("kind"), len(order)))
    for f in findings:
        fk = f.get("kind")
        label = fk if fk in order else f"unknown-kind({fk})"
        lines.append(f"  - {label}: {f.get('detail', '')}")
    return "\n".join(lines)


def _is_xplane_dir(target: str) -> bool:
    if not os.path.isdir(target):
        return False
    from horovod_tpu.utils.profiler import trace_files

    try:
        return bool(trace_files(target))
    except Exception:
        return False


def _is_http(target: str) -> bool:
    return target.startswith(("http://", "https://"))


def fetch_http(target: str) -> str:
    """GET the exposition text from an ``HVD_TELEMETRY_PORT`` endpoint.
    A bare ``http://host:port`` targets ``/metrics``; a full path
    (``/metrics``, ``/healthz``) is used verbatim. Error statuses with a
    body are returned, not raised: ``/healthz`` deliberately answers 503
    while a warn-state verdict is live — exactly the moment the payload
    matters most."""
    import urllib.error
    import urllib.request
    from urllib.parse import urlparse

    url = target
    if urlparse(target).path in ("", "/"):
        url = target.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as exc:
        body = exc.read().decode("utf-8", "replace")
        if body:
            return body
        raise


def xplane_samples(data: dict) -> List[Tuple[str, Dict[str, str], float]]:
    """Flatten an :func:`~horovod_tpu.utils.xplane.hbm_json` dict into
    exposition-shaped samples (``xplane_*`` names, the op class as a
    label) so ``--json`` is shape-identical with the other sources."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for key, val in data.items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out.append((f"xplane_{key}", {}, float(val)))
    # Per-dtype byte split (HBM diet round 2): the bf16-vs-f32 audit
    # columns, dtype as a label like the op class.
    for dt, val in sorted((data.get("bytes_by_dtype_per_step")
                           or {}).items()):
        if isinstance(val, (int, float)):
            out.append(("xplane_bytes_per_step", {"dtype": dt},
                        float(val)))
    for cls, fields in sorted((data.get("classes") or {}).items()):
        for f in ("ms", "bytes"):
            if isinstance(fields.get(f), (int, float)):
                out.append((f"xplane_class_{f}", {"class": cls},
                            float(fields[f])))
        for dt, val in sorted((fields.get("by_dtype") or {}).items()):
            if isinstance(val, (int, float)):
                out.append(("xplane_class_dtype_bytes",
                            {"class": cls, "dtype": dt}, float(val)))
    return out


def _envelope(source: str, target: str,
              samples: List[Tuple[str, Dict[str, str], float]],
              doctor: dict = None) -> dict:
    env = {"source": source, "target": target,
           "samples": [{"name": n, "labels": l, "value": v}
                       for n, l, v in samples]}
    if doctor is not None:
        # The hang doctor's verdict rides INSIDE the one-envelope shape
        # (never replaces it): dashboards keyed on {source, target,
        # samples} keep parsing, doctor-aware ones read env["doctor"].
        env["doctor"] = doctor
    return env


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.stats",
        description="Query horovod_tpu telemetry: an HVD_TELEMETRY_FILE "
                    "exposition file, an http://host:port endpoint "
                    "(HVD_TELEMETRY_PORT), an xplane capture dir, or "
                    "'live'.")
    ap.add_argument("target",
                    help="exposition file | http://host:port | xplane "
                         "capture dir | 'live'")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one envelope shape "
                         "for every source)")
    ap.add_argument("--fleet", action="store_true",
                    help="render the merged world rollup instead of "
                         "one rank's registry: target is the rank-0 "
                         "http endpoint (/fleet), a fleet KV directory "
                         "(HVD_FLEET_DIR — works with no live "
                         "process), or a saved report JSON file")
    ap.add_argument("--doctor", action="store_true",
                    help="render the hang doctor's attributed verdict: "
                         "target is a live rank's http endpoint "
                         "(/doctor — on-demand diagnosis), a "
                         "flight-dump directory (offline, works on a "
                         "dead world), or a saved verdict/dump JSON")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=None,
                    help="redraw the report every N seconds (exposition "
                         "file, http target or 'live'); Ctrl-C exits "
                         "cleanly")
    ap.add_argument("--steps", type=int, default=1,
                    help="steps in an xplane capture window (per-step "
                         "attribution)")
    args = ap.parse_args(argv)

    def render_once() -> int:
        if args.doctor:
            try:
                verdict = _doctor_verdict_for(args.target)
            except Exception as exc:
                print(f"cannot build doctor view from {args.target}: "
                      f"{exc}")
                return 1
            print(json.dumps(_envelope("doctor", args.target, [],
                                       doctor=verdict))
                  if args.json else render_doctor(verdict))
            return 0
        if args.fleet:
            try:
                report = _fleet_report_for(args.target)
            except Exception as exc:
                print(f"cannot build fleet view from {args.target}: {exc}")
                return 1
            print(json.dumps(report) if args.json
                  else render_fleet(report))
            return 0
        if args.target == "live":
            from horovod_tpu.core import telemetry

            if args.json:
                print(json.dumps(_envelope(
                    "live", "live",
                    parse_prometheus(telemetry.prometheus()))))
            else:
                print(telemetry.report())
            return 0
        if _is_http(args.target):
            try:
                text = fetch_http(args.target)
            except Exception as exc:
                print(f"cannot fetch {args.target}: {exc}")
                return 1
            samples = parse_prometheus(text)
            if args.json:
                if not samples and text.lstrip().startswith("{"):
                    # A /healthz target already answers machine-readable
                    # JSON (the sentinel health document, not metric
                    # samples) — pass it through instead of burying it
                    # in an empty-samples envelope.
                    print(text.strip())
                else:
                    print(json.dumps(_envelope("http", args.target,
                                               samples)))
            elif samples:
                print(render(samples))
            else:
                # A /healthz target returns JSON, not exposition text —
                # show it as-is rather than "no samples".
                print(text.rstrip("\n"))
            return 0
        if _is_xplane_dir(args.target):
            from horovod_tpu.utils import xplane

            if args.json:
                data = xplane.hbm_json(args.target, steps=args.steps)
                print(json.dumps(_envelope("xplane", args.target,
                                           xplane_samples(data))))
            else:
                print(xplane.hbm_report(args.target, steps=args.steps))
            return 0
        try:
            with open(args.target) as fh:
                text = fh.read()
        except OSError as exc:
            print(f"cannot read {args.target}: {exc}")
            return 1
        samples = parse_prometheus(text)
        if args.json:
            print(json.dumps(_envelope("file", args.target, samples)))
        else:
            print(render(samples))
        return 0

    # --watch: the poor-man's dashboard — file, http and 'live' targets
    # (stalls can be watched as they develop, from outside the process
    # via the HTTP endpoint). Ctrl-C is the documented way out — exit
    # cleanly, not with a KeyboardInterrupt stack trace.
    try:
        while True:
            rc = render_once()
            if args.watch is None or rc != 0:
                return rc
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
