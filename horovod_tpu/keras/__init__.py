"""High-level trainer frontend — the role horovod.keras plays in the
reference (reference: horovod/keras/__init__.py, horovod/_keras/__init__.py).

Keras itself is not the compute stack on TPU; the equivalent surface is a
compiled flax/optax ``Trainer`` with the same integration points the
reference patches into Keras: a distributed optimizer wrapping gradient
reduction (reference: _keras/__init__.py:20-70 create_distributed_optimizer),
the callback suite (:mod:`horovod_tpu.keras.callbacks`), and
``load_model``/``save_model`` that round-trip the *wrapped* optimizer state
(reference: _keras/__init__.py:93-109).
"""

from __future__ import annotations

import collections.abc
import math
import os
import queue as _queue
import threading
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.common.topology import (  # noqa: F401
    HorovodInternalError, init, shutdown, is_initialized, size, rank,
    local_size, local_rank, cross_size, cross_rank, mesh, num_processes,
)
from horovod_tpu.jax import (
    DistributedOptimizer,  # noqa: F401 — same wrapper (reference binds P9 to keras)
    Compression,  # noqa: F401
    allreduce_pytree,
    broadcast_pytree,
    canonical_state_dtype as _canonical_state_dtype,
    cast_resident_params as _cast_resident_params,
    jit as _hvd_jit,
    sharded_state_specs as _sharded_state_specs,
    state_storage as _state_storage,
)
from horovod_tpu.jax import allreduce as _allreduce
from horovod_tpu.jax import numerics as _jnumerics
from horovod_tpu.jax.sharded import (
    drift_ulp as _drift_ulp,
    has_master_shards as _has_master_shards,
)
from horovod_tpu.core import elastic as _elastic
from horovod_tpu.core import numerics as _numerics
from horovod_tpu.core import preempt as _preempt
from horovod_tpu.core import sentinel as _sentinel
from horovod_tpu.core import telemetry as _tele
from horovod_tpu.core import timeline as _tl
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.ops import collectives as _ops
from horovod_tpu.ops.collectives import HVD_AXIS
from horovod_tpu.utils import checkpoint as _ckpt

import logging as _logging

_ELASTIC_LOG = _logging.getLogger("horovod_tpu.elastic.trainer")


def _default_loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


class _LazyLogs(collections.abc.MutableMapping):
    """Per-batch logs whose values stay device-resident until read.

    ``fit`` hands this to ``on_batch_end`` instead of a plain float dict
    so the training loop never blocks on a device fetch it doesn't need
    (the fetch would serialize the pipelined step dispatch). Reading a
    value — ``logs["loss"]``, ``.get``, ``.items()``, ``dict(logs)``,
    ``{**logs}``, ``logs.copy()`` — yields Python floats, so callbacks
    that json-serialize, type-check, copy, or accumulate keep the
    classic Keras contract; each value read costs one host round trip.
    Writes (``logs["lr"] = ...``, ``.update``) land in a host-side
    overlay that shadows the device value and, for the epoch's last
    batch, flows into the epoch logs/history — the same visibility a
    plain dict gave. Deliberately NOT a dict subclass: CPython's
    ``dict(d)``/``{**d}`` fast path would bypass ``__getitem__`` and
    leak device arrays.
    """

    def __init__(self, raw):
        self._raw = raw      # device-resident step outputs
        self._host = {}      # callback-written values (host objects)

    def __getitem__(self, k):
        if k in self._host:
            return self._host[k]
        return float(self._raw[k])

    def __setitem__(self, k, v):
        self._host[k] = v

    def __delitem__(self, k):
        found = k in self._host or k in self._raw
        if not found:
            raise KeyError(k)
        # Remove from BOTH layers: deleting a shadowed key must not
        # resurrect the underlying device value (plain-dict contract).
        self._host.pop(k, None)
        self._raw.pop(k, None)

    def __iter__(self):
        yield from self._raw
        for k in self._host:
            if k not in self._raw:
                yield k

    def __contains__(self, k):
        # Mapping's default is `self[k]` — a blocking device fetch for a
        # mere membership guard (`if "loss" in logs:`). Keep it free.
        return k in self._host or k in self._raw

    def __len__(self):
        return sum(1 for _ in self)

    def copy(self) -> dict:
        # Best-effort float coercion of callback-written values too: the
        # pre-_LazyLogs epoch logs applied float() to every value, and
        # history/json consumers rely on host floats (values float()
        # rejects are kept as written).
        out = {}
        for k in self:
            v = self[k]
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                out[k] = v
        return out

    def __repr__(self):
        return repr(self.copy())


class _SacrificialDispatcher:
    """Runs closures on a worker thread so the caller can ABANDON a call
    that wedged (elastic worlds, core/elastic.py).

    A peer dying at the wrong instant can block the runtime's dispatch
    path itself, synchronously, inside C++ — past any point where
    Python-level recovery could run. Dispatching from a sacrificial
    thread keeps the main thread free to observe the death verdict and
    reconfigure; a wedged worker is simply leaked along with the
    poisoned backend (it blocks with the GIL released, so it costs a
    thread, not the process)."""

    def __init__(self):
        self._req: "_queue.Queue" = _queue.Queue()
        self._res: "_queue.Queue" = _queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-elastic-dispatch", daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            fn = self._req.get()
            try:
                self._res.put(("ok", fn()))
            except BaseException as exc:  # surfaced to the caller
                self._res.put(("exc", exc))

    def call(self, fn, poll: Callable[[], None]):
        """Run ``fn()`` on the worker; ``poll()`` runs every few ms and
        may raise (the death-verdict escape) — the in-flight call is
        then abandoned and this dispatcher must be discarded."""
        self._req.put(fn)
        while True:
            try:
                kind, val = self._res.get(timeout=0.005)
            except _queue.Empty:
                poll()
                continue
            if kind == "exc":
                raise val
            return val


class Trainer:
    """Compiled data-parallel fit/evaluate loop over the world mesh.

    The training step (forward, backward, fused gradient allreduce,
    optimizer update) is one XLA program; callbacks run host-side between
    steps, mirroring Keras's contract in the reference.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable = _default_loss,
        metrics: Sequence[str] = ("accuracy",),
        distributed: bool = True,
        compression=Compression.none,
        rng: int = 0,
        fused_update: bool = False,
        sharded_update: bool = False,
        state_dtype=None,
    ):
        """``fused_update``/``sharded_update`` forward to
        :func:`horovod_tpu.jax.DistributedOptimizer` — ``sharded_update``
        runs the optimizer on a 1/N shard of params/state per chip
        (reduce-scatter + all-gather; per-coordinate transforms only) and
        lays the optimizer state out ``P('hvd')`` in the compiled step.

        ``state_dtype='bf16'`` (HBM diet round 2): resident parameters
        are cast to bf16 at :meth:`build` (batch-norm statistics stay
        f32), the optimizer state is stored reduced, and — with
        ``sharded_update`` — f32 master weights ride the sharded state
        as each chip's 1/N shard; :meth:`load` rebuilds the bf16
        residents bitwise from the persisted masters."""
        self.model = model
        self._sharded_update = bool(sharded_update and distributed)
        self._state_dtype = _canonical_state_dtype(state_dtype)
        if distributed:
            optimizer = DistributedOptimizer(optimizer,
                                             compression=compression,
                                             fused_update=fused_update,
                                             sharded_update=sharded_update,
                                             state_dtype=state_dtype)
        elif self._state_dtype is not None:
            # Non-distributed trainer: the storage policy still applies
            # (no masters — see docs/troubleshooting.md on drift).
            optimizer = _state_storage(optimizer, self._state_dtype)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.metrics = tuple(metrics)
        self.rng = jax.random.PRNGKey(rng)
        self.params = None
        self.batch_stats = {}
        self.opt_state = None
        self.lr_scale = 1.0
        self.steps_per_epoch: Optional[int] = None
        self._train_step = None
        self._eval_step = None
        self._epoch = 0
        self._gstep = 0  # global step counter (numerics attribution)
        self._elastic_dispatcher: Optional[_SacrificialDispatcher] = None
        # Elastic: the previous step's donated state, parked until the
        # NEXT dispatcher call releases it on the worker thread —
        # releasing donated buffers can block inside a dead runtime, so
        # the main thread must never hold their last reference.
        self._elastic_graveyard: list = []

    # -- state ---------------------------------------------------------------

    def build(self, x_sample):
        """Initialize parameters from one (host) batch sample."""
        if self.params is not None:
            return
        self.rng, key = jax.random.split(self.rng)
        variables = self.model.init(
            {"params": key, "dropout": key}, jnp.asarray(x_sample), False)
        self.params = variables["params"]
        # Resident params at the policy width (identity when off); the
        # f32 masters (sharded_update) derive from these in
        # optimizer.init, so cast BEFORE init. BN statistics are outside
        # the param tree and stay f32.
        self.params = _cast_resident_params(self.params, self._state_dtype)
        self.batch_stats = dict(variables.get("batch_stats", {}))
        self.opt_state = self.optimizer.init(self.params)

    def broadcast_state(self, root_rank: int = 0):
        """Reference: BroadcastGlobalVariablesCallback on_train_begin.

        Hardened (r4, found by the smoke tier): the state is pulled to
        HOST before broadcasting, and the result is drained before
        returning. A second fit() used to hand the broadcast mesh-
        sharded train-step outputs with async work still in flight —
        the eager broadcast programs then recompiled for the new input
        layouts and their 8-device all-reduce wedged with only 5
        executions launched (XLA:CPU aborts the rendezvous after 40 s).
        ``device_get`` is itself a hard sync, and host leaves make every
        fit take the identical first-fit program path — no layout-driven
        recompiles, nothing concurrent in flight. The broadcast runs
        once per fit, so the host round trip is startup cost, not step
        cost."""
        host = jax.device_get((self.params, self.batch_stats,
                               self.opt_state))
        params, batch_stats, opt_state = host
        self.params = broadcast_pytree(params, root_rank)
        if batch_stats:
            self.batch_stats = broadcast_pytree(batch_stats, root_rank)
        self.opt_state = broadcast_pytree(opt_state, root_rank)
        jax.block_until_ready((self.params, self.batch_stats,
                               self.opt_state))
        # Consistency anchor (core/numerics.py): right after the sync
        # broadcast every process MUST digest identically — an eager
        # drain point, so the allgather is safe, and a mismatch here is
        # attributed before training compounds it.
        if _numerics.enabled() and num_processes() > 1:
            self.check_consistency(tag="broadcast_state")

    def check_consistency(self, tag: str = "params"):
        """Cross-rank state-consistency digest (core/numerics.py): every
        process digests its parameter/batch-stats buckets (crc32 + sum +
        nonfinite count per dtype), the digests are allgathered, and a
        mismatch yields an attributed ``diverged`` verdict + flight dump
        on EVERY process naming the deviating rank and bucket. A
        collective — call in lockstep on every process (fit calls it at
        epoch boundaries and after :meth:`broadcast_state`)."""
        return _numerics.check_consistency(
            {"params": self.params, "batch_stats": self.batch_stats},
            tag=tag, step=self._gstep)

    def _note_numerics(self, health):
        """Per-step host intake on the HVD_NUMERICS_EVERY cadence (every
        step under halt — a delayed check could not raise before the
        next poisoned update). The device_get is the only forced fetch
        the numerics layer adds to the loop, and only on checked
        steps."""
        pol = _numerics.policy()
        if pol == "off":
            return
        every = _numerics.check_every()
        if pol == "halt" or self._gstep % every == 0:
            _numerics.note_step_health(jax.device_get(health),
                                       step=self._gstep,
                                       origin="trainer")
        if (self._gstep % every == 0
                and _has_master_shards(self.opt_state)):
            # bf16 drift gauge: master↔resident max ULP per bucket (the
            # automated troubleshooting-ladder audit). Globalizing the
            # master shards is a collective in multi-controller worlds —
            # the step cadence is lockstep across processes.
            _numerics.note_drift(
                _drift_ulp(self.opt_state, self.params),
                step=self._gstep)

    def set_lr_scale(self, scale: float, momentum_correction: bool = False):
        """Scale the effective learning rate (callbacks drive this). With
        ``momentum_correction``, SGD momentum buffers are rescaled by
        ``new/old`` (Goyal et al.; reference: _keras/callbacks.py:104-113)."""
        old, self.lr_scale = self.lr_scale, float(scale)
        if momentum_correction and old != self.lr_scale and old != 0:
            factor = self.lr_scale / old
            self.opt_state = jax.tree_util.tree_map(
                lambda s: (s._replace(
                    trace=jax.tree_util.tree_map(
                        lambda t: t * factor, s.trace))
                    if isinstance(s, optax.TraceState) else s),
                self.opt_state,
                is_leaf=lambda s: isinstance(s, optax.TraceState))

    # -- compiled steps ------------------------------------------------------

    def _build_steps(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        want_acc = "accuracy" in self.metrics

        def forward(params, batch_stats, x, y, train, dropout_key):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            kwargs = {"rngs": {"dropout": dropout_key}} if train else {}
            if batch_stats and train:
                logits, mutated = model.apply(variables, x, train,
                                              mutable=["batch_stats"],
                                              **kwargs)
                new_bs = mutated["batch_stats"]
            else:
                logits = model.apply(variables, x, train, **kwargs)
                new_bs = batch_stats
            return loss_fn(logits, y), (logits, new_bs)

        def metrics_of(loss, logits, y):
            logs = {"loss": _allreduce(loss)}
            if want_acc:
                acc = jnp.mean(jnp.argmax(logits, -1) == y)
                logs["accuracy"] = _allreduce(acc)
            return logs

        # Sharded update: each chip carries its 1/N block of the flat
        # optimizer-state buffers instead of a replicated copy.
        ospec = (_sharded_state_specs(self.opt_state)
                 if self._sharded_update else P())

        # donate_argnums: params/batch_stats/opt_state are rebound to the
        # step's outputs every batch, so XLA may update them in place —
        # without donation every param-sized buffer pays a copy-on-update
        # each step. Callbacks run AFTER the rebind and therefore always
        # see live buffers.
        # Master-shard layout (state_dtype + sharded_update): the f32
        # masters advance INSIDE opt.update and the returned tree is only
        # a re-anchored resident delta, so the post-hoc `updates *
        # lr_scale` below would be overwritten by the next step's
        # re-anchor — the scale must ride into the epilogue instead
        # (shard_update's reserved `lr_scale` extra arg).
        scale_inside = (self._state_dtype is not None
                        and self._sharded_update)

        # Numerics observatory (core/numerics.py): the optimizer wrapper
        # computes in-step gradient health and stashes it mid-trace;
        # collect it HERE (same trace) and return it device-resident in
        # the logs — the host fetches on the HVD_NUMERICS_EVERY cadence.
        # Read at build time: the compiled program either carries the
        # stats or (policy off) lowers to the identical pre-numerics HLO.
        num_on = _numerics.enabled()

        @_hvd_jit(in_specs=(P(), P(), ospec, P(HVD_AXIS), P(HVD_AXIS), P(),
                            P()),
                  out_specs=(P(), P(), ospec, P()),
                  donate_argnums=(0, 1, 2))
        def train_step(params, batch_stats, opt_state, x, y, lr_scale,
                       dropout_key):
            (loss, (logits, new_bs)), grads = jax.value_and_grad(
                forward, has_aux=True)(params, batch_stats, x, y, True,
                                       dropout_key)
            prev_state = opt_state
            if scale_inside:
                updates, opt_state = opt.update(grads, opt_state, params,
                                                lr_scale=lr_scale)
            else:
                updates, opt_state = opt.update(grads, opt_state, params)
                updates = jax.tree_util.tree_map(lambda u: u * lr_scale,
                                                 updates)
            logs = metrics_of(loss, logits, y)
            if num_on:
                health = _jnumerics.collect_traced()
                if health is None:
                    # Fallback (the optimizer wrapper did not run — e.g.
                    # distributed=False): gradient health straight from
                    # the local grads, psum'd over the rank axis when one
                    # is bound so a NaN on ANY rank is seen identically
                    # everywhere (host-side reads of a replicated output
                    # only ever see device 0's tile). Under halt the
                    # guard must run HERE — the wrapper's guard did not.
                    ax = (_ops.rank_axes()
                          if _ops.in_spmd(loss) else None)
                    stats = _jnumerics.tree_stats(grads, ax=ax)
                    per_rank = (_jnumerics.per_rank_nonfinite(grads, ax)
                                if ax is not None else None)
                    health = _jnumerics.health_of(stats, per_rank)
                    if _numerics.policy() == "halt":
                        finite = _jnumerics.all_finite(stats)
                        updates = _jnumerics.guard_updates(finite,
                                                           updates)
                        opt_state = _jnumerics.guard_state(
                            finite, opt_state, prev_state)
                new_params = optax.apply_updates(params, updates)
                # Masterless-drift gauge input (fused.state_storage
                # caveat): update/param norm ratio per step.
                health["update_norm"] = _jnumerics.norm(updates)
                health["param_norm"] = _jnumerics.norm(new_params)
                logs["_numerics"] = health
            else:
                new_params = optax.apply_updates(params, updates)
            return new_params, new_bs, opt_state, logs

        @_hvd_jit(in_specs=(P(), P(), P(HVD_AXIS), P(HVD_AXIS)),
                  out_specs=P())
        def eval_step(params, batch_stats, x, y):
            loss, (logits, _) = forward(params, batch_stats, x, y, False,
                                        jax.random.PRNGKey(0))
            return metrics_of(loss, logits, y)

        self._train_step, self._eval_step = train_step, eval_step

    # -- data plumbing -------------------------------------------------------

    def _shard(self, arr):
        """Place this controller's host batch over its local chips, forming
        the (global_batch, ...) mesh-sharded array."""
        m = mesh()
        nloc = local_size()
        per = arr.shape[0] // nloc
        shards = [
            jax.device_put(arr[i * per:(i + 1) * per], d)
            for i, d in enumerate(m.local_mesh.devices.flat)
        ]
        shape = (per * size(),) + arr.shape[1:]
        return jax.make_array_from_single_device_arrays(
            shape, NamedSharding(m, P(HVD_AXIS)), shards)

    def _batches(self, x, y, batch_size, shuffle, seed):
        n_local = batch_size * local_size()
        steps = len(x) // n_local
        idx = np.arange(steps * n_local)
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        for s in range(steps):
            sel = idx[s * n_local:(s + 1) * n_local]
            yield self._shard(x[sel]), self._shard(y[sel])

    # -- public API ----------------------------------------------------------

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1,
            callbacks: Sequence = (), validation_data=None,
            initial_epoch: int = 0, shuffle: bool = True,
            verbose: int = 0) -> dict:
        """Train; returns a history dict of per-epoch logs. ``x``/``y`` are
        this process's host arrays; ``batch_size`` is per chip (global
        batch = batch_size * size), matching the reference examples'
        convention.

        ``on_batch_end`` receives a :class:`_LazyLogs` mapping — values
        are fetched from device only when read (reads yield Python
        floats; writes land in a host overlay that reaches the epoch
        history). ``on_epoch_end`` receives a plain float dict.

        With ``HVD_ELASTIC=1`` (core/elastic.py) the loop survives rank
        loss: a death verdict raises out of the epoch, the world is
        reconfigured (mesh over survivors, fresh compiled steps), the
        newest elastic checkpoint is restored and broadcast, and
        training continues at the restored epoch — a recompile, not a
        crash. Epoch boundaries write the elastic checkpoint and honor
        supervisor restart requests (rejoin/regrow)."""
        x, y = np.asarray(x), np.asarray(y)
        x_sample = x[:batch_size * max(local_size(), 1)]
        self.build(x_sample)
        if self._train_step is None:
            self._build_steps()
        self.steps_per_epoch = len(x) // (batch_size * local_size())
        for cb in callbacks:
            cb.set_trainer(self)
        history: dict = {}
        for cb in callbacks:
            cb.on_train_begin()
        # Graceful preemption intake (core/preempt.py): SIGTERM — the
        # TPU maintenance/eviction signal — is polled at every batch
        # boundary; when it lands, the epoch raises and the ladder below
        # drains the step, checkpoints, barriers, and exits 0.
        _preempt.install()
        elastic_on = _elastic.active()
        if elastic_on:
            # A new fit revokes any standing completion mark (peers
            # resume leasing us), and train end announces completion so
            # the last rank to finish is not verdicted dead.
            _elastic.get_world().announce_active()
        epoch = initial_epoch
        while epoch < epochs:
            try:
                self._run_epoch(epoch, x, y, batch_size, shuffle,
                                callbacks, validation_data, history,
                                verbose, elastic_on)
            except _preempt.PreemptRequested:
                self._graceful_preempt(epoch)  # exits 0; no return
            except _elastic.WorldChanged:
                if not elastic_on:
                    raise
                _ELASTIC_LOG.warning(
                    "elastic recovery: death verdict observed at epoch "
                    "%d; reconfiguring", epoch)
                epoch = self._elastic_recover(x_sample)
                # Recovery replays every epoch since the newest
                # checkpoint: drop the replayed epochs' history entries
                # so each index keeps exactly one record. (Epoch-indexed
                # callbacks still observe a replayed epoch twice — the
                # documented cost of checkpoint-granularity recovery.)
                for k in history:
                    del history[k][max(0, epoch - initial_epoch):]
                continue
            epoch += 1
        for cb in callbacks:
            cb.on_train_end()
        if elastic_on:
            _elastic.get_world().announce_done()
        return history

    def _run_epoch(self, epoch, x, y, batch_size, shuffle, callbacks,
                   validation_data, history, verbose, elastic_on):
        self._epoch = epoch
        for cb in callbacks:
            cb.on_epoch_begin(epoch)
        lazy = _LazyLogs({})
        batches = self._batches(x, y, batch_size, shuffle, seed=epoch)
        nxt, b = next(batches, None), 0
        prev_step = None  # elastic: last step's device loss (readiness)
        while nxt is not None:
            if _preempt.requested():
                # Batch boundary: the last dispatched step is the one
                # the ladder drains; no new work is dispatched into a
                # world about to be evicted.
                raise _preempt.PreemptRequested()
            xb, yb = nxt
            for cb in callbacks:
                cb.on_batch_begin(b)
            if elastic_on:
                # Never dispatch into a world with a death verdict (the
                # collective would wedge behind the dead peer), and keep
                # the in-flight window at ONE step: the runtime's
                # dispatch queue is finite, and a deeper backlog behind
                # a dead peer's collective blocks the dispatch call
                # itself — past any point where recovery could run. The
                # one-step lag keeps the device busy (step N executes
                # while the host preps N+1); only the await's poll
                # granularity is added latency.
                self._elastic_guard()
                self._elastic_await(prev_step)
            t_step = time.perf_counter()
            # The split stays on the main thread (tiny, non-donating —
            # cannot wedge) so the worker closure below never mutates
            # trainer state: an abandoned call that unwedges after
            # recovery must have nothing to clobber.
            self.rng, dk = jax.random.split(self.rng)

            # Everything the step touches is bound at CLOSURE CREATION
            # (default args), not call time: an abandoned worker that
            # unwedges after recovery then re-dispatches only into the
            # OLD world's objects — it can never reach the rebuilt step
            # or the recovered state.
            def _one_step(xb=xb, yb=yb, dk=dk, step_fn=self._train_step,
                          params=self.params, bs=self.batch_stats,
                          opt=self.opt_state,
                          grave=self._elastic_graveyard):
                if elastic_on:
                    # Release the PREVIOUS step's parked donated state
                    # here, on the abandonable worker: dropping buffers
                    # donated into an execution wedged behind a dead
                    # peer blocks inside the runtime.
                    grave.clear()
                return step_fn(params, bs, opt, xb, yb,
                               jnp.float32(self.lr_scale), dk)

            try:
                out = (self._elastic_call(_one_step) if elastic_on
                       else _one_step())
                if elastic_on:
                    # Park-then-rebind: the old references stay alive in
                    # the graveyard, so these assignments never run a
                    # (possibly blocking) destructor on the main thread
                    # — and the worker never mutates trainer state, so
                    # an abandoned call that completes later cannot
                    # clobber a recovered world.
                    self._elastic_graveyard.append(
                        (self.params, self.batch_stats, self.opt_state))
                self.params, self.batch_stats, self.opt_state, logs = out
            except Exception as exc:
                self._elastic_translate(exc, elastic_on)
                raise
            # Compiled-path telemetry: dispatch time of the whole step
            # program (execution is async — the ring records the host
            # cost of handing work to the runtime; wall step time
            # shows up in the inter-dispatch cadence).
            t_step = time.perf_counter() - t_step
            _tele.REGISTRY.counter("trainer.steps").inc()
            _tele.REGISTRY.ring("trainer.step_s").push(t_step)
            # Performance sentinel: the wall step time feeds the
            # trainer watchdog (anomaly -> flight dump + bounded
            # capture + attributed verdict) and drives periodic
            # auto-capture (HVD_PROFILE_DIR) — see core/sentinel.py.
            _sentinel.observe_step(t_step, origin="trainer")
            # Prefetch: the step above dispatched asynchronously;
            # pulling the next batch NOW overlaps its host->device
            # transfers with the running step (the role tf.data
            # prefetching plays for reference keras users — without
            # it, per-batch feed+fetch serializes with compute:
            # together with the device-resident logs below, measured
            # 2.1x on the tunneled chip, docs/benchmarks.md).
            nxt = next(batches, None)
            # Numerics: pop the device-resident health dict BEFORE
            # the logs proxy (callbacks must not see — or float() —
            # the per-rank vector); checked on the numerics cadence.
            self._gstep += 1
            health = (logs.pop("_numerics", None)
                      if isinstance(logs, dict) else None)
            if health is not None:
                if elastic_on:
                    # The intake device_gets this step's health — a
                    # blocking fetch that wedges on a step the dead peer
                    # never joins; dispatcher-routed like the step.
                    self._elastic_call(
                        lambda h=health: self._note_numerics(h))
                else:
                    self._note_numerics(health)
            # Batch logs stay device-resident (fetching every batch
            # costs a full host round trip); the proxy converts any
            # value a callback actually reads to a Python float at
            # that moment, so float-expecting callbacks keep working
            # and pay only for what they read.
            if elastic_on and isinstance(logs, dict):
                prev_step = logs.get("loss")
            lazy = _LazyLogs(logs)
            if elastic_on and callbacks:
                # A callback reading lazy logs performs a blocking
                # device fetch of this step's outputs — dispatcher-
                # routed like every other fetch that could wedge behind
                # a dead peer.
                self._elastic_call(
                    lambda b=b, lazy=lazy: [cb.on_batch_end(b, lazy)
                                            for cb in callbacks])
            else:
                for cb in callbacks:
                    cb.on_batch_end(b, lazy)
            b += 1
        # Epoch logs come from the last batch's view INCLUDING any
        # callback writes (plain-dict behavior before _LazyLogs).
        try:
            logs = (self._elastic_epoch_logs(lazy) if elastic_on
                    else lazy.copy())
            # Epoch boundary = eager drain point: report the (already
            # host-visible) loss to the sentinel for perf.jsonl's
            # final_loss column, and run the cross-rank consistency
            # digest when there is more than one controller to diverge.
            if "loss" in logs:
                _sentinel.note_loss(logs["loss"])
            if _numerics.enabled() and num_processes() > 1:
                # A collective: in elastic mode it runs on the
                # sacrificial dispatcher so a peer dying mid-digest
                # cannot wedge the loop past recovery.
                if elastic_on:
                    self._elastic_call(
                        lambda: self.check_consistency(tag="epoch_end"))
                else:
                    self.check_consistency(tag="epoch_end")
            if validation_data is not None:
                # Collectives + blocking metric fetches: dispatcher-
                # routed in elastic mode for the same wedge-proofing as
                # the train step.
                if elastic_on:
                    val = self._elastic_call(
                        lambda: self.evaluate(*validation_data,
                                              batch_size=batch_size))
                else:
                    val = self.evaluate(*validation_data,
                                        batch_size=batch_size)
                logs.update({f"val_{k}": v for k, v in val.items()})
        except Exception as exc:
            self._elastic_translate(exc, elastic_on)
            raise
        for cb in callbacks:
            cb.on_epoch_end(epoch, logs)
        for k, v in logs.items():
            history.setdefault(k, []).append(v)
        if verbose:
            print(f"epoch {epoch}: " +
                  " ".join(f"{k}={v:.4f}" for k, v in logs.items()))
        if elastic_on:
            self._elastic_epoch_boundary(epoch)

    # -- elastic worlds (core/elastic.py) ------------------------------------

    def _elastic_guard(self):
        if _elastic.get_world().world_changed():
            raise _elastic.WorldChanged()

    def _elastic_call(self, fn):
        """Run ``fn`` on the sacrificial dispatcher, polling for a death
        verdict: the call is abandoned (and the dispatcher discarded for
        a fresh one) the moment the world changes under it."""
        if self._elastic_dispatcher is None:
            self._elastic_dispatcher = _SacrificialDispatcher()
        try:
            return self._elastic_dispatcher.call(fn, self._elastic_guard)
        except _elastic.WorldChanged:
            # The in-flight call may be wedged inside the dead world's
            # runtime forever — never reuse this worker.
            self._elastic_dispatcher = None
            raise

    def _elastic_await(self, arr):
        """Bounded-in-flight await: poll one device value's readiness,
        bailing to recovery the moment a death verdict lands. A plain
        blocking fetch would sit inside a collective the dead peer never
        joins; deeper dispatch queues wedge the dispatch call itself."""
        if arr is None:
            return
        is_ready = getattr(arr, "is_ready", None)
        if is_ready is None:
            return
        w = _elastic.get_world()
        while True:
            if w.world_changed():
                raise _elastic.WorldChanged()
            try:
                if is_ready():
                    return
            except Exception:
                return  # errored buffer: the step's own fetch surfaces it
            time.sleep(0.005)

    def _elastic_translate(self, exc: Exception, elastic_on: bool):
        """A step/fetch raised: when a death verdict explains it (or
        arrives within a couple of leases — the runtime error usually
        beats the heartbeat), convert to WorldChanged so fit recovers
        instead of crashing."""
        if isinstance(exc, _elastic.WorldChanged) or not elastic_on:
            return
        w = _elastic.get_world()
        if w.world_changed() or w.await_verdict(_elastic.verdict_wait_s()):
            raise _elastic.WorldChanged() from exc

    def _elastic_epoch_logs(self, lazy) -> dict:
        """Epoch-end fetch that cannot wedge on a dead world: poll the
        device values' readiness, bailing to recovery the moment a death
        verdict lands (a blocking fetch would sit inside a collective
        the dead peer never joins)."""
        w = _elastic.get_world()
        for v in list(lazy._raw.values()):
            is_ready = getattr(v, "is_ready", None)
            if is_ready is None:
                continue
            while True:
                if w.world_changed():
                    raise _elastic.WorldChanged()
                try:
                    if is_ready():
                        break
                except Exception:
                    break  # the copy below surfaces the real error
                time.sleep(0.05)
        return lazy.copy()

    def _elastic_epoch_boundary(self, epoch: int):
        """Elastic bookkeeping at the epoch drain point: write the
        checkpoint recovery resumes from, then honor a pending
        supervisor restart request (rejoin admission / regrow)."""
        d = _elastic.checkpoint_dir()
        if d:
            try:
                # save() globalizes sharded state (a collective) and
                # fetches device buffers — dispatcher-routed for the
                # same wedge-proofing as the step itself.
                self._elastic_call(lambda: self.save(d, step=epoch))
            except Exception as exc:
                self._elastic_translate(exc, True)
                raise
        req = _elastic.get_world().restart_requested()
        if req:
            _elastic.get_world().exit_for_restart(req)

    # -- graceful preemption (core/preempt.py) -------------------------------

    def _graceful_preempt(self, epoch: int):
        """The planned-eviction ladder: finish (or deadline-abort) the
        in-flight step, quiesce the engine (admission closed, /healthz
        ``draining``), write the crash-atomic emergency checkpoint,
        rendezvous with the peers at the drain barrier, journal a
        ``preempted`` note, and exit 0. Every rung is bounded — a rung
        wedged behind a dead peer is abandoned, never waited out (the
        launcher's ``--grace-s`` SIGKILL escalation is the backstop).
        Does not return."""
        why = _preempt.reason() or "preemption requested"
        deadline = _preempt.step_deadline_s()
        _ELASTIC_LOG.warning(
            "graceful preemption (%s): draining the current step, "
            "checkpointing, and exiting cleanly", why)
        state = (self.params, self.batch_stats, self.opt_state)
        drained, _ = _preempt.bounded(
            lambda: jax.block_until_ready(state), deadline,
            "in-flight step drain")
        from horovod_tpu.core import engine as _eng

        _eng.quiesce_engine(min(deadline, 5.0),
                            reason=f"graceful preemption ({why})")
        ckpt_dir = _elastic.checkpoint_dir()
        ckpt_path = None
        if ckpt_dir:
            # Crash-atomic by construction (utils/checkpoint.py: tmp +
            # fsync + rename): an escalated SIGKILL mid-save can never
            # corrupt the newest checkpoint a relaunch resumes from.
            ok, ckpt_path = _preempt.bounded(
                lambda: self.save(ckpt_dir, step=epoch), deadline,
                "emergency checkpoint")
            if not ok:
                _ELASTIC_LOG.error(
                    "graceful preemption: emergency checkpoint did not "
                    "complete; the relaunch resumes from the previous "
                    "one")
        else:
            _ELASTIC_LOG.warning(
                "graceful preemption: no checkpoint dir configured "
                "(HVD_CHECKPOINT_DIR / HVD_ELASTIC_DIR) — exiting "
                "without an emergency checkpoint")
        if _elastic.active():
            # A preempting rank going silent must read as a PLANNED
            # exit to its peers' lease, not a casualty.
            _elastic.get_world().announce_done()
        barriered = _preempt.drain_barrier()
        note = _preempt.journal_note(
            epoch=epoch, step=self._gstep,
            checkpoint=ckpt_path, step_drained=bool(drained),
            barrier_ok=bool(barriered))
        _ELASTIC_LOG.warning(
            "graceful preemption complete: step_drained=%s checkpoint=%s"
            " barrier_ok=%s note=%s — exiting 0", bool(drained),
            ckpt_path or "none", bool(barriered), note or "none")
        # The stdout marker the launcher/operator (and the chaos tier)
        # greps for; os._exit because interpreter teardown in a
        # multi-process world mid-eviction can hang in distributed-
        # client destructors (the exit_for_restart precedent).
        print(f"PREEMPTED rank={_tl._process_index()} epoch={epoch} "
              f"ckpt={'yes' if ckpt_path else 'no'} exiting=0",
              flush=True)
        try:
            import sys

            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(0)

    def _elastic_recover(self, x_sample) -> int:
        """Death-verdict recovery: reconfigure the world (in-place
        shrink, or exit for a supervisor-coordinated restart), rebuild
        the compiled steps over the new mesh, and resume from the newest
        checkpoint via the host-first broadcast pattern. Returns the
        epoch to resume at."""
        w = _elastic.get_world()
        self._elastic_dispatcher = None  # may be wedged in the old world
        try:
            w.reconfigure()
        except _elastic.ElasticRestartRequired as exc:
            w.exit_for_restart(str(exc))  # no return
        except Exception as exc:
            # A blown rebuild must DEGRADE to the coordinated restart,
            # never crash out of fit: an unhandled exception here would
            # reach interpreter exit, whose jax atexit hook calls
            # distributed.shutdown() — a barrier that wedges forever
            # against a dead/partial world (measured) — and the
            # supervisor would wait on the zombie instead of
            # relaunching.
            _ELASTIC_LOG.error("elastic reconfiguration failed",
                               exc_info=True)
            w.exit_for_restart(f"reconfiguration failed: {exc}")
        _ELASTIC_LOG.warning("elastic recovery: world reconfigured "
                             "(epoch %d); rebuilding steps and restoring "
                             "the newest checkpoint", w.epoch)
        # Fresh programs + fresh state on the new backend: everything
        # from the old world (including the RNG key, an old-backend
        # array) is unusable. The old references are PARKED, not
        # dropped — releasing state donated into a wedged execution can
        # block inside the dead runtime. The graveyard (previous step's
        # donated state awaiting worker-side release) is parked whole
        # for the same reason.
        w.park((self.params, self.batch_stats, self.opt_state,
                self.rng, self._elastic_graveyard))
        self._elastic_graveyard = []
        self._train_step = self._eval_step = None
        self.rng = jax.random.PRNGKey(997 + int(w.epoch))
        self.params = None
        self.batch_stats = {}
        self.opt_state = None
        self.build(x_sample)
        # Same restore-and-resume path the regrown world uses at
        # startup (newest checkpoint -> host-first broadcast -> resume
        # at the restored epoch + 1).
        resume = _elastic.maybe_restore(self, x_sample)
        self._build_steps()
        if resume:
            return resume
        # No checkpoint to resume from: reinitialize (the loss curve
        # restarts — elastic training should checkpoint every epoch,
        # which fit does automatically when a checkpoint dir is set).
        self.broadcast_state()
        return self._epoch

    def evaluate(self, x, y, batch_size: int = 32) -> dict:
        x, y = np.asarray(x), np.asarray(y)
        self.build(x[:batch_size * max(local_size(), 1)])
        if self._eval_step is None:
            self._build_steps()
        totals: dict = {}
        steps = 0
        for xb, yb in self._batches(x, y, batch_size, False, 0):
            logs = self._eval_step(self.params, self.batch_stats, xb, yb)
            for k, v in logs.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            steps += 1
        return {k: v / max(steps, 1) for k, v in totals.items()}

    def predict(self, x, batch_size: int = 32):
        x = np.asarray(x)
        outs = [np.asarray(self.model.apply(
            {"params": self.params, **({"batch_stats": self.batch_stats}
                                       if self.batch_stats else {})},
            jnp.asarray(x[i:i + batch_size]), False))
            for i in range(0, len(x), batch_size)]
        return np.concatenate(outs) if outs else np.zeros((0,))

    # -- persistence (reference: hvd.load_model, _keras/__init__.py:93-109) --

    def state_dict(self) -> dict:
        return {"params": self.params, "batch_stats": self.batch_stats,
                "opt_state": self.opt_state, "epoch": self._epoch,
                "lr_scale": self.lr_scale}

    def save(self, directory: str, step: Optional[int] = None):
        """Write a checkpoint (process 0 only; atomic)."""
        return _ckpt.save_checkpoint(
            directory, self.state_dict(),
            self._epoch if step is None else step)

    def load(self, path: str, x_sample, root_rank: int = 0):
        """Restore params + *wrapped* optimizer state and broadcast from
        root so all ranks resume identically.

        A checkpoint that does not match this Trainer's model/optimizer
        raises a ValueError naming the mismatched entries — flax's
        from_bytes restores wrong-SHAPED leaves silently (the error
        would otherwise surface steps later as a cryptic XLA shape
        failure), and a wrong STRUCTURE raises a flax KeyError with no
        model context (r4 verdict weak #4)."""
        self.build(x_sample)
        try:
            restored = _ckpt.load_checkpoint(path, self.state_dict(),
                                             root_rank=root_rank)
        except (OSError, HorovodInternalError):
            raise  # missing file / dead peer are NOT structure problems
        except Exception as exc:
            raise ValueError(
                f"checkpoint {path!r} does not match this Trainer's "
                f"model/optimizer structure: {exc}") from exc
        mism = _signature_mismatches(self.state_dict(), restored)
        if mism:
            shown = "; ".join(mism[:5])
            more = f" (+{len(mism) - 5} more)" if len(mism) > 5 else ""
            raise ValueError(
                f"checkpoint {path!r} does not match this Trainer's "
                f"model: {shown}{more}")
        # Mixed layout: the f32 master shards are the persisted source
        # of truth — rebuild the bf16 residents from them so resident ==
        # cast(master) bitwise after the restore (no-op without masters).
        restored = _ckpt.rebuild_resident_params(restored)
        self.params = restored["params"]
        self.batch_stats = restored["batch_stats"]
        self.opt_state = restored["opt_state"]
        self._epoch = int(restored["epoch"])
        self.lr_scale = float(restored["lr_scale"])
        return self


def _signature_mismatches(expected, restored) -> list:
    """Per-leaf (shape, dtype) comparison of two same-structure pytrees;
    returns human-readable mismatch descriptions (checkpoint vs model)."""
    import jax.tree_util as jtu

    out = []
    exp = {jtu.keystr(kp): v
           for kp, v in jtu.tree_flatten_with_path(expected)[0]}
    got = {jtu.keystr(kp): v
           for kp, v in jtu.tree_flatten_with_path(restored)[0]}
    for key in sorted(set(exp) | set(got)):
        if key not in got:
            out.append(f"{key}: missing from checkpoint")
        elif key not in exp:
            out.append(f"{key}: not in model")
        else:
            se, sg = np.shape(exp[key]), np.shape(got[key])
            if se != sg:
                out.append(f"{key}: checkpoint shape {sg} vs model {se}")
                continue
            # dtype only for real arrays: python-scalar metadata (epoch,
            # lr_scale) legitimately narrows through the msgpack round
            # trip (int64->int32), which is not a model mismatch.
            if se != ():
                de = np.asarray(exp[key]).dtype
                dg = np.asarray(got[key]).dtype
                if de != dg:
                    out.append(
                        f"{key}: checkpoint dtype {dg} vs model {de}")
    return out


def save_model(trainer: Trainer, directory: str,
               step: Optional[int] = None):
    return trainer.save(directory, step)


def load_model(path: str, model, optimizer, x_sample, **trainer_kwargs):
    """Reconstruct a Trainer with a distributed-wrapped optimizer from a
    checkpoint — the reference's ``hvd.load_model`` wraps the deserialized
    optimizer in the same way (reference: _keras/__init__.py:93-109)."""
    t = Trainer(model, optimizer, **trainer_kwargs)
    return t.load(path, x_sample)
