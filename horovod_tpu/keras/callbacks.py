"""Training callbacks with the reference's Keras-callback semantics.

Reference: horovod/_keras/callbacks.py (impls) re-exported by
horovod/keras/callbacks.py and horovod/tensorflow/keras/callbacks.py. They
run inside :class:`horovod_tpu.keras.Trainer`'s fit loop, which provides the
same hook points as Keras (`on_train_begin`, `on_epoch_begin`,
`on_batch_begin/end`, `on_epoch_end`).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from horovod_tpu.common import topology as _topo
from horovod_tpu.utils.metrics import MetricAverage


class Callback:
    """Hook container; the trainer assigns itself to ``self.trainer``."""

    trainer = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_batch_begin(self, batch, logs=None): ...
    def on_batch_end(self, batch, logs=None): ...


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial model + optimizer state from ``root_rank`` at the
    start of training (reference: _keras/callbacks.py:20-30; the TF hook
    equivalent is BroadcastGlobalVariablesHook,
    horovod/tensorflow/__init__.py:118-149)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, logs=None):
        self.trainer.broadcast_state(self.root_rank)


class MetricAverageCallback(Callback):
    """Allreduce-average epoch-end metrics over ranks so logged values are
    global (reference: _keras/callbacks.py:33-67)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            logs.update(MetricAverage(logs))


class LearningRateScheduleCallback(Callback):
    """Multiply the learning rate by ``multiplier(epoch)`` inside
    [start_epoch, end_epoch) (reference: _keras/callbacks.py:70-146).

    ``staircase=True`` adjusts once per epoch; ``False`` interpolates per
    batch using ``steps_per_epoch`` (autodetected from the trainer).
    ``momentum_correction`` rescales SGD momentum buffers by
    ``new_lr/old_lr`` when the rate changes (Goyal et al. 2017 — the
    reference does this by briefly scaling the momentum *coefficient*,
    which is the same first-order correction).
    """

    def __init__(self, multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def on_train_begin(self, logs=None):
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self.trainer.steps_per_epoch
            if not self.steps_per_epoch:
                raise ValueError(
                    "could not autodetect steps_per_epoch; pass it to "
                    f"{type(self).__name__}()")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def _adjust(self, epoch: float):
        old = self.trainer.lr_scale
        new = float(self.multiplier(epoch))
        self.trainer.set_lr_scale(
            new, momentum_correction=self.momentum_correction)
        return old, new

    def on_batch_begin(self, batch, logs=None):
        if self.current_epoch < self.start_epoch or (
                self.end_epoch is not None
                and self.current_epoch >= self.end_epoch):
            return
        if self.staircase and batch == 0:
            self._adjust(self.current_epoch)
        elif not self.staircase:
            self._adjust(self.current_epoch + batch / self.steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self.trainer.lr_scale


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr/size to lr over ``warmup_epochs`` — the
    formula of the reference (_keras/callbacks.py:149-168, after Goyal et
    al.): ``1/size * (epoch*(size-1)/warmup + 1)``."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        def multiplier(epoch):
            size = _topo.size()
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / size * (epoch * (size - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to scale {self.trainer.lr_scale:g}.")
