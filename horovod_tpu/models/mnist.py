"""MNIST models matching the reference examples' architectures.

``MnistConvNet`` is the 2-layer convnet of the reference's TF/Keras/torch
MNIST examples — conv5x5(32) → pool → conv5x5(64) → pool → dense(1024) →
dropout → dense(10) (reference: examples/tensorflow_mnist.py:30-63,
examples/keras_mnist.py, examples/pytorch_mnist.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 2:  # flat (B, 784) as the reference feeds it
            x = x.reshape((-1, 28, 28, 1))
        x = jnp.asarray(x, self.dtype)
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class MnistMLP(nn.Module):
    """Small dense net — the smoke-test model for optimizer integration
    tests (the role test_keras.py's 2-layer Dense model plays in the
    reference, test/test_keras.py:41-77)."""

    num_classes: int = 10
    hidden: int = 128

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_classes)(x)
