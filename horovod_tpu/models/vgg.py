"""VGG-16 — the hard case of the reference's headline benchmark.

VGG-16 is the model where the reference's scaling efficiency drops to 68%
(reference: README.md:50, docs/benchmarks.md:7) because its ~138M parameters
are dominated by two huge dense layers that serialize gradient allreduce.
It's included precisely as the communication-bound stress model.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
               512, 512, 512, "M", 512, 512, 512, "M"]
        x = jnp.asarray(x, self.dtype)
        for v in cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
