"""Transformer encoder/LM — the tensor-fusion and long-context stress model.

BASELINE.json names BERT-base pretraining as the fusion stress config (many
large gradient buckets). The reference has no transformer; this one is
TPU-designed: bf16 compute, f32 params, attention is *pluggable* so the
sequence-parallel implementations in :mod:`horovod_tpu.parallel` (ring
attention over ``ppermute``, Ulysses ``all_to_all``) slot in without model
changes, and all control flow is static for XLA.

Dimensions follow BERT-base (L=12, H=768, A=12) — every matmul dimension a
multiple of 128, i.e. MXU-tile aligned.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

# attention_fn signature: (q, k, v, bias) -> out, with q/k/v shaped
# (batch, seq, heads, head_dim). Default is plain softmax attention; the
# parallel package provides ring/Ulysses implementations.
AttentionFn = Callable[..., jnp.ndarray]


def dot_product_attention(q, k, v, bias=None):
    """Plain softmax attention, f32 accumulation on the MXU."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k,
                        preferred_element_type=jnp.float32)
    if bias is not None:
        logits = logits + bias
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(q, k, v, bias=None):
    """Causal-masked attention for LM training."""
    qlen, klen = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((qlen, klen), jnp.bool_))
    causal_bias = jnp.where(mask, 0.0, -1e9)[None, None]
    if bias is not None:
        causal_bias = causal_bias + bias
    return dot_product_attention(q, k, v, causal_bias)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522  # BERT wordpiece vocab
    num_layers: int = 12
    num_heads: int = 12
    hidden_dim: int = 768
    mlp_dim: int = 3072
    max_len: int = 512
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    causal: bool = False
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False  # jax.checkpoint each layer: FLOPs for HBM

    @property
    def head_dim(self) -> int:
        return self.hidden_dim // self.num_heads


class MultiHeadAttention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask_bias=None):
        cfg = self.cfg
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, cfg.head_dim), dtype=cfg.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        attn = cfg.attention_fn or (
            causal_attention if cfg.causal else dot_product_attention)
        out = attn(q, k, v, mask_bias)
        return nn.DenseGeneral(cfg.hidden_dim, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(out)


class EncoderLayer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask_bias=None, deterministic=True):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        h = MultiHeadAttention(cfg)(h, mask_bias)
        h = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(h)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_dim, dtype=cfg.dtype)(h)
        h = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(h)
        return x + h


class TransformerLM(nn.Module):
    """Token-in, logits-out transformer (pre-norm). With ``cfg.causal`` it
    is a GPT-style LM; without, a BERT-style masked-LM encoder."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, deterministic: bool = True,
                 return_hidden: bool = False):
        cfg = self.cfg
        if tokens.shape[-1] > cfg.max_len:
            # Out-of-range gathers are silently clamped under jit; fail
            # loudly at trace time instead.
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds max_len "
                f"{cfg.max_len}")
        x = nn.Embed(cfg.vocab_size, cfg.hidden_dim, dtype=cfg.dtype,
                     name="tok_embed")(tokens)
        pos = jnp.arange(tokens.shape[-1])[None]
        x = x + nn.Embed(cfg.max_len, cfg.hidden_dim, dtype=cfg.dtype,
                         name="pos_embed")(pos)
        layer = EncoderLayer
        if cfg.remat:
            layer = nn.remat(EncoderLayer, static_argnums=(3,))
        for i in range(cfg.num_layers):
            x = layer(cfg, name=f"layer_{i}")(x, None, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        if return_hidden:
            # Pre-head hidden states, for heads that consume the weights
            # directly without materializing [.., vocab] logits
            # (ops/chunked_loss.py). lm_head params still exist: init
            # runs with return_hidden=False.
            return x
        # Untied output head, f32 logits.
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="lm_head")(x)


def BertBase(**overrides) -> TransformerLM:
    return TransformerLM(TransformerConfig(**overrides))
