"""ResNet v1.5 family in flax, laid out for the MXU.

The reference benchmarks ResNet-50/101 from ``tf.keras.applications`` /
``tf_cnn_benchmarks`` (reference: examples/tensorflow_synthetic_benchmark.py:
44-45, docs/benchmarks.md:33-38). This is a native implementation, not a
port: NHWC layout (XLA's preferred TPU conv layout), bfloat16 compute with
float32 parameters and batch-norm statistics, and static shapes throughout so
XLA can tile convs onto the systolic array.

v1.5 = stride-2 in the 3x3 of the bottleneck (not the 1x1), the variant every
modern benchmark reports.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

ModuleDef = Any

# Tag under which conv outputs are offered to a remat policy — transparent
# when no remat is active. Wrap the whole loss/apply in
# ``jax.checkpoint(fn, policy=conv_saves_policy())`` to store ONLY conv
# outputs for backward and recompute the BN/ReLU/residual-join chains from
# them instead of round-tripping them through HBM: a bandwidth-bound
# ResNet step (bs32 measures ~65% idle MXU) trades spare compute for
# removed traffic, with numerics identical — the recompute is the same
# deterministic elementwise function of the same saved values.
CONV_SAVE_NAME = "conv_out"
# Tag on the big post-norm/activation elementwise intermediates — the
# candidates for DROPPING from the saved set (see act_drop_policy).
ACT_DROP_NAME = "block_act"


def conv_saves_policy():
    """Remat policy: keep ONLY conv outputs, drop and recompute
    everything else. MEASURED NEGATIVE on the v5e headline bench
    (docs/benchmarks.md): dropping the BN mean/var reductions forces
    full re-reads of conv outputs to recompute them — traffic went UP
    7.81 → 10.82 GB/step. Kept for the record; use
    :func:`act_drop_policy` instead."""
    return jax.checkpoint_policies.save_only_these_names(CONV_SAVE_NAME)


def act_drop_policy():
    """Remat policy: save everything stock autodiff would EXCEPT the
    tagged post-BN/ReLU/join activations; those are recomputed in
    backward from the (still saved) conv outputs and BN statistics —
    elementwise recompute, no extra reduction passes. Function-level
    ``jax.checkpoint`` keeps flax param paths untouched (``nn.remat``
    would rename module scopes, making checkpoints
    non-interchangeable)."""
    return jax.checkpoint_policies.save_anything_except_these_names(
        ACT_DROP_NAME)


def _name_conv(y):
    return checkpoint_name(y, CONV_SAVE_NAME)


def _name_act(y):
    return checkpoint_name(y, ACT_DROP_NAME)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1(x4) with projection shortcut."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _name_conv(self.conv(self.filters, (1, 1))(x))
        y = _name_act(self.norm()(y))
        y = _name_act(self.act(y))
        y = _name_conv(self.conv(self.filters, (3, 3), self.strides)(y))
        y = _name_act(self.norm()(y))
        y = _name_act(self.act(y))
        y = _name_conv(self.conv(self.filters * 4, (1, 1))(y))
        # Zero-init of the last BN scale: each block starts as identity,
        # which is what lets large-batch distributed training (the regime
        # this framework exists for) hold accuracy at high learning rates.
        y = _name_act(self.norm(scale_init=nn.initializers.zeros)(y))
        if residual.shape != y.shape:
            residual = _name_conv(
                self.conv(self.filters * 4, (1, 1), self.strides,
                          name="conv_proj")(residual))
            residual = _name_act(self.norm(name="norm_proj")(residual))
        return _name_act(self.act(residual + y))


class BasicBlock(nn.Module):
    """3x3 -> 3x3 (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _name_conv(self.conv(self.filters, (3, 3), self.strides)(x))
        y = _name_act(self.norm()(y))
        y = _name_act(self.act(y))
        y = _name_conv(self.conv(self.filters, (3, 3))(y))
        y = _name_act(self.norm(scale_init=nn.initializers.zeros)(y))
        if residual.shape != y.shape:
            residual = _name_conv(
                self.conv(self.filters, (1, 1), self.strides,
                          name="conv_proj")(residual))
            residual = _name_act(self.norm(name="norm_proj")(residual))
        return _name_act(self.act(residual + y))


def space_to_depth_2x2(x):
    """NHWC [N,H,W,C] → [N,H/2,W/2,4C]: each output channel block is one
    subpixel of the 2x2 macro-pixel (row-major: (row_sub, col_sub, c))."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


def conv7_kernel_to_s2d(k7):
    """Exact reparameterization of a 7x7/s2 'SAME' conv kernel [7,7,C,O]
    as the equivalent 4x4/s1 kernel [4,4,4C,O] over space-to-depth input
    (zero-pad 7→8 taps, fold each tap's parity into the subpixel
    channels). Used by the equivalence test; training uses the 4x4 form
    directly."""
    c, o = k7.shape[2], k7.shape[3]
    k8 = jnp.pad(k7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    # [8,8,C,O] -> [4,2,4,2,C,O] -> [4,4,2,2,C,O] -> [4,4,4C,O]
    k = k8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return k.reshape(4, 4, 4 * c, o)


class ResNet(nn.Module):
    """ResNet over NHWC images.

    Batch-norm statistics are per-replica (each chip normalizes its local
    batch), matching the reference's data-parallel semantics where BN state
    is never allreduced — only initially broadcast (reference:
    horovod/tensorflow/__init__.py:96-115).

    ``stem``: ``"conv7"`` is the classic 7x7/s2 convolution; contracting
    over only 3 input channels it wastes most of the MXU's 128 lanes.
    ``"space_to_depth"`` reshapes the image to [H/2, W/2, 12] and trains
    the mathematically equivalent 4x4/s1 kernel instead (exactness:
    :func:`conv7_kernel_to_s2d`; the standard TPU ResNet stem). Same
    function class, different parameterization — checkpoints are not
    interchangeable between stems.

    Conv outputs carry the :data:`CONV_SAVE_NAME` checkpoint tag: wrap
    the loss in ``jax.checkpoint(fn, policy=conv_saves_policy())`` to
    recompute the BN/ReLU/join chains in backward instead of storing
    them (see :func:`conv_saves_policy`).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        if self.stem not in ("conv7", "space_to_depth"):
            # Silent fallback would train a different parameterization
            # than the user asked for (checkpoints are not
            # interchangeable between stems).
            raise ValueError(f"unknown stem {self.stem!r}; expected "
                             "'conv7' or 'space_to_depth'")
        if self.stem == "space_to_depth":
            x = space_to_depth_2x2(x)
            # Pad (1,2): macro-row span of the 7x7/s2 taps (see
            # conv7_kernel_to_s2d) — NOT flax 'SAME', which would center
            # the 4x4 window differently and break equivalence.
            x = nn.Conv(self.num_filters, (4, 4), use_bias=False,
                        dtype=self.dtype, padding=((1, 2), (1, 2)),
                        name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = _name_act(norm(name="bn_init")(x))
        x = _name_act(self.act(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Logits in f32: the loss/softmax wants full precision.
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
