"""ResNet v1.5 family in flax, laid out for the MXU.

The reference benchmarks ResNet-50/101 from ``tf.keras.applications`` /
``tf_cnn_benchmarks`` (reference: examples/tensorflow_synthetic_benchmark.py:
44-45, docs/benchmarks.md:33-38). This is a native implementation, not a
port: NHWC layout (XLA's preferred TPU conv layout), bfloat16 compute with
float32 parameters and batch-norm statistics, and static shapes throughout so
XLA can tile convs onto the systolic array.

v1.5 = stride-2 in the 3x3 of the bottleneck (not the 1x1), the variant every
modern benchmark reports.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1(x4) with projection shortcut."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init of the last BN scale: each block starts as identity,
        # which is what lets large-batch distributed training (the regime
        # this framework exists for) hold accuracy at high learning rates.
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet over NHWC images.

    Batch-norm statistics are per-replica (each chip normalizes its local
    batch), matching the reference's data-parallel semantics where BN state
    is never allreduced — only initially broadcast (reference:
    horovod/tensorflow/__init__.py:96-115).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Logits in f32: the loss/softmax wants full precision.
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
