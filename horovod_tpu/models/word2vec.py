"""Skip-gram word2vec with sampled-softmax (NEG) loss.

The reference's word2vec example trains a 128-d embedding over a 50k vocab
with NCE loss and allgathers nothing — its gradients are the sparse-
embedding stress case (reference: examples/tensorflow_word2vec.py; sparse
path: horovod/tensorflow/__init__.py:73-84). On TPU the embedding gradient
is dense (scatter-add into the table happens on-chip), so the sparse
IndexedSlices machinery is unnecessary on the hot path — but the JAX
frontend's BCOO sparse allreduce covers the API.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class Word2Vec(nn.Module):
    vocab_size: int = 50000
    embedding_dim: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        # U[-1, 1) as in the reference example
        # (examples/tensorflow_word2vec.py:157); flax's uniform() is [0, s).
        def _symmetric_uniform(key, shape, dtype):
            return jax.random.uniform(key, shape, dtype, -1.0, 1.0)

        self.embeddings = nn.Embed(self.vocab_size, self.embedding_dim,
                                   embedding_init=_symmetric_uniform,
                                   dtype=self.dtype)
        self.nce_weight = self.param(
            "nce_weight",
            nn.initializers.truncated_normal(1.0 / self.embedding_dim ** 0.5),
            (self.vocab_size, self.embedding_dim), self.dtype)
        self.nce_bias = self.param("nce_bias", nn.initializers.zeros,
                                   (self.vocab_size,), self.dtype)

    def __call__(self, center: jnp.ndarray) -> jnp.ndarray:
        """Embed center words: (B,) int32 -> (B, D)."""
        return self.embeddings(center)

    def neg_loss(self, center, context, negatives):
        """Negative-sampling loss.

        Args:
          center: (B,) center word ids.
          context: (B,) true context ids.
          negatives: (B, K) sampled negative ids.
        """
        v = self.embeddings(center)  # (B, D)
        u_pos = self.nce_weight[context]  # (B, D)
        b_pos = self.nce_bias[context]
        u_neg = self.nce_weight[negatives]  # (B, K, D)
        b_neg = self.nce_bias[negatives]
        pos = jnp.sum(v * u_pos, axis=-1) + b_pos  # (B,)
        neg = jnp.einsum("bd,bkd->bk", v, u_neg) + b_neg  # (B, K)
        loss_pos = -jax.nn.log_sigmoid(pos)
        loss_neg = -jnp.sum(jax.nn.log_sigmoid(-neg), axis=-1)
        return jnp.mean(loss_pos + loss_neg)
