"""Inception V3 — the third headline benchmark model.

The reference's flagship scaling claim is 90% efficiency for Inception V3
at 512 GPUs (reference: README.md:45-50, docs/benchmarks.md:1-7), with the
model supplied by tf.keras.applications. Native flax implementation
(Szegedy et al. 2015 v3 topology: factorized 7x1/1x7 convolutions, the
A/B/C/D/E block family, aux head omitted — benchmarks train the main head).
NHWC, bf16 compute, f32 params. ~23.8M parameters at 1000 classes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _pool_avg(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


def conv3_kernel_to_s2d(k3):
    """Exact reparameterization of the stem's 3x3/s2 'VALID' kernel
    [3,3,C,O] as the equivalent 2x2/s1 kernel [2,2,4C,O] over
    space-to-depth input (zero-pad 3→4 taps, fold tap parity into the
    subpixel channels — the same mapping as ResNet's
    :func:`~horovod_tpu.models.resnet.conv7_kernel_to_s2d`). Used by the
    equivalence test; training uses the 2x2 form directly."""
    c, o = k3.shape[2], k3.shape[3]
    k4 = jnp.pad(k3, ((0, 1), (0, 1), (0, 0), (0, 0)))
    # [4,4,C,O] -> [2,2,2,2,C,O] -> [2,2,2,2,C,O] (subpixel-major) -> [2,2,4C,O]
    k = k4.reshape(2, 2, 2, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return k.reshape(2, 2, 4 * c, o)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train),
                           train)
        b4 = c(self.pool_features, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), (2, 2), "VALID")(x, train)
        b2 = c(96, (3, 3), (2, 2), "VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """17x17 blocks with factorized 7x1/1x7 convolutions."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        f = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = x
        for k in ((1, 1), (1, 7), (7, 1)):
            feats = 192 if k == (7, 1) else f
            b2 = c(feats, k)(b2, train)
        b3 = x
        for i, k in enumerate(((1, 1), (7, 1), (1, 7), (7, 1), (1, 7))):
            feats = 192 if i == 4 else f
            b3 = c(feats, k)(b3, train)
        b4 = c(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (3, 3), (2, 2), "VALID")(c(192, (1, 1))(x, train), train)
        b2 = c(192, (1, 1))(x, train)
        for k in ((1, 7), (7, 1)):
            b2 = c(192, k)(b2, train)
        b2 = c(192, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """8x8 blocks with split 1x3/3x1 branches."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([c(384, (1, 3))(b2, train),
                              c(384, (3, 1))(b2, train)], axis=-1)
        b3 = c(448, (1, 1))(x, train)
        b3 = c(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([c(384, (1, 3))(b3, train),
                              c(384, (3, 1))(b3, train)], axis=-1)
        b4 = c(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """``stem``: ``"conv3"`` is the canonical 3x3/s2 VALID convolution;
    ``"space_to_depth"`` reshapes the 299px image to [150,150,12] (one
    zero pad row/col) and trains the mathematically equivalent 2x2/s1
    VALID kernel (exactness: :func:`conv3_kernel_to_s2d`) — the ResNet
    stem treatment applied to the 32-channel Inception stem the r4
    profile names as >10% of the step (docs/benchmarks.md). Checkpoints
    are not interchangeable between stems."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stem: str = "conv3"

    @nn.compact
    def __call__(self, x, train: bool = True):
        from horovod_tpu.models.resnet import space_to_depth_2x2

        c = partial(ConvBN, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        if self.stem not in ("conv3", "space_to_depth"):
            raise ValueError(f"unknown stem {self.stem!r}; expected "
                             "'conv3' or 'space_to_depth'")
        if self.stem == "space_to_depth":
            # 299 is odd: one zero row/col pad reaches the 2x2 macro
            # grid; the padded taps are exactly the zero-padded 4th
            # kernel row/col of conv3_kernel_to_s2d.
            n, h, w, _ = x.shape
            x = jnp.pad(x, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)))
            x = space_to_depth_2x2(x)
            x = c(32, (2, 2), (1, 1), "VALID")(x, train)
        else:
            x = c(32, (3, 3), (2, 2), "VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionB(self.dtype)(x, train)
        x = InceptionC(128, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(192, self.dtype)(x, train)
        x = InceptionD(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
