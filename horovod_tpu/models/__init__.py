"""Model zoo for horovod_tpu benchmarks and examples.

The reference ships no model library — its examples pull models from
``tf.keras.applications`` / ``torchvision`` (reference:
examples/tensorflow_synthetic_benchmark.py:10,44-45,
examples/pytorch_imagenet_resnet50.py). A TPU-native framework cannot lean on
those (torchvision has no TPU path; tf.keras is not the compute stack here),
so the models the reference's examples and headline benchmarks use are
implemented natively in flax: ResNet-50/101/152 and VGG-16 (the benchmark
models of reference README.md:45-50), the 2-layer MNIST convnet
(examples/tensorflow_mnist.py:30-63), word2vec skip-gram
(examples/tensorflow_word2vec.py), and a BERT-style transformer encoder (the
tensor-fusion stress config of BASELINE.json) with pluggable attention so the
long-context paths in :mod:`horovod_tpu.parallel` can drop in.

All models default to bfloat16 compute with float32 parameters — the MXU's
native mixed precision.
"""

from horovod_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.vgg import VGG16  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
from horovod_tpu.models.mnist import MnistConvNet, MnistMLP  # noqa: F401
from horovod_tpu.models.word2vec import Word2Vec  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    TransformerLM,
    BertBase,
)

_REGISTRY = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "vgg16": VGG16,
    "inceptionv3": InceptionV3,
    "inception_v3": InceptionV3,
    "mnist_cnn": MnistConvNet,
    "mnist_mlp": MnistMLP,
}


def get_model(name: str, **kwargs):
    """Construct a vision model by name (benchmark scripts use this the way
    the reference uses ``getattr(applications, args.model)`` —
    examples/tensorflow_synthetic_benchmark.py:44-45)."""
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown model '{name}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)
