"""Device/process topology — the TPU-native replacement for MPI rank discovery.

The reference derives ``rank/size/local_rank/local_size/cross_rank/cross_size``
from ``MPI_COMM_WORLD`` plus a shared-memory split and a cross-node split
(reference: horovod/common/operations.cc:1638-1705 and the C getters at
operations.cc:2226-2262). On TPU there is no MPI: a *rank* is a TPU chip, the
world is a ``jax.sharding.Mesh`` over all chips, the "local" communicator is
the set of chips attached to one host process (ICI-connected within a slice),
and the "cross" communicator is the across-host tier (DCN).

Mapping (see SURVEY.md §2.3):

==================  ==========================================================
reference concept   TPU-native equivalent
==================  ==========================================================
MPI_COMM_WORLD      1-D ``Mesh(jax.devices(), ('hvd',))``
rank                global id of this process's first device (device-level
                    rank inside SPMD code comes from ``lax.axis_index``)
size                total number of chips in the mesh
local_comm          this process's ``jax.local_devices()``; co-hosted
                    controllers are split by ``local_rank()`` (hostname
                    exchange at init — the shared-memory split)
cross_comm          one representative per host (DCN tier):
                    ``cross_rank()``/``cross_size()`` enumerate hosts
==================  ==========================================================

Single-controller SPMD means one Python process may *speak for* several ranks
(its local chips); host-side code therefore sees the process-level view while
per-chip rank identity lives inside compiled programs.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Sequence

import numpy as np

HVD_AXIS = "hvd"


class HorovodInternalError(RuntimeError):
    """Engine-surfaced error (reference: coordinator ERROR responses,
    horovod/common/operations.cc:315-517)."""


class NotInitializedError(ValueError):
    """Raised by topology getters before init() (reference:
    horovod/common/__init__.py:90-139 raises ValueError)."""

    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; call horovod_tpu.init()."
        )


class _Topology:
    """Singleton world state (reference: HorovodGlobalState,
    horovod/common/operations.cc:108-247 — minus the comm thread, which on
    TPU lives in the native engine, see horovod_tpu/core)."""

    def __init__(self) -> None:
        self.initialized = False
        self.lock = threading.Lock()
        self.mesh = None
        self.devices: list = []
        self.local_devices: list = []
        self.size = 0
        self.rank0 = 0  # global rank of this process's first local device
        self.local_size = 0
        self.local_rank = 0  # this controller's index among co-hosted ones
        self.cross_size = 0
        self.cross_rank = 0
        self.host_num_processes = 1  # controllers sharing this host
        self.num_processes = 1
        self.process_index = 0
        self.homogeneous = True
        self.two_tier = None  # (dcn, ici) Mesh when the world has 2 tiers


_state = _Topology()


def _build_mesh(devs: Sequence) -> "object":
    from jax.sharding import Mesh

    return Mesh(np.asarray(devs), (HVD_AXIS,))


def _build_two_tier(devices: Sequence):
    """(dcn, ici) mesh over the SAME devices in the SAME order as the flat
    world mesh — the reference's local/cross communicator split
    (operations.cc:1668-1705). Axis names match
    :mod:`horovod_tpu.parallel.mesh`. Returns None when the world has no
    usable two-tier structure (single process without an override,
    heterogeneous chip counts, or process-interleaved device order —
    hierarchical collectives would silently permute ranks then).

    ``HVD_TWO_TIER_SHAPE=o,i`` overrides the process grouping — the test
    and simulation knob (e.g. treat a single 8-device process as 2 slices
    of 4), mirroring how the reference's hierarchical path is exercised
    by telling MPI there are multiple nodes.
    """
    from jax.sharding import Mesh

    shape_env = os.environ.get("HVD_TWO_TIER_SHAPE")
    if shape_env:
        # An explicit override must fail loudly — silently degrading to
        # flat collectives would invalidate whatever the user is measuring.
        try:
            outer, inner = (int(v) for v in shape_env.split(","))
        except ValueError:
            raise ValueError(
                f"HVD_TWO_TIER_SHAPE={shape_env!r} is not 'outer,inner' "
                "(e.g. '2,4')") from None
        if outer < 1 or inner < 1 or outer * inner != len(devices):
            raise ValueError(
                f"HVD_TWO_TIER_SHAPE={shape_env!r} does not factor the "
                f"{len(devices)}-device world")
        arr = np.empty((outer, inner), dtype=object)
        for idx, d in enumerate(devices):
            arr[idx // inner, idx % inner] = d
        return Mesh(arr, ("dcn", "ici"))
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    if len(by_proc) < 2:
        return None
    if len({len(v) for v in by_proc.values()}) != 1:
        return None  # heterogeneous: reference gates hierarchical off too
    rows = [by_proc[p] for p in sorted(by_proc)]
    flat = [d for row in rows for d in row]
    if flat != list(devices):
        return None  # interleaved order would change rank identity
    arr = np.empty((len(rows), len(rows[0])), dtype=object)
    for r, row in enumerate(rows):
        for c, d in enumerate(row):
            arr[r, c] = d
    return Mesh(arr, ("dcn", "ici"))


# Count of successfully COMPLETED hostname exchanges; advances only on
# success, so it stays agreed across processes (see _host_split).
_host_split_completed = 0


def _host_split(num_processes: int, process_index: int):
    """Shared-host split (reference: the MPI_Comm_split_type(SHARED) local
    communicator + the cross split, operations.cc:1668-1705): every
    process publishes its hostname to the coordination service and reads
    its peers', yielding which controllers share a physical host.

    Returns ``(local_rank, host_num_processes, cross_rank, cross_size)``
    — controller index among co-hosted controllers, how many controllers
    share this host, this host's index, and the number of distinct hosts
    — or ``None`` when no coordination service is reachable (callers
    degrade to the one-controller-per-host view).

    ``HVD_HOSTNAME`` overrides the reported hostname — the simulation
    knob for exercising multi-host layouts on one machine (the same role
    mpirun's hostfile plays for the reference)."""
    import json as _json
    import socket

    host = os.environ.get("HVD_HOSTNAME") or socket.gethostname()
    if num_processes == 1:
        return 0, 1, 0, 1
    from horovod_tpu.core import coordinator as coord

    try:
        kv = coord.JaxKV()
    except Exception:
        # No coordination service is a PROPERTY OF THE WORLD (the jax
        # distributed client is either up everywhere or nowhere), so the
        # one-controller-per-host fallback stays consistent across it.
        return None
    global _host_split_completed
    # Keys are namespaced by the count of COMPLETED exchanges, which
    # agrees across processes (lifecycle is collective, and an exchange
    # completes either everywhere or nowhere — completion requires
    # every process's key, which requires every process to have
    # published). This closes both failure modes at once: a new
    # incarnation reads FRESH keys (never a peer's stale hostname from
    # the previous one), while a FAILED attempt does not advance the
    # count, so retriers and a late straggler converge on the same
    # namespace. Keys are immutable in the common case; the remaining
    # delete+set only fires for a hostname changed across a *failed*
    # attempt within one incarnation.
    inc = _host_split_completed
    try:
        key = f"hvd/host/i{inc}/p{process_index}"
        existing = kv.try_get(key)
        if existing is not None and _json.loads(existing) != host:
            kv.delete(key)
            existing = None
        if existing is None:
            kv.set(key, _json.dumps(host))
        deadline = coord.negotiation_timeout_s()
        peers = [_json.loads(kv.get(f"hvd/host/i{inc}/p{p}", deadline))
                 for p in range(num_processes)]
        if peers[process_index] != host:  # own delete/set failed
            raise KeyError("own hostname key is stale")
        _host_split_completed += 1
    except Exception as exc:
        # The service exists but a peer's hostname never arrived: a
        # silent per-process fallback here would leave the world
        # DISAGREEING on cross_size/local_rank ownership — fail loudly
        # instead (the same contract negotiation rounds have).
        raise HorovodInternalError(
            f"shared-host split failed: could not exchange hostnames "
            f"with all {num_processes} processes ({exc}); a peer may "
            "not have reached hvd.init()") from None
    by_host: dict = {}
    for p, h in enumerate(peers):
        by_host.setdefault(h, []).append(p)
    hosts = sorted(by_host, key=lambda h: by_host[h][0])  # first-pid order
    mine = by_host[host]
    return (mine.index(process_index), len(mine),
            hosts.index(host), len(hosts))


def init(ranks: Optional[Sequence[int]] = None, devices: Optional[Sequence] = None):
    """Initialize the world.

    Args:
      ranks: optional subset of global device indices to form the world from,
        mirroring the reference's ``init(comm=[ranks])`` rank-subset support
        (reference: horovod/common/__init__.py:58-84). Only valid
        single-process.
      devices: explicit device list (tests use this to shrink the world).

    Idempotent like the reference's ``InitializeHorovodOnce``
    (reference: horovod/common/operations.cc:2176-2194).
    """
    with _state.lock:
        if _state.initialized:
            return

        import jax

        # Launcher-driven platform selection (horovod_tpu.run --cpu): the
        # env var JAX_PLATFORMS alone can be preempted by pre-registered
        # plugins, so apply it through jax.config while the backend is
        # still uninitialized.
        plat = os.environ.get("HVD_PLATFORM")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:
                pass  # backend already up; leave the platform as-is

        # Multi-host: if the user (or launcher) provided coordination env,
        # bring up the JAX distributed client so jax.devices() is global.
        # The already-initialized probe must NOT touch the backend
        # (jax.process_count() would initialize it, after which
        # distributed.initialize refuses to run), hence the client check.
        coord = os.environ.get("HVD_COORDINATOR_ADDRESS")
        if coord and os.environ.get("HVD_NUM_PROCESSES"):
            from jax._src import distributed as _jax_dist

            if _jax_dist.global_state.client is None:
                from horovod_tpu.core import elastic as _elastic

                if _elastic.enabled():
                    # Elastic worlds own the bring-up: the stock client
                    # TERMINATES survivors when the coordination service
                    # notices a dead peer — detection must live in the
                    # elastic heartbeat lease instead (core/elastic.py).
                    _elastic.bring_up_distributed(
                        coord,
                        int(os.environ["HVD_NUM_PROCESSES"]),
                        int(os.environ.get("HVD_PROCESS_ID", "0")))
                else:
                    jax.distributed.initialize(
                        coordinator_address=coord,
                        num_processes=int(os.environ["HVD_NUM_PROCESSES"]),
                        process_id=int(os.environ.get("HVD_PROCESS_ID",
                                                      "0")),
                    )

        # Multi-controller on the CPU platform: current jaxlib executes
        # cross-process CPU collectives only through a CPU collectives
        # backend — without one, the first collective dies with
        # "Multiprocess computations aren't implemented on the CPU
        # backend". Select gloo while the backend is still uninitialized
        # (works before or after jax.distributed.initialize; an env var
        # alone is preempted the same way JAX_PLATFORMS is). No-op for
        # single-process and for real TPU platforms.
        try:
            from jax._src import distributed as _jax_dist

            multiproc = _jax_dist.global_state.client is not None
        except Exception:
            multiproc = False
        if multiproc and (plat == "cpu"
                          or jax.config.jax_platforms == "cpu"):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # older jaxlib without the knob / backend already up

        if devices is None:
            devices = list(jax.devices())
        if ranks is not None:
            if jax.process_count() > 1:
                raise ValueError("ranks= subset is only supported single-process")
            devices = [devices[i] for i in ranks]

        local = [d for d in devices if d.process_index == jax.process_index()]
        if not local:
            raise ValueError("this process owns no devices in the requested world")

        _state.devices = list(devices)
        _state.local_devices = local
        _state.mesh = _build_mesh(devices)
        _state.size = len(devices)
        _state.local_size = len(local)
        _state.num_processes = jax.process_count()
        _state.process_index = jax.process_index()
        # Global rank of the first local device: devices are mesh-ordered, so
        # this is its index in the world list.
        _state.rank0 = _state.devices.index(local[0])
        # Shared-host split (reference: operations.cc:1668-1705). Without
        # a coordination service (or single-process) every controller is
        # assumed to own its host — the previous fixed behavior.
        split = _host_split(jax.process_count(), jax.process_index())
        if split is None:
            _state.local_rank = 0
            _state.host_num_processes = 1
            _state.cross_rank = jax.process_index()
            _state.cross_size = jax.process_count()
        else:
            (_state.local_rank, _state.host_num_processes,
             _state.cross_rank, _state.cross_size) = split
        counts = {}
        for d in devices:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
        _state.homogeneous = len(set(counts.values())) == 1
        _state.two_tier = _build_two_tier(devices)
        _state.initialized = True
    # If an engine was constructed before init() (legal: enqueue works
    # pre-init), re-apply its params so the multi-controller fusion guard
    # sees the now-known topology.
    try:
        from horovod_tpu.core import engine as _eng

        if _eng._engine is not None:
            _eng._engine.set_params(
                fusion_threshold=_eng._engine.fusion_threshold)
    except Exception:
        pass
    if _state.num_processes > 1:
        # Multi-controller liveness: negotiation rounds need EVERY
        # process's engine participating (peers block on our round
        # message even when we never use the engine path ourselves —
        # the reference equivalently gathers a possibly-empty request
        # list from every rank each tick, operations.cc:2117-2131).
        # A failure here MUST be loud: a silent non-participant stalls
        # every peer for the full negotiation timeout.
        try:
            from horovod_tpu.core import coordinator as _coord, engine as _eng

            if _coord.negotiation_enabled():
                _eng.get_engine()
        except Exception as exc:
            import logging

            logging.getLogger("horovod_tpu").error(
                "failed to start the collective engine for negotiation "
                "rounds (%s); peer processes' engine collectives will "
                "stall until HVD_NEGOTIATION_TIMEOUT", exc)
    # Elastic worlds (HVD_ELASTIC=1): start the heartbeat lease + adopt
    # the world-epoch journal. No-op when elastic is off.
    try:
        from horovod_tpu.core import elastic as _elastic

        if _elastic.enabled():
            _elastic.get_world().on_init(_state.num_processes,
                                         _state.process_index)
    except Exception:
        import logging

        logging.getLogger("horovod_tpu").warning(
            "elastic world bring-up failed", exc_info=True)
    # Fleet observability plane: per-rank snapshot publisher (+ rank-0
    # aggregator) over the KV plane. No-op unless a fleet directory
    # resolves (HVD_FLEET_DIR, or the elastic dir); must never break init.
    try:
        from horovod_tpu.core import fleet as _fleet

        _fleet.maybe_start(_state.process_index, _state.num_processes)
    except Exception:
        import logging

        logging.getLogger("horovod_tpu").warning(
            "fleet plane bring-up failed", exc_info=True)


def shutdown():
    """Tear down the world (reference: horovod_shutdown,
    horovod/common/operations.cc:2216-2224)."""
    with _state.lock:
        if not _state.initialized:
            return
        try:
            from horovod_tpu.core import engine as _engine

            _engine.shutdown_engine()
        except Exception:
            pass
        try:
            from horovod_tpu.ops import collectives as _coll

            _coll._ranked_program.cache_clear()
        except Exception:
            pass
        try:
            from horovod_tpu.core import fleet as _fleet

            _fleet.stop()
        except Exception:
            pass
        try:
            # Shutdown -> init re-entry (elastic reconfiguration rebuilds
            # the mesh in-process): cached concrete trees hold arrays of
            # the outgoing world — clear them with the mesh-keyed
            # programs so nothing pins the old Mesh/devices.
            from horovod_tpu import jax as _hjax

            _hjax._ZERO_TREES.clear()
        except Exception:
            pass
        _state.initialized = False
        _state.mesh = None
        _state.two_tier = None
        _state.devices = []
        _state.local_devices = []


atexit.register(shutdown)


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> _Topology:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def size() -> int:
    """Total number of ranks (chips) in the world."""
    return _require_init().size


def rank() -> int:
    """Global rank of this process's first chip. Inside compiled SPMD code use
    ``horovod_tpu.ops.axis_rank()`` for the per-chip rank."""
    return _require_init().rank0


def local_size() -> int:
    """Number of chips THIS CONTROLLER drives (the mapping table above:
    local_comm = this process's ``jax.local_devices()``) — the
    per-process sizing knob examples use for their local batch."""
    return _require_init().local_size


def local_rank() -> int:
    """This controller's index among the controllers sharing its host
    (reference: the shared-memory-split local rank,
    operations.cc:1668-1705) — the owner key for per-host resources
    (cache dirs, log files, host-level data shards; see
    docs/running.md). 0 for the usual one controller per host; with two
    controllers on one machine they see 0 and 1."""
    return _require_init().local_rank


def local_num_processes() -> int:
    """Number of controller processes sharing this host."""
    return _require_init().host_num_processes


def cross_size() -> int:
    """Number of distinct hosts in the world (one controller per host —
    the common TPU layout — makes this equal to ``num_processes()``)."""
    return _require_init().cross_size


def cross_rank() -> int:
    """This host's index among the world's hosts. For a per-process id
    that is unique even with several controllers on one host, use
    :func:`process_index`."""
    return _require_init().cross_rank


def num_processes() -> int:
    return _require_init().num_processes


def process_index() -> int:
    return _require_init().process_index


def mesh():
    """The world ``jax.sharding.Mesh`` (1-D, axis name ``'hvd'``)."""
    return _require_init().mesh


def two_tier():
    """The (dcn, ici) world mesh, or None when the world has no two-tier
    structure (see :func:`_build_two_tier`)."""
    return _require_init().two_tier


def devices() -> list:
    return list(_require_init().devices)


def device_rank_axis() -> str:
    """Name of the mesh axis that enumerates ranks."""
    return HVD_AXIS


def is_homogeneous() -> bool:
    """Every process owns the same number of chips (reference:
    horovod/common/operations.cc:1686-1705 homogeneity check)."""
    return _require_init().homogeneous


def mpi_threads_supported() -> bool:
    """Reference API parity (horovod/common/operations.cc:2256-2262). There
    is no MPI on TPU; host threads may always call into the engine."""
    _require_init()
    return True
