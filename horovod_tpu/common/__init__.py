"""Shared basics for horovod_tpu (reference: horovod/common/__init__.py)."""

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    local_num_processes,
    cross_size,
    cross_rank,
    num_processes,
    process_index,
    mesh,
    devices,
    device_rank_axis,
    is_homogeneous,
    mpi_threads_supported,
    HorovodInternalError,
    NotInitializedError,
)
