"""jax version compatibility shims.

One import site for the API drift the framework spans:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to ``jax``
  (>= 0.4.35-ish), and its replication-check keyword was renamed
  ``check_rep`` -> ``check_vma``. Every module here spells the NEW name
  (``check_vma``); on an older jax the shim forwards it as ``check_rep``.

Import ``shard_map`` from here instead of repeating the try/except +
keyword dance at each call site.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:  # pragma: no cover - exercised only on older jax
    import functools

    @functools.wraps(_shard_map)
    def shard_map(*args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)
