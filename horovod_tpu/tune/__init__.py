"""Autotuning: Bayesian optimization of engine parameters.

Reference: horovod/common/parameter_manager.{h,cc} (C9) +
horovod/common/optim/ (C10). Enabled with HVD_AUTOTUNE=1 (reference:
HOROVOD_AUTOTUNE, operations.cc:1797-1804); CSV log via HVD_AUTOTUNE_LOG.
"""

from horovod_tpu.tune.bayesian_optimization import BayesianOptimization  # noqa: F401
from horovod_tpu.tune.gaussian_process import GaussianProcessRegressor  # noqa: F401
from horovod_tpu.tune.parameter_manager import (  # noqa: F401
    ParameterManager,
    autotune_enabled,
)
