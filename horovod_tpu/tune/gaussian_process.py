"""Gaussian-process regressor for the autotuner.

Reference: horovod/common/optim/gaussian_process.{h,cc} — RBF kernel,
hyperparameters fit by maximizing log-marginal likelihood with L-BFGS,
Cholesky-factored posterior. Same math here on numpy/scipy instead of
Eigen/LBFGSpp.
"""

from __future__ import annotations

import numpy as np
from scipy.linalg import cho_factor, cho_solve
from scipy.optimize import minimize


class GaussianProcessRegressor:
    """GP with RBF kernel k(a,b) = σ² exp(-‖a-b‖²/(2ℓ²)) + α·δ."""

    def __init__(self, alpha: float = 1e-6):
        self.alpha = alpha
        self.length_scale = 1.0
        self.sigma_f = 1.0
        self._x = None
        self._y = None
        self._chol = None
        self._y_mean = 0.0

    def _kernel(self, a: np.ndarray, b: np.ndarray,
                length_scale: float, sigma_f: float) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return sigma_f ** 2 * np.exp(-0.5 * d2 / length_scale ** 2)

    def _nll(self, theta, x, y):
        ls, sf = np.exp(theta)
        k = self._kernel(x, x, ls, sf) + self.alpha * np.eye(len(x))
        try:
            c, low = cho_factor(k)
        except np.linalg.LinAlgError:
            return 1e25
        a = cho_solve((c, low), y)
        return (0.5 * y @ a + np.log(np.diag(c)).sum()
                + 0.5 * len(x) * np.log(2 * np.pi))

    def fit(self, x: np.ndarray, y: np.ndarray):
        """Fit hyperparameters by LML maximization (reference:
        gaussian_process.cc:95-98 uses L-BFGS the same way)."""
        x = np.atleast_2d(np.asarray(x, float))
        y = np.asarray(y, float).ravel()
        self._y_mean = float(y.mean()) if len(y) else 0.0
        yc = y - self._y_mean
        best = None
        for ls0 in (0.1, 1.0, 3.0):
            res = minimize(self._nll, np.log([ls0, max(yc.std(), 1e-3)]),
                           args=(x, yc), method="L-BFGS-B",
                           bounds=[(-5, 5), (-5, 5)])
            if best is None or res.fun < best.fun:
                best = res
        self.length_scale, self.sigma_f = np.exp(best.x)
        k = self._kernel(x, x, self.length_scale, self.sigma_f) \
            + self.alpha * np.eye(len(x))
        self._chol = cho_factor(k)
        self._x, self._y = x, yc
        return self

    def predict(self, x: np.ndarray):
        """Posterior mean and stddev at query points."""
        x = np.atleast_2d(np.asarray(x, float))
        if self._x is None:
            return (np.full(len(x), self._y_mean),
                    np.full(len(x), self.sigma_f))
        ks = self._kernel(x, self._x, self.length_scale, self.sigma_f)
        a = cho_solve(self._chol, self._y)
        mu = ks @ a + self._y_mean
        v = cho_solve(self._chol, ks.T)
        var = self.sigma_f ** 2 - np.einsum("ij,ji->i", ks, v)
        return mu, np.sqrt(np.clip(var, 1e-12, None))
