"""Bayesian optimization with expected-improvement acquisition.

Reference: horovod/common/optim/bayesian_optimization.{h,cc} — GP posterior
+ EI maximized by multi-restart L-BFGS. Deterministic given the seed so
every controller process proposes identical parameters from identical
samples (the reference instead has rank 0 tune and broadcast —
parameter_manager.cc:203-236; determinism makes the broadcast redundant).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
from scipy.optimize import minimize
from scipy.stats import norm

from horovod_tpu.tune.gaussian_process import GaussianProcessRegressor


class BayesianOptimization:
    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 xi: float = 0.01, seed: int = 0):
        self.bounds = np.asarray(bounds, float)
        self.xi = xi
        self.gp = GaussianProcessRegressor()
        self.xs: list = []
        self.ys: list = []
        self._rng = np.random.default_rng(seed)

    @property
    def dim(self) -> int:
        return len(self.bounds)

    def add_sample(self, x, y: float):
        self.xs.append(np.asarray(x, float).ravel())
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def best(self) -> Optional[np.ndarray]:
        if not self.ys:
            return None
        return self.xs[int(np.argmax(self.ys))]

    def _ei(self, x):
        mu, sd = self.gp.predict(x)
        f_best = max(self.ys)
        z = (mu - f_best - self.xi) / sd
        return (mu - f_best - self.xi) * norm.cdf(z) + sd * norm.pdf(z)

    def next_sample(self, n_restarts: int = 10) -> np.ndarray:
        """Maximize EI via multi-restart L-BFGS-B (reference:
        bayesian_optimization.cc:92-104)."""
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        if len(self.xs) < 2:
            return self._rng.uniform(lo, hi)
        best_x, best_v = None, np.inf
        starts = self._rng.uniform(lo, hi, size=(n_restarts, self.dim))
        for s in starts:
            res = minimize(lambda x: -self._ei(x[None])[0], s,
                           method="L-BFGS-B", bounds=self.bounds)
            if res.fun < best_v:
                best_v, best_x = res.fun, res.x
        if best_x is None or not np.isfinite(best_v):
            return self._rng.uniform(lo, hi)
        return np.clip(best_x, lo, hi)
