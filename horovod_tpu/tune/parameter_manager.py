"""Online autotuning of engine parameters.

Reference: horovod/common/parameter_manager.{h,cc} — tunes
{fusion_threshold ∈ [0,64] MB, cycle_time ∈ [1,100] ms} jointly with
Bayesian optimization, maximizing throughput (bytes/µs), with 3 discarded
warmup samples, scores taken as the median of 5 samples of 10 cycles each,
and a CSV log (HOROVOD_AUTOTUNE_LOG). Same procedure here; the tuned values
are pushed into the running engine via ``set_params``.

The reference has rank 0 tune and broadcast a Params struct over MPI
(parameter_manager.cc:203-236). Here the optimizer is deterministic given
identical (x, y) histories; since multi-controller fusion is disabled until
negotiation exists (engine guard), tuning runs on single-controller worlds
where no sync is needed at all.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from horovod_tpu.tune.bayesian_optimization import BayesianOptimization

# Search space (reference: parameter_manager.cc:44-52).
FUSION_MB_BOUNDS = (0.0, 64.0)
CYCLE_MS_BOUNDS = (1.0, 100.0)

WARMUPS = 3            # reference: parameter_manager.cc:27-30
CYCLES_PER_SAMPLE = 10
SAMPLES_PER_STEP = 5
MAX_STEPS = 20


class ParameterManager:
    """Feed ``update(bytes)`` once per completed engine cycle; the manager
    scores throughput, proposes new (fusion_threshold, cycle_time) via
    Bayesian optimization, applies them to ``engine`` and eventually
    settles on the best point seen."""

    def __init__(self, engine=None, log_path: Optional[str] = None,
                 warmups: int = WARMUPS,
                 cycles_per_sample: int = CYCLES_PER_SAMPLE,
                 samples_per_step: int = SAMPLES_PER_STEP,
                 max_steps: int = MAX_STEPS, seed: int = 0,
                 clock=time.monotonic):
        # ``clock`` is a seam for deterministic tests: patching the time
        # module globally would warp live engine/coordinator threads.
        self._clock = clock
        self.engine = engine
        self.bo = BayesianOptimization(
            [FUSION_MB_BOUNDS, CYCLE_MS_BOUNDS], seed=seed)
        self.warmups_left = warmups
        self.cycles_per_sample = cycles_per_sample
        self.samples_per_step = samples_per_step
        self.max_steps = max_steps
        self.active = True
        self.current = np.array([
            (FUSION_MB_BOUNDS[0] + FUSION_MB_BOUNDS[1]) / 2,
            5.0,  # reference default 5 ms cycle
        ])
        self._cycle_count = 0
        self._bytes = 0
        self._t0 = self._clock()
        self._scores: list = []
        self._steps = 0
        self._log = None
        if log_path is None:
            log_path = (os.environ.get("HVD_AUTOTUNE_LOG")
                        or os.environ.get("HOROVOD_AUTOTUNE_LOG"))
        if log_path:
            self._log = open(log_path, "w")
            self._log.write("fusion_mb,cycle_ms,score_bytes_per_us\n")
        self._apply(self.current)

    # -- plumbing -------------------------------------------------------------

    def _apply(self, x):
        self.current = np.asarray(x, float)
        if self.engine is not None:
            self.engine.set_params(
                cycle_time_s=float(self.current[1]) / 1e3,
                fusion_threshold=int(self.current[0] * 1024 * 1024))

    def params(self) -> dict:
        return {"fusion_threshold_mb": float(self.current[0]),
                "cycle_time_ms": float(self.current[1]),
                "active": self.active}

    # -- scoring loop (reference: parameter_manager.cc:110-200) ---------------

    def update(self, nbytes: int) -> bool:
        """Record one engine cycle's traffic. Returns True when parameters
        changed."""
        if not self.active:
            return False
        self._bytes += int(nbytes)
        self._cycle_count += 1
        if self._cycle_count < self.cycles_per_sample:
            return False
        elapsed_us = max((self._clock() - self._t0) * 1e6, 1.0)
        score = self._bytes / elapsed_us
        self._cycle_count = 0
        self._bytes = 0
        self._t0 = self._clock()
        if self.warmups_left > 0:
            self.warmups_left -= 1
            return False
        self._scores.append(score)
        if len(self._scores) < self.samples_per_step:
            return False
        med = float(np.median(self._scores))
        self._scores.clear()
        if self._log:
            self._log.write(
                f"{self.current[0]:.3f},{self.current[1]:.3f},{med:.6f}\n")
            self._log.flush()
        self.bo.add_sample(self.current, med)
        self._steps += 1
        if self._steps >= self.max_steps:
            # Converged: lock in the best point seen (reference stops
            # tuning once samples are exhausted).
            self.active = False
            self._apply(self.bo.best())
            if self._log:
                self._log.write(
                    f"# converged: fusion_mb={self.current[0]:.3f} "
                    f"cycle_ms={self.current[1]:.3f}\n")
                self._log.flush()
            return True
        self._apply(self.bo.next_sample())
        return True

    def close(self):
        if self._log:
            self._log.close()
            self._log = None


def autotune_enabled() -> bool:
    """HOROVOD_AUTOTUNE activation (reference: operations.cc:1797-1804)."""
    return bool(os.environ.get("HVD_AUTOTUNE")
                or os.environ.get("HOROVOD_AUTOTUNE"))
