"""Pipeline parallelism: GPipe schedule over a ``pp`` mesh axis.

Each chip owns one stage's parameters; activations flow stage-to-stage via
neighbour ``ppermute`` (ICI point-to-point) while ``M`` microbatches fill
the pipe. The schedule is the classic GPipe fill-drain: ``M + P - 1`` ticks,
bubble fraction ``(P-1)/(M+P-1)``.

No reference equivalent (SURVEY.md §2.3). Implemented as a single
``lax.fori_loop`` inside SPMD code so XLA overlaps each tick's compute with
the activation shift.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    axis_name: str = "pp",
):
    """Run ``stage_fn`` over ``P`` pipeline stages for ``M`` microbatches.

    Args:
      stage_fn: ``(params, activation) -> activation``; this chip's stage.
        Activation shape must be invariant across stages.
      stage_params: this chip's stage parameters (under ``shard_map``, pass
        a pytree whose leaves were sharded over ``axis_name``).
      x_microbatches: (M, ...) microbatched input. Only stage 0 reads it.

    Returns:
      (M, ...) outputs, valid on every chip (the last stage's results are
      broadcast back over the pp axis).
    """
    p = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    act_shape = x_microbatches.shape[1:]

    perm = [(j, (j + 1) % p) for j in range(p)]
    zero = jnp.zeros(act_shape, x_microbatches.dtype)

    def tick(t, carry):
        outputs, current = carry
        # Stage 0 ingests microbatch t (or junk past the end, masked later).
        feed = x_microbatches[jnp.minimum(t, m - 1)]
        current = jnp.where(idx == 0, feed, current)
        y = stage_fn(stage_params, current)
        # The last stage finished microbatch t-(P-1) this tick.
        done = t - (p - 1)
        slot = jnp.clip(done, 0, m - 1)
        take = jnp.logical_and(idx == p - 1, done >= 0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(take, y, outputs[slot]).astype(outputs.dtype),
            slot, axis=0)
        return outputs, lax.ppermute(y, axis_name, perm)

    outputs0 = jnp.zeros((m,) + act_shape, x_microbatches.dtype)
    outputs, _ = lax.fori_loop(0, m + p - 1, tick, (outputs0, zero))
    # Broadcast final outputs from the last stage to all pp ranks so the
    # loss is computable everywhere (one psum of the microbatch outputs).
    outputs = jnp.where(idx == p - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees into one pytree with a
    leading stage axis — shard that axis over ``pp`` and unstack inside
    shard_map with ``jax.tree.map(lambda x: x[0], ...)``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
