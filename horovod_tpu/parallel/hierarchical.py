"""Two-tier hierarchical collectives: reduce-scatter(ICI) → cross-tier
op(DCN) → all-gather(ICI).

This is the TPU-native re-design of the reference's hierarchical allreduce —
NCCL ReduceScatter → host-staged cross-node MPI_Allreduce → NCCL AllGather
(reference: horovod/common/operations.cc:1194-1346) — with XLA collectives
replacing both NCCL and MPI and no host staging buffer. The reference pads
fused buffers to 64-element atomic units so the scatter divides evenly
(reference: operations.h:52-54, operations.cc:712-731); here the same
padding happens at trace time with static shapes.

These run *inside* SPMD code over a mesh that has both tiers as named axes
(see :func:`horovod_tpu.parallel.mesh.two_tier_mesh`).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import DCN_AXIS, ICI_AXIS


def _padded_flat(x, inner: int):
    flat = jnp.ravel(x)
    rem = flat.size % inner
    pad = inner - rem if rem else 0
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def hierarchical_allreduce(
    x,
    inner_axis: str = ICI_AXIS,
    outer_axis: str = DCN_AXIS,
    average: bool = False,
    dcn_policy=None,
):
    """Allreduce ``x`` across both tiers, moving only 1/inner_size of the
    payload over the slow outer tier per chip.

    Cost model (why this beats flat allreduce across DCN): flat ring
    allreduce sends 2·N bytes/chip over DCN; hierarchical sends 2·N/L where
    L = inner size, with the bulk 2·N·(L-1)/L riding ICI — the same
    bandwidth argument as the reference's NCCL/MPI split
    (operations.cc:1194-1346).

    ``dcn_policy`` (a quantized wire policy from
    :mod:`horovod_tpu.jax.compression`) composes the EQuARX block-scaled
    wire with the tier split: the ICI reduce-scatter stays at the resident
    dtype, and ONLY the 1/L shard crosses the outer tier quantized
    (payload + f32 scales, block-padded) — cross-tier bytes drop by both
    the tier factor AND the wire factor. Requires a float ``x``; a
    single-tier outer axis elides the quantization entirely (no wire hop
    to shrink, and the digest stays on the unquantized path).
    """
    inner = lax.psum(1, inner_axis)  # static at trace time
    flat, pad = _padded_flat(x, inner)
    chunk = lax.psum_scatter(flat, inner_axis, scatter_dimension=0, tiled=True)
    if dcn_policy is not None and lax.psum(1, outer_axis) > 1:
        from horovod_tpu.jax import quantize as _Q

        chunk = _Q.spmd_allreduce(chunk, outer_axis, False, dcn_policy)
    else:
        chunk = lax.psum(chunk, outer_axis)
    out = lax.all_gather(chunk, inner_axis, axis=0, tiled=True)
    if pad:
        out = out[:-pad]
    if average:
        world = inner * lax.psum(1, outer_axis)
        if (jnp.issubdtype(out.dtype, jnp.floating)
                or jnp.issubdtype(out.dtype, jnp.complexfloating)):
            out = (out / world).astype(x.dtype)
        else:
            out = out // world
    return out.reshape(x.shape)


def hierarchical_allgather(x, inner_axis: str = ICI_AXIS,
                           outer_axis: str = DCN_AXIS):
    """Allgather along dim 0 across both tiers (reference: the MPI
    shared-memory-window hierarchical allgather, operations.cc:875-1010).

    Gather over the outer tier first (each chip contributes its block once
    over DCN), then share over ICI... except XLA already routes a flat
    all_gather over the fastest links; the two-phase form exists for
    explicit control. Result ordering is outer-major, matching a flat
    gather over a (outer, inner)-ordered mesh.
    """
    outer = lax.all_gather(x, outer_axis, axis=0, tiled=True)
    both = lax.all_gather(outer, inner_axis, axis=1,
                          tiled=False)  # (outer*n, inner, ...)
    # Reorder to global rank order: outer-major, inner-minor.
    o = lax.psum(1, outer_axis)
    i = lax.psum(1, inner_axis)
    n = x.shape[0]
    both = both.reshape((o, n, i) + x.shape[1:])
    both = jnp.swapaxes(both, 1, 2)
    return both.reshape((o * i * n,) + x.shape[1:])
