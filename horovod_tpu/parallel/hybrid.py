"""A full hybrid-parallel (dp × pp × tp × sp) transformer training step.

Composes every strategy in this package into one compiled SPMD program:

- **dp**: batch sharded; gradients pmean'd (the horovod verb).
- **pp**: encoder layers split into GPipe stages (:mod:`.pipeline`).
- **tp**: attention projections and MLP are Megatron-sharded
  (:mod:`.tensor_parallel`); one forward psum per block half.
- **sp**: sequence sharded; attention is exact ring attention
  (:mod:`.ring_attention`) — K/V blocks rotate over ICI neighbours.

Parameter placement: stage params live on their pp rank, tp-sharded leaves
are per-chip shards, everything is replicated across dp and sp. Gradient
reduction is therefore pmean over (dp, sp) for stage params and the head,
plus a psum over pp for the embeddings (they contribute only on stage 0).

This powers ``__graft_entry__.dryrun_multichip`` and serves as the
reference recipe for users composing their own hybrid steps.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.mesh import hybrid_mesh
from horovod_tpu.parallel.moe import moe_layer
from horovod_tpu.parallel.pipeline import pipeline_apply
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    ParallelMLP,
    RowParallelDense,
)

from horovod_tpu.common.compat import shard_map as _shard_map


@dataclasses.dataclass(frozen=True)
class HybridConfig:
    vocab_size: int = 64
    hidden_dim: int = 32
    mlp_dim: int = 64
    num_heads: int = 4
    layers_per_stage: int = 1
    seq_len: int = 16          # global sequence length
    microbatches: int = 2
    lr: float = 0.1
    dtype: object = jnp.float32
    # Expert-parallel MoE block per layer (experts sharded over 'ep').
    use_moe: bool = True
    experts_per_chip: int = 2
    moe_capacity_factor: float = 2.0


def partition_axes(n: int) -> dict:
    """Factor ``n`` devices into (dp, pp, tp, sp, ep): powers of two feed
    the model axes first (pp, tp, sp, then ep), any remainder rides dp.
    Axes the budget can't fill stay at size 1 — their collectives become
    no-ops but the sharding structure is identical."""
    sizes = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
    rem = n
    for ax in ("pp", "tp", "sp", "ep"):
        if rem % 2 == 0 and rem > 1:
            sizes[ax] = 2
            rem //= 2
    sizes["dp"] = rem
    return sizes


class HybridStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` pre-norm transformer layers
    with tp-sharded projections and ring attention over sp."""

    cfg: HybridConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        tp = lax.psum(1, "tp")
        heads_local = cfg.num_heads // tp
        head_dim = cfg.hidden_dim // cfg.num_heads
        for i in range(cfg.layers_per_stage):
            h = nn.LayerNorm(dtype=cfg.dtype, name=f"ln_attn_{i}")(x)
            qkv = [
                ColumnParallelDense(
                    cfg.num_heads * head_dim, "tp", dtype=cfg.dtype,
                    name=f"{nm}_{i}")(h)
                for nm in ("q", "k", "v")
            ]
            q, k, v = (
                t.reshape(t.shape[:-1] + (heads_local, head_dim))
                for t in qkv
            )
            a = ring_attention(q, k, v, "sp", causal=True)
            a = a.reshape(a.shape[:-2] + (heads_local * head_dim,))
            a = RowParallelDense(cfg.hidden_dim, "tp", dtype=cfg.dtype,
                                 name=f"attn_out_{i}")(a)
            x = x + a
            h = nn.LayerNorm(dtype=cfg.dtype, name=f"ln_mlp_{i}")(x)
            x = x + ParallelMLP(cfg.hidden_dim, cfg.mlp_dim, "tp",
                                dtype=cfg.dtype, name=f"mlp_{i}")(h)
            if cfg.use_moe:
                # Expert-parallel MoE block: experts sharded over 'ep'.
                # Tokens are replicated across ep in this recipe (they are
                # sharded over dp and sp only), so each ep chip routes the
                # same tokens and expert compute is duplicated ep-fold —
                # correct but redundant. Production deployments map the ep
                # groups onto dp groups so tokens arrive pre-sharded; kept
                # simple here because it leaves gradient reduction uniform
                # (see reduce_grads). The load-balance aux loss is dropped
                # (pipeline activations must be shape-invariant).
                ep = lax.psum(1, "ep")
                ep_idx = lax.axis_index("ep")
                e_local = cfg.experts_per_chip

                def _expert_init(key, shape, dtype):
                    # Experts are *sharded* over ep: distinct weights per
                    # ep chip. Everything else in the stage (router,
                    # attention, MLP, norms) must stay REPLICATED across
                    # ep — the module init key is identical across ep, and
                    # only expert leaves fold the ep index in.
                    return nn.initializers.lecun_normal()(
                        jax.random.fold_in(key, ep_idx), shape, dtype)

                h = nn.LayerNorm(dtype=cfg.dtype, name=f"ln_moe_{i}")(x)
                router = self.param(
                    f"moe_router_{i}", nn.initializers.lecun_normal(),
                    (cfg.hidden_dim, e_local * ep), jnp.float32)
                wi = self.param(
                    f"moe_wi_{i}", _expert_init,
                    (e_local, cfg.hidden_dim, cfg.mlp_dim), jnp.float32)
                wo = self.param(
                    f"moe_wo_{i}", _expert_init,
                    (e_local, cfg.mlp_dim, cfg.hidden_dim), jnp.float32)
                b, s, hid = h.shape
                y, _aux = moe_layer(
                    h.reshape(b * s, hid), router, wi, wo, "ep",
                    capacity_factor=cfg.moe_capacity_factor)
                x = x + y.reshape(b, s, hid).astype(x.dtype)
        return x


def build_train_step(mesh: Mesh, cfg: HybridConfig):
    """Return ``(step, token_spec)`` where ``step(tokens, key) ->
    (loss_before, loss_after)`` initializes hybrid-sharded parameters,
    takes one full SGD step, and re-evaluates — all inside a single
    compiled SPMD program over ``mesh``. Required axes: dp/pp/tp/sp, plus
    ``ep`` when ``cfg.use_moe`` (the only place the ep axis is touched)."""
    cfg_stage = HybridStage(cfg)

    def spmd(tokens, key):
        dp = lax.psum(1, "dp")
        pp = lax.psum(1, "pp")
        sp = lax.psum(1, "sp")
        pp_idx = lax.axis_index("pp")
        sp_idx = lax.axis_index("sp")
        tp_idx = lax.axis_index("tp")
        b_local, s_local = tokens.shape
        m = cfg.microbatches
        bm = b_local // m

        # Distinct init per (pp stage, tp shard); identical across
        # dp/sp/ep — expert weights alone diverge per ep chip, via their
        # own initializer (see HybridStage._expert_init). Folding ep here
        # would make the router/attention/MLP weights diverge across ep,
        # silently desynchronizing the replicas.
        stage_key = jax.random.fold_in(
            jax.random.fold_in(key, pp_idx), tp_idx)
        dummy = jnp.zeros((bm, s_local, cfg.hidden_dim), cfg.dtype)
        stage_params = cfg_stage.init(stage_key, dummy)["params"]
        ek = jax.random.split(key, 3)
        embed = jax.random.normal(
            ek[0], (cfg.vocab_size, cfg.hidden_dim), cfg.dtype) * 0.02
        pos = jax.random.normal(
            ek[1], (cfg.seq_len, cfg.hidden_dim), cfg.dtype) * 0.02
        head = jax.random.normal(
            ek[2], (cfg.hidden_dim, cfg.vocab_size), cfg.dtype) * 0.02
        params = {"embed": embed, "pos": pos, "head": head,
                  "stage": stage_params}

        def loss_fn(params):
            x = params["embed"][tokens]
            pos_slice = lax.dynamic_slice_in_dim(
                params["pos"], sp_idx * s_local, s_local, axis=0)
            x = x + pos_slice[None]
            micro = x.reshape((m, bm, s_local, cfg.hidden_dim))
            out = pipeline_apply(
                lambda p, a: cfg_stage.apply({"params": p}, a),
                params["stage"], micro, "pp")
            out = out.reshape((b_local, s_local, cfg.hidden_dim))
            logits = (out @ params["head"]).astype(jnp.float32)
            # Next-token prediction. The target for the last position of
            # each sp shard is the NEXT shard's first token, fetched over
            # ICI via ppermute (shard j sends its first column to shard
            # j-1); the global last position has no next token and is
            # masked out of the loss.
            nxt_first = lax.ppermute(
                tokens[:, :1], "sp",
                [(j, (j - 1) % sp) for j in range(sp)])
            tgt = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
            ll = jax.nn.log_softmax(logits)
            tok_loss = -jnp.take_along_axis(ll, tgt[..., None], axis=-1)[..., 0]
            pos_ids = sp_idx * s_local + jnp.arange(s_local)
            mask = (pos_ids < cfg.seq_len - 1).astype(tok_loss.dtype)
            num = lax.psum((tok_loss * mask[None, :]).sum(), ("dp", "sp"))
            den = lax.psum(jnp.float32(b_local) * mask.sum(), ("dp", "sp"))
            return num / den

        def reduce_grads(g):
            # Stage/head: replicated over dp+sp -> pmean. Embeddings feed
            # only stage-0 activations -> also psum over pp.
            g = jax.tree.map(lambda t: lax.pmean(t, ("dp", "sp")), g)
            g["embed"] = lax.psum(g["embed"], "pp")
            g["pos"] = lax.psum(g["pos"], "pp")
            return g

        loss0, grads = jax.value_and_grad(loss_fn)(params)
        grads = reduce_grads(grads)
        params = jax.tree.map(lambda p, g: p - cfg.lr * g, params, grads)
        loss1 = loss_fn(params)
        # pmean over the remaining axes so every chip returns the same
        # replicated scalar.
        return (lax.pmean(loss0, ("pp", "tp")),
                lax.pmean(loss1, ("pp", "tp")))

    token_spec = P(("dp",), ("sp",))
    step = jax.jit(_shard_map(
        spmd, mesh=mesh, in_specs=(token_spec, P()),
        out_specs=(P(), P()), check_vma=False))
    return step, token_spec


def dryrun(n_devices: int, devices=None,
           cfg: HybridConfig = HybridConfig()) -> Tuple[float, float]:
    """Build the mesh, run one hybrid step, return (loss_before,
    loss_after)."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
    sizes = partition_axes(n_devices)
    mesh = hybrid_mesh(sizes, devices[:n_devices])
    dp, sp = sizes["dp"], sizes["sp"]
    batch = 2 * cfg.microbatches * dp
    if cfg.seq_len % sp:
        raise ValueError("seq_len must divide by sp")
    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, cfg.seq_len)).astype(np.int32)
    step, _ = build_train_step(mesh, cfg)
    l0, l1 = step(tokens, jax.random.PRNGKey(0))
    return float(l0), float(l1)
