"""Parallelism strategies over TPU device meshes.

The reference is data-parallel only (SURVEY.md §2.3) with one distributed
trick: 2-level hierarchical collectives splitting intra-node (NCCL) from
inter-node (MPI) traffic (reference: horovod/common/operations.cc:1194-1346,
875-1010). On TPU the same two tiers are ICI (within a slice) and DCN
(across slices); :mod:`horovod_tpu.parallel.hierarchical` implements the
composition natively.

Beyond reference parity, a TPU framework must scale model *and* sequence
dimensions, so this package also provides tensor parallelism, sequence/
context parallelism (ring attention, Ulysses all-to-all), and pipeline
parallelism — all expressed as shardings + XLA collectives over a hybrid
``jax.sharding.Mesh``.
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    hybrid_mesh,
    two_tier_mesh,
    MeshAxes,
)
from horovod_tpu.parallel.hierarchical import (  # noqa: F401
    hierarchical_allreduce,
    hierarchical_allgather,
)
from horovod_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from horovod_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from horovod_tpu.parallel.tensor_parallel import (  # noqa: F401
    ColumnParallelDense,
    RowParallelDense,
    ParallelMLP,
)
from horovod_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from horovod_tpu.parallel.moe import moe_layer  # noqa: F401
