"""Ring attention: exact attention over sequences sharded across chips.

Context parallelism for sequences too long for one chip's HBM: Q stays put,
K/V blocks rotate around the mesh axis ring via ``ppermute`` while each chip
accumulates its queries' attention with an online (flash-style) softmax.
After ``axis_size`` steps every query has attended to every key. Communication
is neighbour-to-neighbour only, so it rides ICI at full bisection bandwidth
and overlaps with the block matmuls.

No reference equivalent exists (SURVEY.md §5: long-context absent) — this is
the new scope a TPU framework needs. Design follows the public blockwise/
ring-attention formulation (Liu et al., 2023), implemented with
``lax.fori_loop`` + ``lax.ppermute`` so XLA pipelines the collective with
compute; accumulation in f32.

Shapes: q/k/v are (batch, seq_local, heads, head_dim), sequence-sharded over
``axis_name``. Causal masking uses global positions derived from
``lax.axis_index``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, bias, m_prev, l_prev, o_prev):
    """One online-softmax accumulation step.

    q: (b, sq, h, d); k/v: (b, sk, h, d); bias: broadcastable to
    (b, h, sq, sk) or None. Accumulators: m/l (b, h, sq), o (b, sq, h, d),
    all f32.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # Rescale previous accumulators to the new max.
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o_prev * jnp.transpose(alpha, (0, 2, 1))[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   bias=None):
    """Exact (not approximate) attention over a sequence sharded on
    ``axis_name``. Drop-in for
    :func:`horovod_tpu.models.transformer.dot_product_attention` inside
    SPMD code.

    ``bias``, if given, is this chip's (b, h, sq_local, seq_global) slice;
    the k-dimension window matching each rotating block is sliced
    dynamically.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]

    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    q_pos = my_idx * sq + jnp.arange(sq)  # global query positions

    def body(i, carry):
        m, l, o, kb, vb = carry
        # Block i holds keys originating at rank (my_idx - i) mod size.
        src = (my_idx - i) % axis_size
        k_pos = src * sk + jnp.arange(sk)
        step_bias = None
        if causal:
            step_bias = jnp.where(
                q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF
            )[None, None]
        if bias is not None:
            window = lax.dynamic_slice_in_dim(bias, src * sk, sk, axis=3)
            step_bias = window if step_bias is None else step_bias + window
        m, l, o = _block_attend(q, kb, vb, step_bias, m, l, o)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, o, kb, vb

    m, l, o, _, _ = lax.fori_loop(0, axis_size, body, (m0, l0, o0, k, v))
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)
