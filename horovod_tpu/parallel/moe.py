"""Mixture-of-experts with expert parallelism over an ``ep`` mesh axis.

Switch-Transformer-style top-1 token-choice routing with capacity: tokens
are dispatched to experts with one ``all_to_all`` (each chip owns
``n_experts / ep`` experts' FFN weights), expert FFNs run as dense batched
matmuls on the MXU, and a mirror ``all_to_all`` brings results home.
Overflow tokens beyond expert capacity pass through the residual (their
combine weight is zero) — standard Switch semantics.

No reference equivalent (data-parallel only, SURVEY.md §2.3); this
completes the ep axis of the hybrid mesh. All dispatch/combine logic is
one-hot einsum — no gather/scatter with dynamic shapes, so XLA tiles
everything statically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _top1_dispatch(x, router_logits, n_experts: int, capacity: int):
    """Build dispatch/combine tensors for top-1 routing.

    Returns (dispatch (t,E,C) bool-ish float, combine (t,E,C) float,
    aux_loss scalar).
    """
    t = x.shape[0]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (t,)
    gate = jnp.max(probs, axis=-1)  # (t,)
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)
    # Position of each token within its chosen expert's queue.
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (t, E)
    keep = (pos < capacity) * onehot
    pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_clamped, capacity,
                                dtype=jnp.float32)  # (t, E, C)
    dispatch = keep[..., None] * pos_onehot  # (t, E, C)
    combine = dispatch * gate[:, None, None]
    # Switch load-balancing loss: E * sum_e fraction_tokens_e * mean_prob_e.
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux, t


def moe_layer(
    x,
    router_w,
    expert_wi,
    expert_wo,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
    act: Callable = jax.nn.gelu,
):
    """Apply an expert-parallel MoE FFN block inside SPMD code.

    Args:
      x: (tokens_local, hidden) this chip's tokens.
      router_w: (hidden, n_experts_global) router weights (replicated).
      expert_wi: (experts_local, hidden, ff) this chip's experts' input
        projections — experts are sharded over ``axis_name``.
      expert_wo: (experts_local, ff, hidden).
      capacity_factor: per-expert queue size multiplier.

    Returns:
      (tokens_local, hidden) output, plus the scalar load-balancing aux
      loss (already pmean'd over the ep axis).
    """
    ep = lax.psum(1, axis_name)
    e_local = expert_wi.shape[0]
    n_experts = e_local * ep
    t, hidden = x.shape
    capacity = max(1, int(t * capacity_factor / n_experts))

    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    dispatch, combine, aux, _ = _top1_dispatch(x, logits, n_experts,
                                               capacity)

    # (t, E, C) x (t, h) -> (E, C, h): token payloads in expert queues.
    expert_in = jnp.einsum("tec,th->ech", dispatch, x.astype(jnp.float32))
    # Route queues to their owning chips: (E, C, h) = (ep, e_local, C, h);
    # all_to_all swaps the ep dim for a source-chip dim.
    expert_in = expert_in.reshape(ep, e_local, capacity, hidden)
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                               concat_axis=0, tiled=True)
    # Post-all_to_all layout is again (ep, e_local, C, h), but dim 0 now
    # indexes SOURCE chips: row s holds chip s's queue for this chip's
    # local experts. Merge source × capacity into one batch per expert.
    expert_in = jnp.transpose(expert_in, (1, 0, 2, 3)).reshape(
        e_local, ep * capacity, hidden)

    # Dense batched expert FFNs on the MXU.
    h1 = act(jnp.einsum("ebh,ehf->ebf", expert_in,
                        expert_wi.astype(jnp.float32)))
    out = jnp.einsum("ebf,efh->ebh", h1, expert_wo.astype(jnp.float32))

    # Reverse the routing.
    out = out.reshape(e_local, ep, capacity, hidden)
    out = jnp.transpose(out, (1, 0, 2, 3))  # (ep, e_local, C, h)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=True)
    out = out.reshape(n_experts, capacity, hidden)

    y = jnp.einsum("tec,ech->th", combine, out)
    return y.astype(x.dtype), lax.pmean(aux, axis_name)
