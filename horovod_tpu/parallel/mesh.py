"""Hybrid mesh construction — the TPU analogue of communicator topology.

The reference builds three MPI communicators: world, local (shared-memory
split) and cross (one rank per node) — reference:
horovod/common/operations.cc:1668-1705. On TPU the analogous split is the
physical network tier: ICI links chips within a slice, DCN links slices.
:func:`two_tier_mesh` builds exactly that 2-D mesh; :func:`hybrid_mesh`
generalizes to arbitrary named parallelism axes (dp/fsdp/pp/tp/sp/ep).

Axis ordering convention: later (inner) axes vary fastest over the device
list, and ``jax.experimental.mesh_utils`` maps them to physically adjacent
chips — so put the bandwidth-hungry axes (tp, sp) last and the
latency-tolerant ones (dp, pp) first.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

ICI_AXIS = "ici"  # reference: local_comm (intra-node NCCL tier)
DCN_AXIS = "dcn"  # reference: cross_comm (inter-node MPI tier)


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """Canonical axis names for hybrid meshes."""

    dp: str = "dp"      # data parallel (gradient allreduce)
    fsdp: str = "fsdp"  # fully-sharded data parallel (params reduce-scattered)
    pp: str = "pp"      # pipeline stages
    tp: str = "tp"      # tensor parallel (within matmuls)
    sp: str = "sp"      # sequence/context parallel (ring attention)
    ep: str = "ep"      # expert parallel (MoE all-to-all)


def hybrid_mesh(
    axes: Mapping[str, int],
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
) -> Mesh:
    """Build a Mesh with the given ``{axis_name: size}`` (insertion order =
    major→minor). Sizes of 1 are kept (harmless, makes specs uniform).

    On real TPUs ``mesh_utils.create_device_mesh`` aligns logical axes with
    the physical torus so inner axes ride ICI neighbours; on CPU/host
    platforms a plain reshape of the device list is used.
    """
    if devices is None:
        devices = jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {n} devices, got {len(devices)}")
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=list(devices),
                allow_split_physical_axes=allow_split_physical_axes)
        except (ValueError, NotImplementedError) as e:
            import warnings

            warnings.warn(
                f"mesh_utils.create_device_mesh failed for {dict(axes)} "
                f"({e}); falling back to device-list order. Logical axes "
                "will NOT be aligned with the physical ICI torus — expect "
                "degraded collective bandwidth.", RuntimeWarning)
            dev_array = np.asarray(list(devices)).reshape(shape)
    else:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def two_tier_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """(dcn, ici) mesh mirroring the reference's cross/local communicators
    (reference: operations.cc:1668-1705): ``ici`` spans each process's local
    chips, ``dcn`` spans processes. Requires a homogeneous topology, exactly
    as the reference's hierarchical path does (operations.cc:1760-1778)."""
    if devices is None:
        devices = jax.devices()
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in by_proc.values()}
    if len(counts) != 1:
        raise ValueError(
            "two_tier_mesh requires every process to own the same number of "
            "chips (reference homogeneity check, operations.cc:1760-1778)")
    local = counts.pop()
    rows = [by_proc[p] for p in sorted(by_proc)]
    dev_array = np.asarray(rows, dtype=object).reshape(len(rows), local)
    return Mesh(dev_array, (DCN_AXIS, ICI_AXIS))
