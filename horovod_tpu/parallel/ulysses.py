"""Ulysses-style sequence parallelism: all-to-all head/sequence re-shard.

The alternative context-parallel scheme to ring attention: instead of
rotating K/V, re-shard — each chip trades its sequence shard of *all* heads
for the *full* sequence of ``heads/P`` heads (one ``all_to_all``), runs
ordinary attention on its heads, then re-shards back. Communication is
2 all-to-alls of activation size regardless of sequence length, which on a
TPU mesh rides ICI natively (no reference equivalent; SURVEY.md §5 notes
long-context is absent there).

Requires ``num_heads % axis_size == 0``. Shapes as in ring_attention:
(batch, seq_local, heads, head_dim) sequence-sharded over ``axis_name``.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax import lax

from horovod_tpu.models.transformer import dot_product_attention


def _seq_to_heads(x, axis_name):
    # (b, s/P, h, d) -> (b, s, h/P, d): scatter heads, gather sequence.
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis_name):
    # (b, s, h/P, d) -> (b, s/P, h, d)
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str,
                      attention_fn: Optional[Callable] = None,
                      bias=None):
    """Exact attention over a sequence sharded on ``axis_name`` via head
    re-sharding. ``attention_fn`` defaults to plain softmax attention and
    may be any (q, k, v, bias) -> out kernel (e.g. a pallas flash kernel) —
    it sees the full sequence and a head subset.

    ``bias``, if given, uses the same layout as :func:`ring_attention`'s:
    this chip's (b, heads, sq_local, seq_global) slice, query-sharded over
    ``axis_name``. It is re-sharded to (b, heads/P, seq_global, seq_global)
    alongside q/k/v.
    """
    h = q.shape[2]
    size = lax.psum(1, axis_name)
    if h % size != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by axis size ({size})")
    fn = attention_fn or dot_product_attention
    q, k, v = (_seq_to_heads(t, axis_name) for t in (q, k, v))
    if bias is not None:
        bias = lax.all_to_all(bias, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
    out = fn(q, k, v, bias)
    return _heads_to_seq(out, axis_name)
