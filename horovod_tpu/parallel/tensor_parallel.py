"""Megatron-style tensor parallelism as flax modules inside SPMD code.

Column-parallel Dense shards the output features (no communication: the
activation becomes feature-sharded); row-parallel Dense shards the input
features and allreduces the partial products. A column→row pair (the
standard MLP/attention pattern) costs exactly one ``psum`` on the forward
pass, and XLA inserts the mirror-image collectives for the backward pass.

No reference equivalent (data-parallel only, SURVEY.md §2.3) — this is TPU
scale-out scope. Modules must be applied inside ``shard_map`` with
``axis_name`` bound; parameter shapes are the per-chip shards, so the same
module works for any tp degree without padding logic (feature counts must
divide evenly — MXU tiling wants that anyway).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


def _axis_size(axis_name: str) -> int:
    # psum of the literal 1 is folded to a static int at trace time.
    return lax.psum(1, axis_name)


class ColumnParallelDense(nn.Module):
    """y = x @ W[:, shard]: output features sharded over ``axis_name``."""

    features: int  # global output features
    axis_name: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        size = _axis_size(self.axis_name)
        if self.features % size != 0:
            raise ValueError(
                f"features {self.features} not divisible by tp={size}")
        local = self.features // size
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], local), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (local,),
                              jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class RowParallelDense(nn.Module):
    """y = psum_tp(x_shard @ W[shard, :]): input features sharded, output
    replicated across the tp axis."""

    features: int
    axis_name: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        y = lax.psum(y, self.axis_name)
        if self.use_bias:
            # Bias added once, after the reduction.
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class ParallelMLP(nn.Module):
    """Transformer MLP block, tensor-parallel: column(4H) → act → row(H),
    one forward psum."""

    hidden_dim: int
    mlp_dim: int
    axis_name: str = "tp"
    dtype: Any = jnp.bfloat16
    act: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.mlp_dim, self.axis_name,
                                dtype=self.dtype, name="wi")(x)
        h = self.act(h)
        return RowParallelDense(self.hidden_dim, self.axis_name,
                                dtype=self.dtype, name="wo")(h)
