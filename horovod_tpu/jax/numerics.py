"""Traced (in-step) gradient-health instrumentation for the compiled
hot path.

The host-side numerics observatory (:mod:`horovod_tpu.core.numerics`)
wants three things the compiled step already holds for free: the global
gradient norm, per-dtype-bucket norms + nonfinite counts, and a per-rank
nonfinite attribution vector. Computing them *inside* the existing
shard_map step piggybacks on buffers that are already HBM-resident (the
packed per-dtype gradient buckets of :mod:`horovod_tpu.jax.sharded` /
the reduced gradient tree of ``DistributedOptimizer``), so the extra HBM
traffic is a handful of scalar reductions — near zero against a
gradient-sized step. With ``HVD_NUMERICS=off`` none of this code runs
and the lowered HLO is pinned identical to the uninstrumented step
(tests/test_numerics.py) — the bench headline path never pays for it.

Mechanism: the optimizer wrappers (``DistributedOptimizer``,
``shard_update``) compute the stats mid-trace and :func:`stash_traced`
them; the keras Trainer's traced step body :func:`collect_traced`-s them
right after ``opt.update`` — same trace, so the tracers are live — and
returns them as device-resident step outputs the host fetches on the
``HVD_NUMERICS_EVERY`` cadence (every step under ``halt``).

Halt guard: under ``HVD_NUMERICS=halt`` the wrappers select the update
away when the reduced gradient carries any nonfinite value — the skip
updates are **negative zero** (``p + (-0.0) == p`` bitwise for every
float p, including ``+0.0``/``-0.0``, where a ``+0.0`` skip would flip
``-0.0`` params) and the optimizer state is re-selected to its input
leaves, so a poisoned step provably mutates nothing. The predicate is a
cross-replica psum (identical on every rank), so both sides of the
select trace uniformly.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
from jax import lax

# One pending health dict per thread: the wrapper stashes during
# opt.update tracing, the Trainer collects later in the SAME trace.
# Uncollected stashes (a user loop that never collects) are simply
# overwritten by the next trace.
_slot = threading.local()


def stash_traced(health: dict):
    _slot.value = health


def collect_traced():
    """Pop the health dict the optimizer wrapper stashed during this
    trace (None when the wrapper did not run / policy off)."""
    out = getattr(_slot, "value", None)
    _slot.value = None
    return out


def _count_nonfinite(x):
    """Number of non-finite elements, int32 (0 for non-float leaves —
    integer buffers cannot hold NaN/Inf)."""
    if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
        return jnp.zeros((), jnp.int32)
    return jnp.sum(~jnp.isfinite(x)).astype(jnp.int32)


def _sumsq(x):
    if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
        return jnp.zeros((), jnp.float32)
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf)


def bucket_stats(bufs: dict, ax=None) -> dict:
    """Per-bucket ``{"sumsq", "nonfinite"}`` over a dict of flat buffers
    (the per-dtype gradient buckets). With a bound rank axis ``ax`` the
    stats are psum'd — for 1/N shards that IS the whole-buffer figure."""
    out = {}
    for k, v in bufs.items():
        ss, nf = _sumsq(v), _count_nonfinite(v)
        if ax is not None:
            ss = lax.psum(ss, ax)
            nf = lax.psum(nf, ax)
        out[k] = {"sumsq": ss, "nonfinite": nf}
    return out


def tree_buckets(tree) -> dict:
    """Group a pytree's leaves into per-dtype-name buckets (the same
    bucketing rule the fused/sharded packers use — one bucket per
    dtype), each a list of leaves."""
    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        out.setdefault(jnp.result_type(leaf).name, []).append(leaf)
    return out


def tree_stats(tree, ax=None) -> dict:
    """:func:`bucket_stats` over a pytree, bucketed per dtype name."""
    out = {}
    for k, leaves in tree_buckets(tree).items():
        ss = sum((_sumsq(l) for l in leaves), jnp.zeros((), jnp.float32))
        nf = sum((_count_nonfinite(l) for l in leaves),
                 jnp.zeros((), jnp.int32))
        if ax is not None:
            ss = lax.psum(ss, ax)
            nf = lax.psum(nf, ax)
        out[k] = {"sumsq": ss, "nonfinite": nf}
    return out


def per_rank_nonfinite(local_tree_or_bufs, ax):
    """(world,) vector of each rank's LOCAL nonfinite count (summed over
    buckets) — the attribution signal: computed on the pre-reduction
    local gradients, all_gathered so every rank can name the offender."""
    leaves = jax.tree_util.tree_leaves(local_tree_or_bufs)
    total = sum((_count_nonfinite(l) for l in leaves),
                jnp.zeros((), jnp.int32))
    return lax.all_gather(total, ax, axis=0, tiled=False)


def health_of(stats: dict, per_rank=None) -> dict:
    """Assemble the step-health dict from per-bucket stats: global grad
    norm, per-bucket norms and nonfinite counts, the all-finite halt
    predicate, and (when available) the per-rank attribution vector."""
    total_ss = sum((v["sumsq"] for v in stats.values()),
                   jnp.zeros((), jnp.float32))
    total_nf = sum((v["nonfinite"] for v in stats.values()),
                   jnp.zeros((), jnp.int32))
    health = {
        "grad_norm": jnp.sqrt(total_ss),
        "nonfinite": total_nf,
        "buckets": {k: {"norm": jnp.sqrt(v["sumsq"]),
                        "nonfinite": v["nonfinite"]}
                    for k, v in stats.items()},
    }
    if per_rank is not None:
        health["per_rank_nonfinite"] = per_rank
    return health


def all_finite(stats: dict):
    total_nf = sum((v["nonfinite"] for v in stats.values()),
                   jnp.zeros((), jnp.int32))
    return total_nf == 0


def _neg_zero_like(u):
    if jnp.issubdtype(jnp.result_type(u), jnp.floating):
        return jnp.full_like(u, -0.0)
    return jnp.zeros_like(u)


def guard_updates(finite, updates):
    """Halt-policy select: the skip branch emits NEGATIVE zero so
    ``optax.apply_updates``'s ``p + u`` is a bitwise no-op for every
    float param (``-0.0 + -0.0 == -0.0``; a ``+0.0`` skip would flip
    ``-0.0`` params to ``+0.0``)."""
    return jax.tree_util.tree_map(
        lambda u: jnp.where(finite, u, _neg_zero_like(u)), updates)


def guard_state(finite, new_state, old_state):
    """Halt-policy select on the optimizer state: a poisoned step must
    not advance momentum/masters either (NaN m/v would poison every
    later step even after the gradients recover)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(finite, n, o), new_state, old_state)


def norm(tree):
    """Global L2 norm of a pytree's float leaves (f32 accumulate)."""
    ss = sum((_sumsq(l) for l in jax.tree_util.tree_leaves(tree)),
             jnp.zeros((), jnp.float32))
    return jnp.sqrt(ss)


def _lex_bits(x):
    """Map a float array to monotonically ordered UNSIGNED ints (the
    standard IEEE total-order trick): ULP distance becomes integer
    subtraction. Unsigned on purpose — exact at every magnitude without
    x64 (a signed-int64 spelling would silently truncate to int32 on
    the default CPU config and wrap for NaN↔finite distances)."""
    bits = x.dtype.itemsize * 8
    if bits not in (16, 32):
        raise ValueError(
            f"max_ulp supports 16/32-bit floats (the resident/master "
            f"dtypes), got {x.dtype}")
    ui = lax.bitcast_convert_type(
        x, {16: jnp.uint16, 32: jnp.uint32}[bits])
    sign = jnp.asarray(1 << (bits - 1), ui.dtype)
    return jnp.where(ui & sign != 0, ~ui, ui | sign)


def max_ulp(a, b):
    """Max ULP distance between two same-dtype float arrays (0 when
    bitwise equal; NaN anywhere reads as a huge distance — it IS).
    ``max(lex) - min(lex)`` per element keeps the subtraction inside the
    unsigned range: exact everywhere, no abs-of-wrapped-difference."""
    if a.dtype != b.dtype:
        raise ValueError(f"dtype mismatch: {a.dtype} vs {b.dtype}")
    la, lb = _lex_bits(a), _lex_bits(b)
    if la.size == 0:
        return jnp.zeros((), la.dtype)
    return jnp.max(jnp.maximum(la, lb) - jnp.minimum(la, lb))
