"""JAX frontend — the flagship (reference: horovod/tensorflow/__init__.py).

The reference wraps TF optimizers so each gradient is allreduced through the
background engine at session-run time. On TPU the idiomatic design compiles
gradient reduction *into* the training step: :func:`DistributedOptimizer`
wraps an optax transform whose ``update`` fuses all gradients into per-dtype
buffers and allreduces them with one XLA collective each, and
:func:`jit` compiles the user's step over the world mesh so those collectives
ride ICI. All verbs also work eagerly for host-side code.
"""

from __future__ import annotations

import pickle
import time as _time
from typing import Any, Callable, Optional

import jax as _jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    cross_size,
    cross_rank,
    num_processes,
    process_index,
    mesh,
    devices,
    mpi_threads_supported,
)
from horovod_tpu.ops import collectives as _C
from horovod_tpu.ops.collectives import (  # noqa: F401
    HVD_AXIS,
    axis_rank,
    allgather,
    broadcast,
    reducescatter,
    alltoall,
    broadcast_pytree,
    fetch,
    grouped_allreduce,
)
from horovod_tpu.jax import compression as _compression
from horovod_tpu.jax import quantize as _quantize
from horovod_tpu.jax.compression import Compression, Compressor  # noqa: F401
from horovod_tpu.jax.fused import (  # noqa: F401
    canonical_state_dtype,
    cast_resident_params,
    fuse,
    state_storage,
)
from horovod_tpu.jax.sharded import (  # noqa: F401
    has_master_shards,
    resident_from_masters,
    shard_update,
    sharded_state_specs,
    unwrap_error_feedback,
)

from horovod_tpu.common.compat import shard_map as _shard_map
from horovod_tpu.jax import mpi_ops  # noqa: F401  — engine-path async
# verbs (allreduce_async/synchronize/... with zero-copy donate=True)
from horovod_tpu.core import numerics as _num
from horovod_tpu.core import sentinel as _sentinel
from horovod_tpu.core import telemetry as _tele
from horovod_tpu.jax import numerics as _jnum

try:
    from jax.experimental import sparse as _jsparse

    _BCOO = _jsparse.BCOO
except Exception:  # pragma: no cover
    _jsparse = None
    _BCOO = ()


# ---------------------------------------------------------------------------
# allreduce with compression + sparse path
# ---------------------------------------------------------------------------

def _is_sparse(x) -> bool:
    return _jsparse is not None and isinstance(x, _BCOO)


def allreduce(
    tensor,
    average: bool = True,
    name: Optional[str] = None,
    compression=Compression.none,
    sparse_as_dense: bool = False,
):
    """Allreduce with optional wire compression and a sparse path.

    Sparse (BCOO) tensors are summed by allgathering values+indices —
    duplicate indices sum implicitly, exactly the reference's
    IndexedSlices→allgather strategy (reference:
    horovod/tensorflow/__init__.py:73-84). ``sparse_as_dense`` densifies
    first (reference: :184-203).

    ``compression`` accepts cast compressors (wrap the psum), quantized
    block-scaled policies (``Compression.int8``/``fp8`` — the collective
    itself changes shape: quantize → int8 reduce-scatter phase →
    dequantize-accumulate → requantize → int8 all-gather, see
    :mod:`horovod_tpu.jax.quantize`; this stateless surface carries no
    error-feedback residual), and ``Compression.select(...)`` per-tensor
    containers resolved by ``name``.
    """
    compression = _compression.for_tensor(compression, name)
    if _is_sparse(tensor):
        if sparse_as_dense:
            return allreduce(tensor.todense(), average, name, compression)
        data = allgather(tensor.data)
        indices = allgather(tensor.indices)
        if average:
            data = data / _world_size_like(data)
        return _BCOO((data, indices), shape=tensor.shape)
    if _C._topo._require_init().size == 1:
        # Single-rank world: the reduction is identity; skip the wire
        # compression round trip too (it would be a lossy cast — or a
        # lossy quantize/dequantize — for nothing; the reference
        # likewise short-circuits size 1).
        out = jnp.asarray(tensor)
        if not _C.in_spmd(out):  # tracers: trace-time, not per-step
            _C._record_eager("allreduce", out, elided=True)
        return out
    if getattr(compression, "quantized", False):
        if jnp.issubdtype(jnp.result_type(tensor), jnp.floating):
            if _C.in_spmd(tensor):
                ax = _C.rank_axes()
                if ax is None:
                    _C._require_axis("allreduce")
                if (isinstance(ax, tuple)
                        and _C.hierarchical_allreduce_enabled()):
                    # Two-tier composition: the ICI phase reduce-scatters
                    # at the resident dtype and ONLY the 1/L shard
                    # crosses the DCN tier block-scaled (payload+scales)
                    # — the quantized wire applied where the bytes hurt.
                    from horovod_tpu.parallel.hierarchical import (
                        hierarchical_allreduce as _hier_ar,
                    )

                    return _hier_ar(tensor, average=average,
                                    dcn_policy=compression)
                return _quantize.spmd_allreduce(tensor, ax, average,
                                                compression)
            _C._record_eager("allreduce", jnp.asarray(tensor))
            return _quantize.eager_allreduce(tensor, average, compression)
        # Non-float payloads have no quantized form: ship full width
        # (the engine data plane makes the same call) instead of
        # tripping the quantized compressor's deliberate
        # NotImplementedError.
        return _C.allreduce(tensor, average=average, name=name)
    tensor, ctx = compression.compress(tensor)
    out = _C.allreduce(tensor, average=average, name=name)
    return compression.decompress(out, ctx)


def _world_size_like(x):
    st = _C._topo._require_init()
    return jnp.asarray(st.size, x.dtype) if not isinstance(x, _jax.core.Tracer) else st.size


def allreduce_pytree(tree, average: bool = True, compression=Compression.none,
                     sparse_as_dense: bool = False):
    """Fused allreduce over a pytree with per-leaf compression. The fusion
    (per-dtype flat buffers) is the compile-time analogue of the reference's
    64 MB fusion buffer (reference: operations.cc:2035-2074)."""
    if _C._topo._require_init().size == 1:
        # Identity at world size 1 — per-leaf allreduce (which itself
        # short-circuits before the compression round trip) elides the
        # per-dtype concatenate -> all-reduce -> slice chain that XLA
        # does NOT simplify away (a full extra HBM round trip of the
        # gradient tree per step on a one-chip bench; docs/benchmarks.md
        # "HBM diet") while keeping the N>1 leaf semantics: dense leaves
        # become jax arrays, sparse leaves densify under sparse_as_dense.
        leaves, treedef = _jax.tree_util.tree_flatten(tree)
        return _jax.tree_util.tree_unflatten(
            treedef, [allreduce(l, average, None, compression,
                                sparse_as_dense) for l in leaves])
    leaves, treedef = _jax.tree_util.tree_flatten(tree)
    dense_idx, sparse_idx = [], []
    for i, l in enumerate(leaves):
        (sparse_idx if _is_sparse(l) else dense_idx).append(i)
    out = list(leaves)
    if dense_idx and getattr(compression, "quantized", False):
        # Quantized policy: fuse per dtype as usual, then run the
        # quantized collective pipeline on each flat buffer (the policy
        # replaces the collective, it does not wrap it).
        reduced = _C._grouped_apply(
            lambda flat: allreduce(flat, average, None, compression),
            [leaves[i] for i in dense_idx])
        for i, r in zip(dense_idx, reduced):
            out[i] = r
    elif dense_idx:
        comp = [compression.compress(leaves[i]) for i in dense_idx]
        reduced = _C.grouped_allreduce([c[0] for c in comp], average=average)
        for i, r, (_, ctx) in zip(dense_idx, reduced, comp):
            out[i] = compression.decompress(r, ctx)
    for i in sparse_idx:
        out[i] = allreduce(leaves[i], average, None, compression, sparse_as_dense)
    return _jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Parameter/state sync (reference §3.4 startup broadcast)
# ---------------------------------------------------------------------------

def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` (reference:
    horovod/tensorflow/__init__.py:96-115 broadcast_global_variables,
    horovod/torch/__init__.py:185-214)."""
    return broadcast_pytree(params, root_rank=root_rank)


# TF-compat alias: in JAX variables are explicit, so this takes the pytree.
broadcast_global_variables = broadcast_parameters


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optax optimizer state (reference:
    horovod/torch/__init__.py:217-333 — the reference must tensor-ize
    scalar hyperparameters; optax states are already pytrees of arrays, so
    this is the same fused broadcast)."""
    return broadcast_pytree(opt_state, root_rank=root_rank)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """Broadcast an arbitrary picklable object (rank-0 config, epoch
    counters — the reference examples hand-roll this with scalar bcasts,
    e.g. examples/pytorch_imagenet_resnet50.py:70-80)."""
    st = _C._topo._require_init()
    if st.num_processes == 1:
        # Single controller: every rank already holds the same host object.
        _check = _C._check_root(root_rank)
        return obj
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # Phase 1: root broadcasts the byte length (same shape on every rank).
    n = int(np.asarray(
        _C.broadcast(jnp.asarray([payload.size], jnp.int32), root_rank)
    )[0])
    # Phase 2: pad/crop to root's length and broadcast the bytes.
    buf = np.zeros((n,), np.uint8)
    buf[: min(n, payload.size)] = payload[:n]
    out = np.asarray(_C.broadcast(jnp.asarray(buf), root_rank))
    return pickle.loads(out.tobytes())


# ---------------------------------------------------------------------------
# DistributedOptimizer / gradient transforms
# ---------------------------------------------------------------------------

# One zero tree per (structure, shapes, dtypes, shardings): the
# accumulation skip path must not allocate-and-write a fresh param-sized
# zero tree every non-boundary microstep (it returns the SAME buffers
# each time — the updates contract only promises values, not fresh
# arrays). Bounded: param-sized device buffers must not outlive a shape
# sweep, so old structures are evicted FIFO.
_ZERO_TREES: dict = {}
_ZERO_TREES_MAX = 8


def _zeros_like_in(dtype):
    """``zeros_like`` honoring a ``state_dtype`` policy: float leaves
    get ``dtype`` zeros instead of their own width, so the gradient
    accumulator cannot silently park a full-width f32 buffer in HBM
    (``acc_init``; f32 grads can't promote it — ``acc_update`` casts
    the sum back)."""
    if dtype is None:
        return jnp.zeros_like

    def one(leaf):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            return jnp.zeros(jnp.shape(leaf), dtype)
        return jnp.zeros_like(leaf)

    return one


def _cached_zero_tree(tree):
    leaves, treedef = _jax.tree_util.tree_flatten(tree)
    if any(isinstance(l, _jax.core.Tracer) for l in leaves):
        # Traced (the lax.cond branch): zeros_like stays a broadcast-of-0
        # — XLA's cheapest form, fusable into the consuming add. A cached
        # concrete tree here would bake a param-sized CONSTANT into the
        # executable instead.
        return _jax.tree.map(jnp.zeros_like, tree)
    key = (treedef,
           tuple((jnp.shape(l), str(jnp.result_type(l)),
                  str(getattr(l, "sharding", None)))
                 for l in leaves))
    z = _ZERO_TREES.get(key)
    if z is None:
        while len(_ZERO_TREES) >= _ZERO_TREES_MAX:
            _ZERO_TREES.pop(next(iter(_ZERO_TREES)))
        z = _ZERO_TREES[key] = _jax.tree.map(jnp.zeros_like, tree)
    return z

def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    name: Optional[str] = None,
    average: bool = True,
    compression=Compression.none,
    sparse_as_dense: bool = False,
    backward_passes_per_step: int = 1,
    fused_update: bool = False,
    sharded_update: bool = False,
    state_dtype=None,
):
    """Wrap an optax transform so gradients are allreduced (fused, with
    compression) before the update (reference: horovod/tensorflow/
    __init__.py:152-250 DistributedOptimizer overriding compute_gradients;
    accumulation mirrors torch's backward_passes_per_step,
    horovod/torch/__init__.py:66-78).

    ``fused_update=True`` additionally runs the *update itself* on
    per-dtype fused buffers (:func:`horovod_tpu.jax.fuse`): ~N tiny
    per-parameter XLA fusions collapse into a couple of large ones —
    worth ~20% of a ResNet-50 step on TPU. Valid for elementwise
    transforms (sgd/momentum/adam/...); keep it off for shape-dependent
    ones (adafactor, LARS).

    ``sharded_update=True`` replaces allreduce + replicated update with
    reduce-scatter -> update a 1/N shard of params/state -> all-gather
    (:func:`horovod_tpu.jax.shard_update`; arxiv 2004.13336): per-chip
    optimizer-state HBM read/write drops by ~(N-1)/N. The optimizer
    state becomes per-dtype flat buffers padded to a world-size multiple
    — lay them out ``P('hvd')`` in the compiled step via
    :func:`sharded_state_specs`. Subsumes ``fused_update`` (the whole
    tree is packed); valid for per-coordinate transforms ONLY (a
    shard-local ``clip_by_global_norm`` would be wrong — see
    sharded.py).

    ``state_dtype='bf16'`` (HBM diet round 2, arxiv 2004.13336 §4 +
    1909.09756) keeps the resident state in the reduced dtype: with
    ``sharded_update`` the params/opt-state live in bf16 HBM and f32
    master weights exist only as each chip's 1/N shard
    (:func:`horovod_tpu.jax.shard_update`); on the fused/plain paths the
    optimizer state is *stored* reduced and *computed* f32
    (:func:`horovod_tpu.jax.state_storage` — no masters: see
    docs/troubleshooting.md on drift). Cast your resident params to the
    policy dtype before ``init`` (the Trainer and bench wiring do).

    ``compression`` accepts a registry name (``'int8'``, ``'int8_ef'``,
    ``'fp8'``, ``'bf16'``, ...) or a compressor; unknown spellings fail
    FAST here, naming the rank (a bad object used to surface as an
    attribute error mid-step). Quantized policies change the collective
    shape (quantize → int8 reduce-scatter phase → dequantize-accumulate
    → requantize → int8 all-gather); ``int8_ef``'s error-feedback
    residual needs the optimizer-state carrier, so it requires
    ``sharded_update=True`` (the stateless paths run ``int8``/``fp8``
    without a residual)."""
    compression = Compression.resolve(compression)
    _sdt = canonical_state_dtype(state_dtype)
    if (getattr(compression, "quantized", False)
            and compression.error_feedback and not sharded_update):
        raise ValueError(
            "Compression.int8_ef needs an optimizer-state carrier for "
            "its error-feedback residual: use sharded_update=True, or "
            "pick Compression.int8 (no residual) for the plain path")
    if sharded_update:
        if backward_passes_per_step > 1:
            # The accumulation wrapper's state ({'inner', 'acc', 'count'})
            # interleaves param-structured accumulators with the sharded
            # flat buffers — sharded_state_specs cannot tell them apart,
            # so a divisible-sized accumulator would silently ride
            # P('hvd') and shard a buffer every rank needs whole.
            raise ValueError(
                "sharded_update does not compose with "
                "backward_passes_per_step > 1: accumulate before the "
                "optimizer, or use fused_update")
        # Reduction happens inside the wrapper (reduce-scatter on the
        # packed buffers), so there is no separate allreduce here.
        optimizer = shard_update(optimizer, average=average,
                                 compression=compression,
                                 state_dtype=_sdt)
        update = optimizer.update
    else:
        if fused_update:
            optimizer = fuse(optimizer, state_dtype=_sdt)
        elif _sdt is not None:
            # Unfused path: no packing, but the state storage policy
            # still applies (m/v stored reduced, computed f32).
            optimizer = state_storage(optimizer, _sdt)

        # In-step gradient health (core/numerics.py) is computed on the
        # REDUCED gradients this closure already holds — but not under
        # the accumulation wrapper: its lax.cond would trap the stashed
        # tracers inside a branch (the Trainer falls back to local-grad
        # health there).
        in_acc = backward_passes_per_step > 1

        def update(grads, state, params=None, **kwargs):
            pol = "off" if in_acc else _num.policy()
            local = grads
            grads = allreduce_pytree(
                grads, average=average, compression=compression,
                sparse_as_dense=sparse_as_dense,
            )
            if pol == "off":
                return optimizer.update(grads, state, params, **kwargs)
            leaves = _jax.tree_util.tree_leaves(grads)
            ax = (_C.rank_axes()
                  if leaves and _C.in_spmd(leaves[0]) else None)
            # Reduced grads are already global (identical on every
            # rank): their stats need no psum. NaN/Inf from ANY rank
            # survives the reduction, so the nonfinite counts see it;
            # the per-rank vector (pre-reduction local counts,
            # all_gathered) names the offender.
            stats = _jnum.tree_stats(grads)
            per_rank = (_jnum.per_rank_nonfinite(local, ax)
                        if ax is not None else None)
            upd, new_state = optimizer.update(grads, state, params,
                                              **kwargs)
            if pol == "halt":
                finite = _jnum.all_finite(stats)
                upd = _jnum.guard_updates(finite, upd)
                new_state = _jnum.guard_state(finite, new_state, state)
            health = _jnum.health_of(stats, per_rank)
            if leaves and _C.in_spmd(leaves[0]):
                _jnum.stash_traced(health)
            else:
                _num.note_step_health(
                    _jax.device_get(health), origin="eager")
            return upd, new_state

    if backward_passes_per_step <= 1:
        return optax.GradientTransformationExtraArgs(optimizer.init, update)

    # Accumulate locally; the collective and inner update fire only on step
    # boundaries (reference: torch/__init__.py:66-78). Hand-rolled rather
    # than optax.MultiSteps: its lax.cond would trace our collective outside
    # the 'hvd' axis in eager use; here the branch is Python when eager and
    # lax.cond when traced (all ranks hold the same count, so the branch is
    # uniform across the mesh).
    k = backward_passes_per_step

    def acc_init(params):
        return {
            "inner": optimizer.init(params),
            # Accumulators honor state_dtype (a skipped microbatch must
            # not park a full-width f32 gradient tree in HBM).
            "acc": _jax.tree.map(_zeros_like_in(_sdt), params),
            "count": jnp.zeros((), jnp.int32),
        }

    def acc_update(grads, state, params=None, **kwargs):
        # Cast the sum back to the accumulator dtype: a wider grad leaf
        # (f32 grads under a bf16 policy) would otherwise promote the
        # accumulator and change the state structure mid-training.
        acc = _jax.tree.map(lambda a, g: (a + g).astype(a.dtype),
                            state["acc"], grads)
        count = state["count"] + 1

        def apply_fn(operand):
            acc_, inner_ = operand
            mean = _jax.tree.map(lambda a: a / k, acc_)
            upd, new_inner = update(mean, inner_, params, **kwargs)
            return upd, {
                "inner": new_inner,
                "acc": _jax.tree.map(jnp.zeros_like, acc_),
                "count": jnp.zeros((), jnp.int32),
            }

        def skip_fn(operand):
            acc_, inner_ = operand
            # The skip branch's zeros must type-match the apply branch's
            # updates. Under the policy those follow the PARAM width when
            # params ride along (state_storage casts them there) and the
            # ACCUMULATOR width otherwise (the mean state_storage's
            # grad-width rule sees IS the policy-dtype accumulator — raw
            # f32 grads would mismatch). Deriving from params (not
            # forcing the policy dtype) keeps the bf16 diet for
            # compliant callers — residents ARE the policy width — while
            # an uncast-f32-params caller still gets a working step
            # instead of a cryptic lax.cond branch-type error.
            if _sdt is not None:
                ref = params if params is not None else acc_
            else:
                ref = grads
            return _cached_zero_tree(ref), {
                "inner": inner_,
                "acc": acc_,
                "count": count,
            }

        if isinstance(count, _jax.core.Tracer):
            return _jax.lax.cond(
                count % k == 0, apply_fn, skip_fn, (acc, state["inner"])
            )
        boundary = int(count) % k == 0
        return (apply_fn if boundary else skip_fn)((acc, state["inner"]))

    return optax.GradientTransformationExtraArgs(acc_init, acc_update)


def grad(fun: Callable, argnums=0, average: bool = True,
         compression=Compression.none, **jax_kwargs) -> Callable:
    """``jax.grad`` with distributed reduction — the functional analogue of
    DistributedGradientTape (reference: horovod/tensorflow/__init__.py:
    253-328)."""
    gfun = _jax.grad(fun, argnums=argnums, **jax_kwargs)

    def wrapped(*args, **kwargs):
        return allreduce_pytree(gfun(*args, **kwargs), average=average,
                                compression=compression)

    return wrapped


def value_and_grad(fun: Callable, argnums=0, average: bool = True,
                   compression=Compression.none, **jax_kwargs) -> Callable:
    gfun = _jax.value_and_grad(fun, argnums=argnums, **jax_kwargs)

    def wrapped(*args, **kwargs):
        v, g = gfun(*args, **kwargs)
        return v, allreduce_pytree(g, average=average, compression=compression)

    return wrapped


# DistributedGradientTape parity name.
DistributedGradientTape = value_and_grad


# ---------------------------------------------------------------------------
# SPMD compilation helper
# ---------------------------------------------------------------------------

class _InstrumentedJit:
    """Thin wrapper around the jitted step: each ``__call__`` records the
    dispatch latency (time to hand the program to the runtime — execution
    itself is async) into the telemetry ring buffer for the compiled path.
    Everything else (``lower``, ``trace``, AOT compilation, ...) delegates
    to the wrapped ``jax.jit`` object, so the perf-critical AOT path
    (``fn.lower(...).compile()`` — bench.py) bypasses instrumentation
    entirely. Overhead: two clock reads + a few deque appends/compares
    per dispatch (ring + the sentinel watchdog), ~1-2 µs against a
    ≥50 µs dispatch."""

    __slots__ = ("_jitted",)

    def __init__(self, jitted):
        self._jitted = jitted

    def __call__(self, *args, **kwargs):
        t0 = _time.perf_counter()
        out = self._jitted(*args, **kwargs)
        dt = _time.perf_counter() - t0
        _tele.REGISTRY.counter("jax.dispatches").inc()
        _tele.REGISTRY.ring("jax.dispatch_s").push(dt)
        # Performance sentinel: the per-call dispatch boundary is the
        # compiled path's watchdog signal (a recompile shows up as one
        # giant dispatch). The AOT path (lower().compile()) bypasses
        # this wrapper entirely — bench.py's hot window stays
        # uninstrumented.
        _sentinel.observe_step(dt, origin="jax.dispatch")
        return out

    def __getattr__(self, item):
        return getattr(self._jitted, item)


def _two_tier_specs(specs):
    """Rewrite every ``'hvd'`` PartitionSpec entry to the ``('dcn','ici')``
    axis pair so user specs written for the flat world mesh map unchanged
    onto the two-tier mesh (same devices, same order — rank identity is
    preserved)."""
    from jax.sharding import PartitionSpec as P

    def one_entry(e):
        if e == HVD_AXIS:
            return (_C.DCN_AXIS, _C.ICI_AXIS)
        if isinstance(e, tuple):
            out = []
            for a in e:
                out.extend((_C.DCN_AXIS, _C.ICI_AXIS) if a == HVD_AXIS
                           else (a,))
            return tuple(out)
        return e

    def one_spec(p):
        return P(*(one_entry(e) for e in p)) if isinstance(p, P) else p

    return _jax.tree_util.tree_map(
        one_spec, specs, is_leaf=lambda x: isinstance(x, P))


def jit(fn: Callable = None, *, in_specs, out_specs, static_argnums=(),
        donate_argnums=()):
    """Compile ``fn`` over the world mesh: ``shard_map`` with the ``'hvd'``
    rank axis bound (so in-step collectives lower to ICI collectives) under
    ``jax.jit``. This replaces the reference's runtime enqueue→negotiate→
    execute pipeline (SURVEY.md §3.2) with one compiled program.

    With ``HVD_HIERARCHICAL_ALLREDUCE`` on and a two-tier world, the step
    maps over the (dcn, ici) mesh instead (specs spelled with ``'hvd'``
    are rewritten) and in-step ``hvd.allreduce`` lowers to
    reduce-scatter(ICI) → psum(DCN) → all-gather(ICI) — the reference's
    hierarchical hot path (operations.cc:1194-1346) at compile time."""

    def wrap(f):
        if _C._hier_allreduce_active():
            sm = _shard_map(
                f, mesh=_C._topo.two_tier(),
                in_specs=_two_tier_specs(in_specs),
                out_specs=_two_tier_specs(out_specs), check_vma=False,
            )
        else:
            sm = _shard_map(
                f, mesh=mesh(), in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        return _InstrumentedJit(
            _jax.jit(sm, static_argnums=static_argnums,
                     donate_argnums=donate_argnums))

    return wrap if fn is None else wrap(fn)
