"""Trace-time fusion of the optimizer update — tensor fusion (reference:
horovod/common/fusion_buffer_manager.cc, operations.cc:2035-2074) applied
to the *parameter update* instead of the wire.

Why this exists: a ResNet-50 step updates ~160 parameter tensors, ~110 of
them tiny (BN scales/biases are 64-2048 floats). XLA lowers one fusion per
tensor, and on TPU each carries a fixed dispatch + HBM round-trip cost.
Concatenating the small ones of each dtype into a single flat vector turns
~110 launches into a couple of big bandwidth-bound fusions — the
economics of the reference's 64 MB fusion buffer, resolved at compile
time.

Why only the SMALL ones: large tensors gain nothing from packing (they
are already bandwidth-bound) and lose a lot — XLA fuses a weight-grad
convolution directly into its momentum/param update when the update
consumes the conv's output per-tensor; routing it through a concatenated
buffer severs that producer-consumer fusion and adds a full extra
HBM round-trip per step (measured: whole-tree packing REGRESSED ResNet-50
bs32 from 12.1 to 13.5 ms/step; small-only packing is the win). The
``threshold_elems`` knob is the compile-time analogue of the reference's
runtime fusion-threshold byte knob.

Correctness domain: any *elementwise* gradient transformation — one where
the update for element ``i`` depends only on gradient/state element ``i``
(sgd, momentum, adam(w), rmsprop, lion, ...). Global-norm clipping also
composes (the norm is global either way). Transforms that inspect
per-parameter *shapes* (adafactor's factored second moments, layerwise
LARS/LAMB trust ratios) must keep the unfused path.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

DEFAULT_THRESHOLD_ELEMS = 4096

# Spellings accepted for the state_dtype policy knob. None / f32 mean
# "off" (full-width f32 state, the pre-r7 behavior).
_STATE_DTYPE_OFF = (None, "f32", "float32")
_STATE_DTYPE_NAMES = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                      "f16": jnp.float16, "float16": jnp.float16}


def canonical_state_dtype(state_dtype):
    """Normalize a ``state_dtype`` policy spelling to a jnp dtype, or
    None when the policy is off. Accepts ``None``/``'f32'`` (off),
    ``'bf16'``/``'bfloat16'`` (the TPU-native reduced-precision layout,
    arxiv 1909.09756), ``'f16'``, or a floating jnp/numpy dtype."""
    if state_dtype in _STATE_DTYPE_OFF:
        return None
    if isinstance(state_dtype, str):
        try:
            return _STATE_DTYPE_NAMES[state_dtype]
        except KeyError:
            raise ValueError(
                f"state_dtype={state_dtype!r}: expected one of "
                f"{sorted(_STATE_DTYPE_NAMES)} or 'f32'/None") from None
    dt = jnp.dtype(state_dtype)
    if dt == jnp.dtype(jnp.float32):
        # jnp.float32/np.float32 mean "off", symmetric with the 'f32'
        # string spelling above.
        return None
    if not jnp.issubdtype(dt, jnp.floating) or dt.itemsize >= 4:
        raise ValueError(f"state_dtype={state_dtype!r} is not a "
                         "reduced-precision float dtype")
    return dt


def cast_resident_params(params, state_dtype):
    """Cast a parameter tree's float leaves to the resident ``state_dtype``
    policy width (non-float leaves untouched; identity when the policy is
    off). Call BEFORE ``optimizer.init`` — the f32 master shards (with
    ``sharded_update``) derive from the residents at init. The Trainer and
    bench wiring route through here; exported so third-party training
    loops apply the same rule. NOTE: batch-norm statistics live outside
    the param tree (keep them f32 — running moments accumulate badly in
    bf16)."""
    dtype = canonical_state_dtype(state_dtype)
    if dtype is None:
        return params
    return jax.tree_util.tree_map(
        lambda l: (l.astype(dtype)
                   if jnp.issubdtype(jnp.result_type(l), jnp.floating)
                   else l),
        params)


def _is_stored_leaf(leaf) -> bool:
    """True for the state leaves the storage policy applies to: non-scalar
    float buffers (m/v/trace and the packed param-shaped buffers). Scalar
    bookkeeping (adam's count, schedule steps) stays exact."""
    return (hasattr(leaf, "dtype") and jnp.ndim(leaf) >= 1
            and jnp.issubdtype(jnp.result_type(leaf), jnp.floating))


def store_state(state, dtype):
    """Downcast every non-scalar f32 state leaf to the storage ``dtype``
    — what lives in HBM between steps."""
    if dtype is None:
        return state
    return jax.tree_util.tree_map(
        lambda l: (l.astype(dtype)
                   if _is_stored_leaf(l) and l.dtype == jnp.float32 else l),
        state)


def load_state(state, dtype):
    """Upcast the storage-``dtype`` leaves back to f32 for the update
    math (the converts fuse into the consuming op — no extra HBM pass)."""
    if dtype is None:
        return state
    return jax.tree_util.tree_map(
        lambda l: (l.astype(jnp.float32)
                   if _is_stored_leaf(l) and l.dtype == dtype else l),
        state)


def state_storage(optimizer: optax.GradientTransformation,
                  state_dtype) -> optax.GradientTransformationExtraArgs:
    """Wrap an elementwise optax transform so its state *storage* is
    ``state_dtype`` while its update *math* stays f32: every non-scalar
    float state buffer (momentum, Adam m/v) is downcast after init/update
    and upcast before the inner update runs. The MLPerf TPU recipes'
    bf16-resident layout (arxiv 1909.09756) applied to optimizer state —
    HBM read+write of the state halves, the arithmetic does not change
    dtype. Identity when ``state_dtype`` is None/'f32'.

    NOTE: without a master copy the *parameter* apply still rounds to the
    param dtype — pair with :func:`horovod_tpu.jax.shard_update`'s
    ``state_dtype`` for f32 master shards (docs/troubleshooting.md
    "bf16-state convergence drift"). The numerics observatory watches
    this masterless regime live: under ``HVD_NUMERICS`` the keras Trainer
    feeds the ``numerics.update_ratio`` gauge (||update||/||params||) —
    a sustained ratio below ~1 resident ulp means updates are being
    rounded away (core/numerics.py, docs/observability.md "Numerics")."""
    dtype = canonical_state_dtype(state_dtype)
    if dtype is None:
        return optax.with_extra_args_support(optimizer)
    optimizer = optax.with_extra_args_support(optimizer)

    def init(params):
        return store_state(optimizer.init(params), dtype)

    def update(grads, state, params=None, **extra_args):
        upd, new_state = optimizer.update(grads, load_state(state, dtype),
                                          params, **extra_args)
        # The f32 math would otherwise hand back a full-width f32 update
        # tree; emit updates at the param width (what optax.apply_updates
        # rounds to anyway) — or at the GRAD width when params are
        # omitted (standard optax convention; an f32-loaded momentum
        # trace would otherwise promote them) — so no full-width f32
        # buffer rides between update and apply, and so a lax.cond
        # accumulation-skip branch's zeros (param- or grad-width by the
        # same rule) type-match the apply branch.
        ref = params if params is not None else grads
        upd = jax.tree_util.tree_map(
            lambda u, r: u.astype(jnp.result_type(r)), upd, ref)
        return upd, store_state(new_state, dtype)

    return optax.GradientTransformationExtraArgs(init, update)


class _FusedLayout(NamedTuple):
    """Static description of how leaves pack into per-dtype buffers."""

    treedef: Any
    dtypes: tuple            # leaf dtype names, flatten order
    shapes: tuple            # leaf shapes, flatten order
    group_keys: tuple        # sorted dtype-name keys, one buffer each
    # per leaf: (group key, offset) for packed leaves, or None for
    # passthrough (large) leaves
    slots: tuple


def _nelems(shp) -> int:
    n = 1
    for d in shp:
        n *= d
    return n


def _layout_of(tree, threshold: int) -> _FusedLayout:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    dtypes = tuple(jnp.asarray(l).dtype.name for l in leaves)
    shapes = tuple(tuple(jnp.shape(l)) for l in leaves)
    offsets: dict = {}
    slots = []
    for dt, shp in zip(dtypes, shapes):
        n = _nelems(shp)
        if n >= threshold:
            slots.append(None)
            continue
        off = offsets.get(dt, 0)
        slots.append((dt, off))
        offsets[dt] = off + n
    return _FusedLayout(treedef, dtypes, shapes,
                        tuple(sorted(offsets)), tuple(slots))


def _pack(tree, layout: _FusedLayout, cast_small: bool = False):
    """Pytree → ``{"buf": {dtype_name: flat vector}, "big": [leaves]}``.
    ``cast_small`` casts packed leaves to the layout dtype (gradients of
    bf16-computed small params join the parameter-dtype buffer — standard
    master-weight mixed precision)."""
    leaves = jax.tree_util.tree_leaves(tree)
    groups: dict = {k: [] for k in layout.group_keys}
    big = []
    for i, leaf in enumerate(leaves):
        slot = layout.slots[i]
        if slot is None:
            big.append(leaf)
            continue
        dt = slot[0]
        leaf = jnp.asarray(leaf, dt) if cast_small else jnp.asarray(leaf)
        groups[dt].append(leaf.ravel())
    return {
        "buf": {k: (jnp.concatenate(v) if len(v) > 1 else v[0])
                for k, v in groups.items() if v},
        "big": big,
    }


def _unpack(packed, layout: _FusedLayout):
    """Inverse of :func:`_pack`: rebuild the original pytree."""
    leaves = []
    big = iter(packed["big"])
    for slot, shp in zip(layout.slots, layout.shapes):
        if slot is None:
            leaves.append(next(big))
            continue
        dt, off = slot
        n = _nelems(shp)
        leaves.append(packed["buf"][dt][off: off + n].reshape(shp))
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def fuse(optimizer: optax.GradientTransformation,
         threshold_elems: int = DEFAULT_THRESHOLD_ELEMS,
         state_dtype=None) -> optax.GradientTransformationExtraArgs:
    """Wrap an elementwise optax transform so tensors smaller than
    ``threshold_elems`` update through per-dtype fused buffers (see module
    docstring); larger tensors keep their per-tensor path, preserving
    XLA's grad-producer→update fusion.

    The optimizer state becomes the wrapped transform's state over the
    packed structure (small-tensor momenta fuse too). ``update`` accepts
    ``params``; ``**extra_args`` are forwarded UNCHANGED (transforms whose
    extra args mirror the parameter tree need the unfused path).

    ``state_dtype`` applies :func:`state_storage` to the inner transform:
    the packed (and passthrough) state buffers live in the reduced dtype
    between steps while the update math stays f32.
    """
    optimizer = state_storage(optimizer, state_dtype)
    # init()'s layout is keyed by PARAM dtypes; update() must reuse it even
    # when called without params (standard optax convention) — a layout
    # recomputed from grads would group by GRAD dtype and mismatch the
    # state structure whenever the two differ (bf16 grads, f32 masters).
    # Keyed by (treedef, shapes) so one fuse()d transform serves several
    # param trees.
    layouts: dict = {}

    def _layout_key(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return treedef, tuple(tuple(jnp.shape(l)) for l in leaves)

    def _remember(tree):
        key = _layout_key(tree)
        layout = layouts.get(key)
        if layout is None:
            layout = layouts[key] = _layout_of(tree, threshold_elems)
        return layout

    def init(params):
        return optimizer.init(_pack(params, _remember(params)))

    def update(grads, state, params=None, **extra_args):
        if params is not None:
            layout = _remember(params)
        else:
            # grads share the params' treedef/shapes, so init()'s cached
            # layout (param dtypes) is found by that key. The grads-derived
            # fallback (init ran in another process AND no params passed)
            # is deliberately NOT cached: its dtype grouping may be wrong
            # for the state, and caching it under the shared key would
            # poison later params-carrying calls.
            layout = (layouts.get(_layout_key(grads))
                      or _layout_of(grads, threshold_elems))
        # Small grads join the parameter-dtype buffers (bf16 compute
        # grads meet f32 master weights here, like the reference's fp16
        # compression decompressing into f32 before apply).
        pgrads = _pack(grads, layout, cast_small=True)
        pparams = None if params is None else _pack(params, layout)
        pupd, new_state = optimizer.update(pgrads, state, pparams,
                                           **extra_args)
        return _unpack(pupd, layout), new_state

    return optax.GradientTransformationExtraArgs(init, update)
