"""Cross-replica sharded weight update — reduce-scatter, update 1/N,
all-gather (arxiv 2004.13336 "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training"; the ZeRO-2 shape).

The Horovod pattern this repo reproduces allreduces the full gradient and
then runs the IDENTICAL optimizer update on every chip: each chip reads
and writes a full copy of the momentum/Adam state and the full parameter
tree every step, even though chip r only "owns" new information about
1/N of the reduced gradient. On a memory-bound step (ResNet-50 bs32 sits
at 87.6% of the practical HBM peak at 35.7% MFU — docs/benchmarks.md)
that redundancy is the dominant removable traffic: per-chip optimizer
read/write drops by ~(N-1)/N when the update is sharded.

:func:`shard_update` wraps an *elementwise* optax transform so that,
inside the compiled SPMD step:

1. gradients are packed into per-dtype flat buffers (the same packing
   :mod:`horovod_tpu.jax.fused` uses, applied to the WHOLE tree — the
   scatter needs one contiguous buffer per dtype) and zero-padded to a
   multiple of the world size,
2. the buffers go through ``lax.psum_scatter`` (reduce-scatter — half an
   allreduce of wire traffic; optional on-the-wire compression applies
   to the flat buffer exactly as it would to an allreduce),
3. the inner optax update runs on the 1/N shard of gradient, parameters
   and optimizer state (state buffers are (padded,) global arrays laid
   out ``P('hvd')`` over the mesh, so each chip holds — and reads and
   writes — only its own 1/N block),
4. the updated-parameter DELTA returns via tiled ``lax.all_gather`` (the
   other half of the allreduce's wire traffic), is un-padded, and
   unpacks to the caller's update pytree.

Called eagerly (no mesh axis bound), the wrapper reduces with a plain
allreduce and updates the full buffers — elementwise transforms make the
full update the concatenation of the per-shard updates, so eager and
SPMD trajectories agree and share one state structure.

Correctness domain: per-coordinate transforms (sgd, momentum, adam(w),
rmsprop, lion, ...). Transforms that aggregate ACROSS coordinates see
only the local shard under sharding — ``clip_by_global_norm`` would
compute a shard-local norm — and must stay on the replicated path (this
is stricter than :func:`horovod_tpu.jax.fuse`, where the norm stayed
global because every chip held every coordinate).

At world size 1 the scatter and gather are identity and the wrapper
degrades to whole-tree-packed :func:`fuse` — a measured NEGATIVE on one
chip (packing severs XLA's wgrad->update producer fusion; see
docs/benchmarks.md "HBM diet"). Shard when N > 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common import topology as _topo
from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.core import numerics as _num
from horovod_tpu.jax import numerics as _jnum
from horovod_tpu.jax import quantize as _Q
from horovod_tpu.jax.compression import Compression
from horovod_tpu.jax.fused import (
    _layout_of,
    _pack,
    _unpack,
    canonical_state_dtype,
    load_state,
    store_state,
)
from horovod_tpu.ops import collectives as _C

# Pack EVERY leaf: the reduce-scatter needs one contiguous buffer per
# dtype, so there is no passthrough tier (unlike fuse()'s small-only
# packing).
_PACK_ALL = 1 << 62


def _world() -> int:
    return _topo._require_init().size


def shard_update(
    optimizer: optax.GradientTransformation,
    average: bool = True,
    compression=Compression.none,
    state_dtype=None,
) -> optax.GradientTransformationExtraArgs:
    """Wrap ``optimizer`` so the gradient reduction AND the update are
    sharded across the world (module docstring). The returned transform
    replaces the allreduce: do NOT reduce gradients before calling it.

    ``init`` returns per-dtype flat state buffers zero-padded to a
    multiple of the world size; lay them out ``P('hvd')`` in the compiled
    step (:func:`sharded_state_specs` builds the spec tree) so each chip
    holds one 1/N block. ``average=False`` keeps the reduced sum, exactly
    like :func:`horovod_tpu.jax.allreduce`.

    ``state_dtype='bf16'`` (HBM diet round 2) adds the mixed-precision
    resident layout of arxiv 2004.13336 §4 / the MLPerf TPU recipes
    (arxiv 1909.09756): the caller keeps *resident* parameters in bf16
    (cast them before ``init``; the Trainer/bench wiring does), and the
    state becomes ``{"master": per-dtype f32 buffers, "inner": storage-
    dtype optax state}``. Both ride the ``sharded_state_specs`` path, so
    the f32 master weights exist ONLY as each chip's 1/N shard. Inside
    the compiled step the epilogue is fused: gradients reduce-scatter at
    their resident (bf16) width, ONLY the 1/N shard upcasts to f32, the
    inner update and the master apply run in f32 on the shard, and the
    resident-parameter delta all-gathers back at bf16 — no full-width
    f32 gradient, parameter or state buffer ever materializes. The f32
    master trajectory is bit-identical to replicated-f32 training for
    per-coordinate exact updates (SGD with dyadic sums); the resident
    params track ``bf16(master)`` within 1 ulp, re-anchored every step
    (the delta is computed against the actual resident shard, so the
    rounding does not accumulate). ``update`` REQUIRES ``params`` under
    this policy (the delta re-anchoring needs the resident values), and
    accepts a reserved ``lr_scale=<scalar>`` extra arg that scales the
    inner update before the master apply — the hook for LR
    warmup/schedule mechanisms, which cannot scale the returned
    resident delta post-hoc (the masters have already advanced; the
    next step's re-anchor would undo a caller-side scale).

    ``compression`` may be a cast compressor (bf16/fp16 — wraps the
    collective as before) or a block-scaled quantized policy
    (``Compression.int8`` / ``int8_ef`` / ``fp8`` —
    :mod:`horovod_tpu.jax.quantize`): the compiled step then lowers to
    quantize → int8 all-to-all (the reduce-scatter phase) →
    dequantize-accumulate in f32 → [1/N update] → requantize → int8
    all-gather → dequantize, every wire hop at ~1/4 of the f32 bytes
    (scales included). Buffers pad to a multiple of ``world * block`` so
    each rank's chunk is scale-block-aligned (zero blocks quantize to
    zero payload — padding stays reduction-neutral). ``int8_ef`` adds an
    error-feedback residual carried in optimizer state (the state
    becomes ``{"qres": ..., "base": <normal state>}``, riding
    :func:`sharded_state_specs` as each rank's own rows): the
    un-transmitted quantization error of this rank's gradient (and of
    its update shard on the gather side) is added back before the next
    quantization, keeping the long-run trajectory unbiased
    (docs/troubleshooting.md "int8 quantization convergence"). At world
    size 1 everything including quantize/dequantize elides. Under the
    ``state_dtype`` policy the two compose: the delta all-gather's
    quantization error lands in the residents and is corrected by the
    next step's master re-anchor.
    """
    sdt = canonical_state_dtype(state_dtype)
    if getattr(compression, "for_tensor", None) is not None:
        raise ValueError(
            "shard_update packs the whole tree into per-dtype buffers, "
            "so a per-tensor Compression.select(...) policy cannot "
            "apply — pass one uniform policy (per-tensor overrides live "
            "on the name-carrying surfaces: eager allreduce and the "
            "TF/torch frontends)")
    qpol = compression if getattr(compression, "quantized", False) else None
    ef = qpol is not None and qpol.error_feedback
    optimizer = optax.with_extra_args_support(optimizer)
    # Layout cache keyed like fuse(): init()'s param-dtype layout must
    # serve update() calls that omit params (grads share treedef/shapes).
    layouts: dict = {}

    def _layout_key(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return treedef, tuple(tuple(jnp.shape(l)) for l in leaves)

    def _remember(tree):
        key = _layout_key(tree)
        layout = layouts.get(key)
        if layout is None:
            layout = layouts[key] = _layout_of(tree, _PACK_ALL)
        return layout

    def _pad_multiple(world: int) -> int:
        # Quantized policies additionally align every rank's chunk to
        # the scale-block size (each shard's scales must split cleanly
        # in the all_to_all exchange). Zero padding stays reduction-
        # neutral: zero blocks quantize to zero payload.
        return world * qpol.block if qpol is not None else world

    def _pack_padded(tree, layout, multiple, cast_small=False):
        packed = _pack(tree, layout, cast_small=cast_small)
        # Same zero-pad-to-multiple contract as reducescatter's.
        return {k: _C._pad_dim0(v, multiple)
                for k, v in packed["buf"].items()}

    def _unpack_padded(bufs, layout):
        # _unpack indexes [off:off+n] per leaf, so trailing padding is
        # simply never read.
        return _unpack({"buf": bufs, "big": []}, layout)

    def init(params):
        world = _world()
        layout = _remember(params)
        pbufs = _pack_padded(params, layout, _pad_multiple(world))
        if sdt is None:
            base = optimizer.init({"buf": pbufs, "big": []})
        else:
            # Mixed layout: the f32 master copy of every resident buffer
            # (the ONLY f32 copy — it shards to 1/N per chip under
            # sharded_state_specs), plus the inner state init'd over the
            # masters (m/v derive from f32) then downcast to storage
            # dtype.
            master = {k: v.astype(jnp.float32) for k, v in pbufs.items()}
            inner = optimizer.init({"buf": master, "big": []})
            base = {"master": master, "inner": store_state(inner, sdt)}
        if not ef:
            return base
        # Error-feedback residuals: per-RANK rows (rank r's row is its
        # own un-transmitted quantization error), so the global (world,
        # n) arrays ride sharded_state_specs as P('hvd') and each chip
        # holds exactly its row inside the compiled step. "g" carries
        # the gradient (scatter-phase) residual over the full padded
        # buffer, "u" the update-shard (gather-phase) residual.
        qres = {
            "g": {k: jnp.zeros((world, v.shape[0]), jnp.float32)
                  for k, v in pbufs.items()},
            "u": {k: jnp.zeros((world, v.shape[0] // world), jnp.float32)
                  for k, v in pbufs.items()},
        }
        return {"qres": qres, "base": base}

    def _master_step(g32, state, resbufs, extra_args):
        """Fused mixed-precision epilogue on one block (the 1/N shard in
        SPMD, the full buffers eagerly): f32 inner update against the f32
        masters, master apply in f32, resident delta emitted at the
        resident width, re-anchored on the actual resident values so the
        bf16 rounding never accumulates.

        ``lr_scale`` (reserved extra arg): post-update scale applied to
        the inner update BEFORE the master apply. Under this policy the
        masters advance inside ``update`` and the return value is only a
        re-anchored resident delta, so a caller-side ``updates * scale``
        (the keras Trainer's LR warmup/schedule mechanism) cannot touch
        the trajectory — the scale must ride into the epilogue."""
        lr_scale = extra_args.pop("lr_scale", None)
        master = state["master"]
        inner = load_state(state["inner"], sdt)
        ushard, new_inner = optimizer.update(
            {"buf": g32, "big": []}, inner, {"buf": master, "big": []},
            **extra_args)
        if lr_scale is not None:
            # Skipped entirely when absent: a *1.0 would still be exact,
            # but the bitwise-equivalence pins deserve an untouched path.
            ushard = {"buf": {k: v * lr_scale
                              for k, v in ushard["buf"].items()},
                      "big": ushard["big"]}
        new_master = {k: master[k] + ushard["buf"][k] for k in master}
        ures = {k: (new_master[k] - resbufs[k].astype(jnp.float32))
                .astype(resbufs[k].dtype) for k in new_master}
        return ures, {"master": new_master,
                      "inner": store_state(new_inner, sdt)}

    def update(grads, state, params=None, **extra_args):
        world = _world()
        mult = _pad_multiple(world)
        if sdt is not None and params is None:
            raise ValueError(
                "shard_update(state_dtype=...) needs params on every "
                "update call: the resident-parameter delta re-anchors "
                "on the actual resident values")
        if ef:
            qres, state = state["qres"], state["base"]
        else:
            qres = None
        new_qres = ({"g": dict(qres["g"]), "u": dict(qres["u"])}
                    if ef else None)

        def wrap(new_state):
            return ({"qres": new_qres, "base": new_state} if ef
                    else new_state)

        if params is not None:
            layout = _remember(params)
        else:
            layout = (layouts.get(_layout_key(grads))
                      or _layout_of(grads, _PACK_ALL))
        gbufs = _pack_padded(grads, layout, mult, cast_small=True)
        pbufs = (None if params is None
                 else _pack_padded(params, layout, mult))

        leaf0 = next(iter(gbufs.values()))
        traced = _C.in_spmd(leaf0)
        ax = _C.rank_axes() if traced else None
        # In-step gradient health (core/numerics.py): computed on the
        # per-dtype buffers already resident for the scatter — a few
        # scalar reductions of extra HBM traffic. With the policy off
        # this block lowers nothing (HLO pinned identical).
        pol = _num.policy()

        def _observe(stats, per_rank=None):
            health = _jnum.health_of(stats, per_rank)
            if traced:
                _jnum.stash_traced(health)
            else:
                _num.note_step_health(jax.device_get(health),
                                      origin="eager")

        if (ax is None and world == 1) or (
                ax is not None and lax.psum(1, ax) == 1):
            # Degenerate 1-rank world: scatter and gather are identity
            # and the wire carries nothing (skip the lossy compression
            # round trip — quantize/dequantize included, so the int8
            # policies elide bit-exactly too; error-feedback residuals
            # pass through untouched as zeros). What remains is
            # whole-tree packing — fuse() semantics, a measured NEGATIVE
            # on one chip (module docstring); kept so the flag is
            # runnable anywhere.
            stats = (_jnum.bucket_stats(gbufs) if pol != "off" else None)
            if sdt is not None:
                g32 = {k: v.astype(jnp.float32) for k, v in gbufs.items()}
                ures, new_state = _master_step(g32, state, pbufs,
                                               extra_args)
            else:
                ufull, new_state = optimizer.update(
                    {"buf": gbufs, "big": []}, state,
                    None if pbufs is None else {"buf": pbufs, "big": []},
                    **extra_args)
                ures = ufull["buf"]
            if stats is not None:
                if pol == "halt":
                    finite = _jnum.all_finite(stats)
                    ures = _jnum.guard_updates(finite, ures)
                    new_state = _jnum.guard_state(finite, new_state,
                                                  state)
                _observe(stats)
            return _unpack_padded(ures, layout), wrap(new_state)
        if ax is not None:
            # --- compiled SPMD path: scatter, update 1/N, gather -------
            n_axis = lax.psum(1, ax)  # static axis size
            idx = lax.axis_index(ax)
            # Hierarchical (two-tier) quantized route: bound (dcn, ici)
            # axes mean the step compiled over the two-tier mesh
            # (HVD_HIERARCHICAL_ALLREDUCE + topology.two_tier()).
            hier_q = qpol is not None and isinstance(ax, tuple)
            if hier_q and ef:
                raise ValueError(
                    "int8_ef (error feedback) does not compose with the "
                    "hierarchical two-tier route: the residual carrier "
                    "is shaped for the flat exchange, but only the 1/L "
                    "ICI-reduced chunk is quantized here. Use the "
                    "stateless 'int8'/'fp8' policy with "
                    "HVD_HIERARCHICAL_ALLREDUCE, or disable the "
                    "hierarchical route for error-feedback runs.")

            def scatter(k, flat):
                if hier_q:
                    # Two-phase exchange: reduce-scatter over ICI at the
                    # RESIDENT dtype, then ship only the 1/L chunk across
                    # DCN block-scaled (quantize → all_to_all payload +
                    # scales over 'dcn' → f32 accumulate). The pre-permute
                    # makes ICI chunk i carry the dcn-major global shards
                    # [d*L+i for d], so the accumulated 1/N shard on chip
                    # (d, i) is EXACTLY the flat psum_scatter's shard
                    # d*L+i — sharded_state_specs layouts, checkpoints
                    # and the flat route stay interchangeable.
                    dax, iax = ax
                    d_sz, i_sz = lax.psum(1, dax), lax.psum(1, iax)
                    sub = flat.shape[0] // (d_sz * i_sz)
                    xp = (flat.reshape(d_sz, i_sz, sub).swapaxes(0, 1)
                          .reshape(flat.shape[0]))
                    chunk = lax.psum_scatter(xp, iax, scatter_dimension=0,
                                             tiled=True)
                    payload, scales = _Q.quantize(
                        chunk.astype(jnp.float32), qpol)
                    shard = _Q.spmd_exchange_accumulate(payload, scales,
                                                        dax, qpol)
                elif qpol is not None:
                    # Quantized reduce-scatter phase: quantize (with the
                    # error-feedback residual added first), exchange the
                    # int8 payload + f32 scales via all_to_all, and
                    # dequantize-accumulate in f32 (jax/quantize.py).
                    # The residual is this rank's un-transmitted error,
                    # recorded for the NEXT step.
                    x = flat.astype(jnp.float32)
                    if ef:
                        x = x + qres["g"][k][0]
                    payload, scales = _Q.quantize(x, qpol)
                    if ef:
                        new_qres["g"][k] = (
                            x - _Q.dequantize(payload, scales, qpol))[None]
                    shard = _Q.spmd_exchange_accumulate(payload, scales,
                                                        ax, qpol)
                else:
                    wire, ctx = compression.compress(flat)
                    shard = lax.psum_scatter(wire, ax, scatter_dimension=0,
                                             tiled=True)
                    shard = compression.decompress(shard, ctx)
                if sdt is not None:
                    # Fused epilogue: the collective runs at the wire
                    # (reduced) width; ONLY the 1/N shard upcasts to f32
                    # — averaging included — so no full-width f32
                    # gradient buffer exists between the reduce-scatter
                    # and the update.
                    shard = shard.astype(jnp.float32)
                    return shard / n_axis if average else shard
                if average:
                    shard = (shard / n_axis).astype(flat.dtype)
                elif qpol is not None:
                    shard = shard.astype(flat.dtype)
                return shard

            gshard = {k: scatter(k, v) for k, v in gbufs.items()}
            # Health on the REDUCED 1/N shards (psum'd = whole-buffer
            # figures; NaN from any rank survives the reduction) plus
            # the pre-scatter local counts for per-rank attribution.
            stats = (_jnum.bucket_stats(gshard, ax=ax)
                     if pol != "off" else None)
            pshard = None if pbufs is None else {
                k: lax.dynamic_slice(
                    v, (idx * (v.shape[0] // n_axis),),
                    (v.shape[0] // n_axis,))
                for k, v in pbufs.items()}
            if sdt is not None:
                # params are guaranteed under the policy, so pshard is
                # never None here.
                ures, new_state = _master_step(gshard, state, pshard,
                                               extra_args)
            else:
                ushard, new_state = optimizer.update(
                    {"buf": gshard, "big": []}, state,
                    None if pshard is None else {"buf": pshard,
                                                 "big": []},
                    **extra_args)
                ures = ushard["buf"]
            if stats is not None:
                if pol == "halt":
                    finite = _jnum.all_finite(stats)
                    ures = _jnum.guard_updates(finite, ures)
                    new_state = _jnum.guard_state(finite, new_state,
                                                  state)
                _observe(stats, _jnum.per_rank_nonfinite(gbufs, ax))

            def gather(k, ushard):
                if qpol is None:
                    return lax.all_gather(ushard, ax, axis=0, tiled=True)
                if hier_q:
                    # Inverse of the two-phase scatter: requantize the
                    # 1/N shard, quantized all-gather over DCN (the only
                    # cross-tier hop), dequantize to the resident dtype,
                    # all-gather the 1/L chunk over ICI at full width,
                    # then undo the dcn-major pre-permute.
                    dax, iax = ax
                    d_sz, i_sz = lax.psum(1, dax), lax.psum(1, iax)
                    payload, scales = _Q.quantize(
                        ushard.astype(jnp.float32), qpol)
                    chunk = _Q.spmd_gather_dequantize(payload, scales,
                                                      dax, qpol,
                                                      ushard.dtype)
                    out = lax.all_gather(chunk, iax, axis=0, tiled=True)
                    return (out.reshape(i_sz, d_sz, ushard.shape[0])
                            .swapaxes(0, 1).reshape(out.shape[0]))
                # Requantize → quantized all-gather: the update delta
                # ships at the wire width too; everyone (owner included)
                # applies the dequantized values so state stays
                # identical. Gather-side error feedback carries the
                # shard's un-transmitted delta error to next step.
                y = ushard.astype(jnp.float32)
                if ef:
                    y = y + qres["u"][k][0]
                payload, scales = _Q.quantize(y, qpol)
                if ef:
                    new_qres["u"][k] = (
                        y - _Q.dequantize(payload, scales, qpol))[None]
                return _Q.spmd_gather_dequantize(payload, scales, ax,
                                                 qpol, ushard.dtype)

            ubufs = {k: gather(k, v) for k, v in ures.items()}
            return _unpack_padded(ubufs, layout), wrap(new_state)

        # --- eager path: allreduce + full-buffer update ---------------
        # (single-controller host calls, and tests). Elementwise inner
        # transforms make this the concatenation of the per-shard
        # updates, so the state structure is shared with the SPMD path.
        def reduce_full(k, flat):
            if qpol is not None:
                # Quantized eager reduction: same wire format as the
                # SPMD exchange (allgather of payload + scales, f32
                # accumulation) — and bit-identical trajectories when
                # per-rank contributions agree, because blockwise
                # quantization of the full buffer equals the
                # concatenation of the per-shard quantizations
                # (buffers pad to world*block). The residual row 0 is
                # this controller's error; rows are kept identical so
                # the state structure matches the SPMD layout.
                x = flat.astype(jnp.float32)
                if ef:
                    x = x + qres["g"][k][0]
                payload, scales = _Q.quantize(x, qpol)
                if ef:
                    r = x - _Q.dequantize(payload, scales, qpol)
                    new_qres["g"][k] = jnp.broadcast_to(
                        r, (world, r.shape[0]))
                out = _Q.eager_exchange_accumulate(payload, scales, qpol,
                                                   world)
            else:
                wire, ctx = compression.compress(flat)
                out = _C.allreduce(wire, average=False)
                out = compression.decompress(out, ctx)
            if sdt is not None:
                out = out.astype(jnp.float32)
                return out / world if average else out
            if average:
                out = (out / world).astype(flat.dtype)
            elif qpol is not None:
                out = out.astype(flat.dtype)
            return out

        gfull = {k: reduce_full(k, v) for k, v in gbufs.items()}
        stats = _jnum.bucket_stats(gfull) if pol != "off" else None
        if sdt is not None:
            ures, new_state = _master_step(gfull, state, pbufs, extra_args)
        else:
            ufull, new_state = optimizer.update(
                {"buf": gfull, "big": []}, state,
                None if pbufs is None else {"buf": pbufs, "big": []},
                **extra_args)
            ures = ufull["buf"]
        if stats is not None:
            if pol == "halt":
                finite = _jnum.all_finite(stats)
                ures = _jnum.guard_updates(finite, ures)
                new_state = _jnum.guard_state(finite, new_state, state)
            _observe(stats)
        if qpol is not None:
            # Mirror the SPMD gather phase: blockwise-quantize the full
            # update buffer (== the concatenation of the per-shard
            # quantizations) so eager and SPMD trajectories agree; no
            # collective is needed — the dequantized value IS what every
            # rank applies.
            def requant(k, u):
                y = u.astype(jnp.float32)
                if ef:
                    y = y + qres["u"][k].reshape(-1)
                payload, scales = _Q.quantize(y, qpol)
                sent = _Q.dequantize(payload, scales, qpol)
                if ef:
                    new_qres["u"][k] = (y - sent).reshape(world, -1)
                return sent.astype(u.dtype)

            ures = {k: requant(k, v) for k, v in ures.items()}
        return _unpack_padded(ures, layout), wrap(new_state)

    return optax.GradientTransformationExtraArgs(init, update)


def unwrap_error_feedback(opt_state):
    """Strip the error-feedback residual wrapper a quantized ``int8_ef``
    :func:`shard_update` adds (``{"qres": ..., "base": <state>}``) —
    returns the base state unchanged for every other layout. The state
    helpers below route through here so they keep working under the
    composed quantized + mixed-precision layout."""
    if (isinstance(opt_state, dict) and set(opt_state) == {"qres", "base"}
            and isinstance(opt_state["qres"], dict)):
        return opt_state["base"]
    return opt_state


def has_master_shards(opt_state) -> bool:
    """True when ``opt_state`` is a :func:`shard_update`
    ``state_dtype=...`` mixed-layout state (f32 master buffers +
    storage-dtype inner state), with or without the error-feedback
    wrapper."""
    opt_state = unwrap_error_feedback(opt_state)
    return (isinstance(opt_state, dict)
            and set(opt_state) == {"master", "inner"}
            and isinstance(opt_state["master"], dict))


def resident_from_masters(opt_state, params_like):
    """Rebuild the resident parameter tree BITWISE from the f32 master
    buffers of a ``state_dtype`` mixed-layout state: each master buffer
    is cast to its group's resident dtype (the group key IS the resident
    dtype name by :func:`~horovod_tpu.jax.fused._layout_of` construction)
    and unpacked over ``params_like``'s structure. This is the checkpoint
    restore path: persisting the masters and rebuilding residents from
    them guarantees ``resident == cast(master)`` exactly after a restore,
    so a save→restore→step trajectory matches the uninterrupted one."""
    if not has_master_shards(opt_state):
        raise ValueError("opt_state carries no master shards (was the "
                         "optimizer built with state_dtype=...?)")
    opt_state = unwrap_error_feedback(opt_state)
    layout = _layout_of(params_like, _PACK_ALL)
    bufs = {k: jnp.asarray(v).astype(k)
            for k, v in opt_state["master"].items()}
    return _unpack({"buf": bufs, "big": []}, layout)


#: Magnitude floor for the drift unit: below this |master| the absolute
#: re-anchor error stays bounded while the RAW ulp spacing shrinks
#: without limit, so ulps-at-the-value would read noisy-large for
#: healthy near-zero weights (the same floor the equivalence tests pin).
DRIFT_MAG_FLOOR = 1e-3


def drift_ulp(opt_state, params) -> dict:
    """Master↔resident divergence per dtype bucket, as the max distance
    between ``cast(master)`` and the resident parameters measured in
    **ulps at the master's magnitude** (``max(|master|, 1e-3) × eps``)
    — the automated form of the docs/troubleshooting.md "bf16-state
    convergence drift" ladder's manual audit, in the same unit the
    equivalence suite pins. The re-anchored :func:`shard_update` path
    keeps this at stable single digits by construction — 0 right after
    init/restore, ~1-2 in steady state, transiently higher only when a
    step's own update is large against a small weight (the re-anchor
    error is bounded by one rounding of the step's delta, never by
    history) — so a GROWING gauge means the policy is not applied where
    you think (or a caller mutated residents outside the update).
    Raw ulp distance at the value itself would be the wrong unit: near
    zero the spacing shrinks without limit and a healthy re-anchor
    rounds to tens of value-ulps while staying absolutely tiny.

    Host-side and periodic (the Trainer calls it every
    ``HVD_NUMERICS_EVERY`` steps under the numerics policy): the master
    shards are globalized with :func:`~horovod_tpu.ops.collectives.fetch`
    — in a multi-controller world this is a collective, call it in
    lockstep on every process."""
    import numpy as np

    if not has_master_shards(opt_state):
        raise ValueError("opt_state carries no master shards (was the "
                         "optimizer built with state_dtype=...?)")
    opt_state = unwrap_error_feedback(opt_state)
    layout = _layout_of(params, _PACK_ALL)
    packed = _pack(params, layout)
    out = {}
    for k, master in opt_state["master"].items():
        # Pad to the MASTER's length, not a recomputed multiple: a
        # quantized policy's block alignment makes the padding larger
        # than the plain world multiple.
        res = jnp.asarray(_C.fetch(
            _C._pad_dim0(packed["buf"][k], int(master.shape[0]))))
        m64 = np.asarray(_C.fetch(master), np.float64)
        cast64 = np.asarray(jnp.asarray(_C.fetch(master))
                            .astype(res.dtype), np.float64)
        res64 = np.asarray(res, np.float64)
        eps = float(jnp.finfo(res.dtype).eps)
        band = np.maximum(np.abs(m64), DRIFT_MAG_FLOOR) * eps
        if not res64.size:
            out[k] = 0
            continue
        with np.errstate(invalid="ignore"):
            mx = float(np.max(np.abs(res64 - cast64) / band))
        # NaN/Inf anywhere (a poisoned step the warn policy let through)
        # IS infinite divergence: report a huge finite gauge value
        # instead of crashing the fit loop mid-observation.
        out[k] = int(np.ceil(mx)) if np.isfinite(mx) else (1 << 62)
    return out


def sharded_state_specs(opt_state, axis: str = HVD_AXIS):
    """PartitionSpec tree for a :func:`shard_update` optimizer state:
    ``P('hvd')`` for the padded per-dtype flat buffers (every array leaf
    — their leading dim is padded to a world-size multiple by
    construction), ``P()`` for scalar leaves (step counters and other
    replicated bookkeeping).

    Use as the ``in_specs``/``out_specs`` entry for the optimizer-state
    argument of :func:`horovod_tpu.jax.jit` so each chip holds exactly
    its 1/N block of m/v/trace buffers::

        spec = hvd.jax.sharded_state_specs(opt_state)
        step = hvd.jax.jit(fn, in_specs=(P(), spec, ...),
                           out_specs=(P(), spec, ...),
                           donate_argnums=(0, 1))
    """
    world = _world()

    def one(leaf):
        shape = jnp.shape(leaf)
        if len(shape) >= 1 and shape[0] > 0 and shape[0] % world == 0:
            return P(axis)
        return P()

    return jax.tree_util.tree_map(one, opt_state)
