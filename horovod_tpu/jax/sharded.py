"""Cross-replica sharded weight update — reduce-scatter, update 1/N,
all-gather (arxiv 2004.13336 "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training"; the ZeRO-2 shape).

The Horovod pattern this repo reproduces allreduces the full gradient and
then runs the IDENTICAL optimizer update on every chip: each chip reads
and writes a full copy of the momentum/Adam state and the full parameter
tree every step, even though chip r only "owns" new information about
1/N of the reduced gradient. On a memory-bound step (ResNet-50 bs32 sits
at 87.6% of the practical HBM peak at 35.7% MFU — docs/benchmarks.md)
that redundancy is the dominant removable traffic: per-chip optimizer
read/write drops by ~(N-1)/N when the update is sharded.

:func:`shard_update` wraps an *elementwise* optax transform so that,
inside the compiled SPMD step:

1. gradients are packed into per-dtype flat buffers (the same packing
   :mod:`horovod_tpu.jax.fused` uses, applied to the WHOLE tree — the
   scatter needs one contiguous buffer per dtype) and zero-padded to a
   multiple of the world size,
2. the buffers go through ``lax.psum_scatter`` (reduce-scatter — half an
   allreduce of wire traffic; optional on-the-wire compression applies
   to the flat buffer exactly as it would to an allreduce),
3. the inner optax update runs on the 1/N shard of gradient, parameters
   and optimizer state (state buffers are (padded,) global arrays laid
   out ``P('hvd')`` over the mesh, so each chip holds — and reads and
   writes — only its own 1/N block),
4. the updated-parameter DELTA returns via tiled ``lax.all_gather`` (the
   other half of the allreduce's wire traffic), is un-padded, and
   unpacks to the caller's update pytree.

Called eagerly (no mesh axis bound), the wrapper reduces with a plain
allreduce and updates the full buffers — elementwise transforms make the
full update the concatenation of the per-shard updates, so eager and
SPMD trajectories agree and share one state structure.

Correctness domain: per-coordinate transforms (sgd, momentum, adam(w),
rmsprop, lion, ...). Transforms that aggregate ACROSS coordinates see
only the local shard under sharding — ``clip_by_global_norm`` would
compute a shard-local norm — and must stay on the replicated path (this
is stricter than :func:`horovod_tpu.jax.fuse`, where the norm stayed
global because every chip held every coordinate).

At world size 1 the scatter and gather are identity and the wrapper
degrades to whole-tree-packed :func:`fuse` — a measured NEGATIVE on one
chip (packing severs XLA's wgrad->update producer fusion; see
docs/benchmarks.md "HBM diet"). Shard when N > 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common import topology as _topo
from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.jax.compression import Compression
from horovod_tpu.jax.fused import _layout_of, _pack, _unpack
from horovod_tpu.ops import collectives as _C

# Pack EVERY leaf: the reduce-scatter needs one contiguous buffer per
# dtype, so there is no passthrough tier (unlike fuse()'s small-only
# packing).
_PACK_ALL = 1 << 62


def _world() -> int:
    return _topo._require_init().size


def shard_update(
    optimizer: optax.GradientTransformation,
    average: bool = True,
    compression=Compression.none,
) -> optax.GradientTransformationExtraArgs:
    """Wrap ``optimizer`` so the gradient reduction AND the update are
    sharded across the world (module docstring). The returned transform
    replaces the allreduce: do NOT reduce gradients before calling it.

    ``init`` returns per-dtype flat state buffers zero-padded to a
    multiple of the world size; lay them out ``P('hvd')`` in the compiled
    step (:func:`sharded_state_specs` builds the spec tree) so each chip
    holds one 1/N block. ``average=False`` keeps the reduced sum, exactly
    like :func:`horovod_tpu.jax.allreduce`.
    """
    optimizer = optax.with_extra_args_support(optimizer)
    # Layout cache keyed like fuse(): init()'s param-dtype layout must
    # serve update() calls that omit params (grads share treedef/shapes).
    layouts: dict = {}

    def _layout_key(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return treedef, tuple(tuple(jnp.shape(l)) for l in leaves)

    def _remember(tree):
        key = _layout_key(tree)
        layout = layouts.get(key)
        if layout is None:
            layout = layouts[key] = _layout_of(tree, _PACK_ALL)
        return layout

    def _pack_padded(tree, layout, world, cast_small=False):
        packed = _pack(tree, layout, cast_small=cast_small)
        # Same zero-pad-to-multiple contract as reducescatter's.
        return {k: _C._pad_dim0(v, world) for k, v in packed["buf"].items()}

    def _unpack_padded(bufs, layout):
        # _unpack indexes [off:off+n] per leaf, so trailing padding is
        # simply never read.
        return _unpack({"buf": bufs, "big": []}, layout)

    def init(params):
        world = _world()
        layout = _remember(params)
        return optimizer.init(
            {"buf": _pack_padded(params, layout, world), "big": []})

    def update(grads, state, params=None, **extra_args):
        world = _world()
        if params is not None:
            layout = _remember(params)
        else:
            layout = (layouts.get(_layout_key(grads))
                      or _layout_of(grads, _PACK_ALL))
        gbufs = _pack_padded(grads, layout, world, cast_small=True)
        pbufs = (None if params is None
                 else _pack_padded(params, layout, world))

        leaf0 = next(iter(gbufs.values()))
        ax = _C.rank_axes() if _C.in_spmd(leaf0) else None
        if (ax is None and world == 1) or (
                ax is not None and lax.psum(1, ax) == 1):
            # Degenerate 1-rank world: scatter and gather are identity
            # and the wire carries nothing (skip the lossy compression
            # round trip). What remains is whole-tree packing — fuse()
            # semantics, a measured NEGATIVE on one chip (module
            # docstring); kept so the flag is runnable anywhere.
            ufull, new_state = optimizer.update(
                {"buf": gbufs, "big": []}, state,
                None if pbufs is None else {"buf": pbufs, "big": []},
                **extra_args)
            return _unpack_padded(ufull["buf"], layout), new_state
        if ax is not None:
            # --- compiled SPMD path: scatter, update 1/N, gather -------
            n_axis = lax.psum(1, ax)  # static axis size
            idx = lax.axis_index(ax)

            def scatter(flat):
                wire, ctx = compression.compress(flat)
                shard = lax.psum_scatter(wire, ax, scatter_dimension=0,
                                         tiled=True)
                shard = compression.decompress(shard, ctx)
                if average:
                    shard = (shard / n_axis).astype(flat.dtype)
                return shard

            gshard = {k: scatter(v) for k, v in gbufs.items()}
            pshard = None if pbufs is None else {
                k: lax.dynamic_slice(
                    v, (idx * (v.shape[0] // n_axis),),
                    (v.shape[0] // n_axis,))
                for k, v in pbufs.items()}
            ushard, new_state = optimizer.update(
                {"buf": gshard, "big": []}, state,
                None if pshard is None else {"buf": pshard, "big": []},
                **extra_args)
            ubufs = {k: lax.all_gather(v, ax, axis=0, tiled=True)
                     for k, v in ushard["buf"].items()}
            return _unpack_padded(ubufs, layout), new_state

        # --- eager path: allreduce + full-buffer update ---------------
        # (single-controller host calls, and tests). Elementwise inner
        # transforms make this the concatenation of the per-shard
        # updates, so the state structure is shared with the SPMD path.
        def reduce_full(flat):
            wire, ctx = compression.compress(flat)
            out = _C.allreduce(wire, average=False)
            out = compression.decompress(out, ctx)
            if average:
                out = (out / world).astype(flat.dtype)
            return out

        gfull = {k: reduce_full(v) for k, v in gbufs.items()}
        ufull, new_state = optimizer.update(
            {"buf": gfull, "big": []}, state,
            None if pbufs is None else {"buf": pbufs, "big": []},
            **extra_args)
        return _unpack_padded(ufull["buf"], layout), new_state

    return optax.GradientTransformationExtraArgs(init, update)


def sharded_state_specs(opt_state, axis: str = HVD_AXIS):
    """PartitionSpec tree for a :func:`shard_update` optimizer state:
    ``P('hvd')`` for the padded per-dtype flat buffers (every array leaf
    — their leading dim is padded to a world-size multiple by
    construction), ``P()`` for scalar leaves (step counters and other
    replicated bookkeeping).

    Use as the ``in_specs``/``out_specs`` entry for the optimizer-state
    argument of :func:`horovod_tpu.jax.jit` so each chip holds exactly
    its 1/N block of m/v/trace buffers::

        spec = hvd.jax.sharded_state_specs(opt_state)
        step = hvd.jax.jit(fn, in_specs=(P(), spec, ...),
                           out_specs=(P(), spec, ...),
                           donate_argnums=(0, 1))
    """
    world = _world()

    def one(leaf):
        shape = jnp.shape(leaf)
        if len(shape) >= 1 and shape[0] > 0 and shape[0] % world == 0:
            return P(axis)
        return P()

    return jax.tree_util.tree_map(one, opt_state)
