"""Block-scaled quantized collectives — the math behind the ``int8`` /
``fp8`` wire policies (EQuARX, arxiv 2506.17615; the MLPerf TPU-pod work,
arxiv 1909.09756, shows reduced-precision communication is load-bearing
for pod-scale efficiency).

The bench is measured nearly bandwidth-bound (membw_util 0.876), so the
next multi-chip scaling win must cut BYTES on the wire. A cast to bf16
halves them; block-scaled int8 quarters them: per ``block`` contiguous
elements the wire carries ``round(x * qmax / amax)`` at 1 byte/element
plus ONE f32 scale — 4/(1 + 4/block) ≈ 3.9x fewer bytes than f32 at the
default block of 512, scales included.

Why there is no "quantized psum": summing int8 payloads saturates, and
widening them for an in-network sum would put the full width right back
on the wire. The TPU-native shape (EQuARX §3) keeps every wire hop at
the quantized width and does the accumulation at f32 on-chip:

- **reduce-scatter phase** = quantize the local buffer → int8
  ``all_to_all`` (each rank receives every rank's copy of ITS chunk,
  payload + scales) → dequantize-accumulate in f32. Wire bytes per rank:
  (world-1)/world of the quantized buffer, exactly a ring
  reduce-scatter's traffic at 1/4 width.
- **all-gather phase** = requantize the (updated) shard → int8
  ``all_gather`` (payload + scales) → dequantize.

Both phases compile into the ``shard_map`` step
(:func:`horovod_tpu.jax.shard_update` composes them with the fused
sharded-update epilogue), and the engines apply the same wire format to
their 16 MB execution chunks through the shared data plane
(:class:`horovod_tpu.core.engine.JaxExecutor` — shared by the python and
C++ engines, which is what makes their reduction digests bit-identical
by construction).

Quantization is deterministic and rank-symmetric: zero blocks get scale
1.0 (payload zeros), so zero padding is reduction-neutral exactly like
the unquantized padding contract, and ties round half-to-even
(``jnp.round`` / ``np.rint`` agree).
"""

from __future__ import annotations

import numpy as np

#: Elements per f32 scale. Mirrored by compression._QuantCompressor.block;
#: per-policy overrides ride the policy object.
DEFAULT_BLOCK = 512

_WIRE_NP_DTYPES = {}


def np_wire_dtype(policy) -> np.dtype:
    """Numpy dtype of the policy's wire payload (fp8 via ml_dtypes)."""
    name = policy.wire_dtype_name
    dt = _WIRE_NP_DTYPES.get(name)
    if dt is None:
        if name == "int8":
            dt = np.dtype(np.int8)
        else:
            import ml_dtypes

            dt = np.dtype(getattr(ml_dtypes, name))
        _WIRE_NP_DTYPES[name] = dt
    return dt


def padded_len(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def wire_bytes_of(n: int, policy) -> tuple:
    """(payload_bytes, scale_bytes) the policy ships for an n-element
    float buffer (block-padded) — the analytic form of the measured
    ``engine.wire_bytes`` counters, used by the benchmark's split."""
    npad = padded_len(n, policy.block)
    return npad * np_wire_dtype(policy).itemsize, (npad // policy.block) * 4


# ---------------------------------------------------------------------------
# jnp math (compiled + eager jax paths)
# ---------------------------------------------------------------------------

def _jnp():
    import jax.numpy as jnp

    return jnp


def quantize(flat, policy):
    """1-D float array (length % block == 0) -> (payload wire-dtype,
    scales f32 of length n/block). Zero blocks get scale 1.0 so their
    payload is exactly zero (padding neutrality)."""
    jnp = _jnp()
    x = flat.astype(jnp.float32).reshape(-1, policy.block)
    amax = jnp.max(jnp.abs(x), axis=1)
    scale = jnp.where(amax > 0, amax / policy.qmax, 1.0).astype(jnp.float32)
    y = x / scale[:, None]
    if policy.round_to_int:
        payload = jnp.clip(jnp.round(y), -policy.qmax, policy.qmax).astype(
            jnp.int8)
    else:
        payload = y.astype(jnp.dtype(policy.wire_dtype_name))
    return payload.reshape(flat.shape[0]), scale


def dequantize(payload, scales, policy, out_dtype=None):
    """Inverse of :func:`quantize`; f32 math, optionally cast."""
    jnp = _jnp()
    x = (payload.astype(jnp.float32).reshape(-1, policy.block)
         * scales.reshape(-1)[:, None]).reshape(payload.shape[0])
    return x if out_dtype is None else x.astype(out_dtype)


def spmd_exchange_accumulate(payload, scales, ax, policy):
    """The reduce-scatter phase on PRE-quantized values: int8
    ``all_to_all`` of (payload, scales) — each rank receives every
    rank's copy of its own chunk — then dequantize-accumulate in f32.
    Split out of :func:`spmd_reduce_scatter` so the error-feedback path
    (shard_update) can quantize once, keep the transmitted value for the
    residual, and exchange here."""
    from jax import lax

    jnp = _jnp()
    world = lax.psum(1, ax)
    nb = scales.shape[0]
    p = lax.all_to_all(payload.reshape(world, -1), ax,
                       split_axis=0, concat_axis=0)
    s = lax.all_to_all(scales.reshape(world, -1), ax,
                       split_axis=0, concat_axis=0)
    contrib = (p.astype(jnp.float32).reshape(world, nb // world, policy.block)
               * s[:, :, None])
    return contrib.sum(axis=0).reshape(payload.shape[0] // world)


def spmd_reduce_scatter(flat, ax, policy):
    """Quantized reduce-scatter inside SPMD code: ``flat`` is this
    rank's (n,) buffer with n divisible by world*block; returns the f32
    (n/world,) SUM shard. The wire carries int8 payload + f32 scales via
    ``all_to_all`` (module docstring — a psum_scatter would have to sum
    payloads); accumulation runs at f32 on-chip."""
    payload, scales = quantize(flat, policy)
    return spmd_exchange_accumulate(payload, scales, ax, policy)


def spmd_gather_dequantize(payload, scales, ax, policy, out_dtype=None):
    """The all-gather phase on PRE-quantized shard values: tiled int8
    ``all_gather`` of (payload, scales), dequantized on arrival. Every
    rank (the owner included) applies the DEQUANTIZED values, so the
    gathered state is identical everywhere."""
    from jax import lax

    p = lax.all_gather(payload, ax, axis=0, tiled=True)
    s = lax.all_gather(scales, ax, axis=0, tiled=True)
    return dequantize(p, s, policy, out_dtype)


def spmd_all_gather(shard, ax, policy, out_dtype=None):
    """Quantized tiled all-gather inside SPMD code: ``shard`` (m,) with
    m divisible by block; returns the (world*m,) dequantized buffer."""
    payload, scales = quantize(shard, policy)
    return spmd_gather_dequantize(payload, scales, ax, policy, out_dtype)


def spmd_allreduce(tensor, ax, average: bool, policy):
    """Generic quantized allreduce for SPMD code: ravel → pad →
    quantized reduce-scatter → (average) → requantize → quantized
    all-gather → unpad/reshape. This is the stateless surface (no
    error-feedback residual — that needs a state carrier; see
    shard_update)."""
    from jax import lax

    jnp = _jnp()
    world = lax.psum(1, ax)
    flat = tensor.reshape(-1)
    n = flat.shape[0]
    npad = padded_len(n, world * policy.block)
    if npad != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((npad - n,), flat.dtype)])
    shard = spmd_reduce_scatter(flat, ax, policy)
    if average:
        shard = shard / world
    out = spmd_all_gather(shard, ax, policy)
    return out[:n].reshape(tensor.shape).astype(tensor.dtype)


def eager_exchange_accumulate(payload, scales, policy, world):
    """Eager twin of :func:`spmd_exchange_accumulate` over the FULL
    buffer: allgather the pre-quantized (payload, scales) across the
    world — the same bytes/hop the in-step exchange ships — and
    dequantize-accumulate on this controller. Returns the f32 sum."""
    from horovod_tpu.ops import collectives as _C

    jnp = _jnp()
    npad = payload.shape[0]
    p = jnp.asarray(np.asarray(_C.allgather(payload))).reshape(world, npad)
    s = jnp.asarray(np.asarray(_C.allgather(scales))).reshape(world, -1)
    return (p.astype(jnp.float32).reshape(world, -1, policy.block)
            * s[:, :, None]).sum(axis=0).reshape(npad)


def eager_allreduce(tensor, average: bool, policy):
    """Quantized allreduce for eager host calls: quantize the local
    contribution, allgather payload + scales across the world,
    dequantize-accumulate on this controller. Matches the
    eager-collective semantics of :mod:`horovod_tpu.ops.collectives`
    (each local chip contributes this controller's value)."""
    from horovod_tpu.ops import collectives as _C

    jnp = _jnp()
    flat = jnp.asarray(tensor).reshape(-1)
    n = flat.shape[0]
    npad = padded_len(max(n, 1), policy.block)
    if npad != n:
        flat = jnp.concatenate([flat, jnp.zeros((npad - n,), flat.dtype)])
    payload, scales = quantize(flat, policy)
    world = _C._topo._require_init().size
    out = eager_exchange_accumulate(payload, scales, policy, world)
    if average:
        out = out / world
    return out[:n].reshape(jnp.shape(tensor)).astype(tensor.dtype)


# ---------------------------------------------------------------------------
# numpy twins (the engines' host-side data plane — core/engine.py stages
# the QUANTIZED buffers, so host->device traffic shrinks with the wire)
# ---------------------------------------------------------------------------

def np_quantize(flat: np.ndarray, policy):
    """Host-side :func:`quantize` twin. Pads to a block multiple itself
    (engine chunks are pow2-bucketed, but defensive padding keeps any
    block size correct); returns (payload, scales, padded_len)."""
    npad = padded_len(max(flat.shape[0], 1), policy.block)
    payload = np.empty((npad,), np_wire_dtype(policy))
    scales = np.empty((npad // policy.block,), np.float32)
    np_quantize_into(flat, policy, payload, scales,
                     np.empty((npad,), np.float32))
    return payload, scales, npad


def np_quantize_into(flat: np.ndarray, policy, payload: np.ndarray,
                     scales: np.ndarray, work: np.ndarray):
    """:func:`np_quantize` staged into caller-owned buffers — the engines
    check ``payload``/``scales``/``work`` out of their buffer pool so the
    steady-state wire staging allocates nothing (``work`` is an f32
    scratch of ``payload``'s length; all three are 1-d, length/dtype
    exact). The math is bit-identical to :func:`np_quantize` — rint
    (ties to even) then clip then int cast — which is what keeps the
    python/C++ engine reduction digests equal under a quantized policy."""
    n = flat.shape[0]
    npad = payload.shape[0]
    work[:n] = np.asarray(flat, np.float32)
    work[n:] = 0.0
    x = work.reshape(-1, policy.block)
    amax = np.max(np.abs(x), axis=1)
    np.copyto(scales, np.where(amax > 0, amax / policy.qmax, 1.0),
              casting="unsafe")
    np.divide(x, scales.reshape(-1, 1), out=x)
    if policy.round_to_int:
        np.rint(x, out=x)
        np.clip(x, -policy.qmax, policy.qmax, out=x)
    np.copyto(payload, work[:npad], casting="unsafe")


def np_dequantize_sum(payloads: np.ndarray, scales: np.ndarray,
                      policy) -> np.ndarray:
    """(world, npad) payload rows + (world, nblocks) scale rows ->
    f32 (npad,) sum of the dequantized contributions."""
    world, npad = payloads.shape
    x = (payloads.astype(np.float32).reshape(world, -1, policy.block)
         * scales.reshape(world, -1)[:, :, None])
    return x.sum(axis=0).reshape(npad)
