"""Gradient compression (reference: horovod/tensorflow/compression.py and
horovod/torch/compression.py — same Compressor/none/fp16 surface).

TPU-first difference: bf16 is the hardware-native reduced precision (full
float32 range, MXU-native), so a ``bf16`` compressor is provided alongside
``fp16`` and is the recommended default for wire compression.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress before the collective, decompress after
    (reference: compression.py:20-31)."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, ctx) where ctx carries what
        decompress needs."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """Cast float tensors to float16 for the wire (reference:
    compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bfloat16 — TPU-native reduced precision."""

    wire_dtype = jnp.bfloat16


class Compression:
    """Option pack (reference: compression.py:67-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
