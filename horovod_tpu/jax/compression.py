"""Gradient compression (reference: horovod/tensorflow/compression.py and
horovod/torch/compression.py — same Compressor/none/fp16 surface).

TPU-first differences:

- bf16 is the hardware-native reduced precision (full float32 range,
  MXU-native), so a ``bf16`` compressor is provided alongside ``fp16``.
- Block-scaled **quantized** policies (``int8``, ``int8_ef``, ``fp8`` —
  EQuARX, arxiv 2506.17615) that cut wire bytes ~4x (int8 payload + one
  f32 scale per :data:`~horovod_tpu.jax.quantize.DEFAULT_BLOCK` elements).
  Unlike the cast compressors these cannot ride a plain sum-on-the-wire
  collective (summing int8 saturates), so they are handled at the
  COLLECTIVE layer: the compiled path lowers to quantize → int8
  all-to-all (the reduce-scatter phase) → dequantize-accumulate →
  requantize → int8 all-gather (:mod:`horovod_tpu.jax.quantize`,
  :func:`horovod_tpu.jax.shard_update`), and the engines apply the same
  wire format to their execution chunks (HVD_COMPRESSION / per-request
  policy; core/engine.py JaxExecutor). Their ``compress``/``decompress``
  deliberately raise: a call site that still treats them as cast
  compressors would silently ship full width.
"""

from __future__ import annotations

import fnmatch
import os

import jax.numpy as jnp


def _where_am_i() -> str:
    """Rank attribution for fail-fast policy errors (the satellite
    contract: a bad compressor must name the rank, not surface as an
    attribute error mid-step)."""
    try:
        from horovod_tpu.common import topology as _topo

        if _topo.is_initialized():
            return f"rank {_topo.rank()}"
    except Exception:
        pass
    return f"pid {os.getpid()}"


class Compressor:
    """Interface: compress before the collective, decompress after
    (reference: compression.py:20-31)."""

    #: Quantized policies are handled at the collective layer (module
    #: docstring); cast policies wrap the collective with compress/
    #: decompress.
    quantized = False
    #: Wire-format name the engines understand (core/engine.py
    #: ENGINE_WIRE_POLICIES); None = engine ships full width.
    engine_wire = None

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, ctx) where ctx carries what
        decompress needs."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """Cast float tensors to float16 for the wire (reference:
    compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bfloat16 — TPU-native reduced precision."""

    wire_dtype = jnp.bfloat16


class _QuantCompressor(Compressor):
    """Base for block-scaled quantized wire policies. Pure metadata — the
    math lives in :mod:`horovod_tpu.jax.quantize` (compiled/eager) and in
    the engines' shared data plane (core/engine.py), which read these
    class attributes. ``compress``/``decompress`` raise on purpose: see
    the module docstring."""

    quantized = True
    #: Payload dtype NAME (resolved lazily — fp8 rides ml_dtypes).
    wire_dtype_name = "int8"
    #: Largest representable payload magnitude (the per-block scale is
    #: amax / qmax).
    qmax = 127.0
    #: Payload values are produced by round-to-nearest-int (int8) rather
    #: than a dtype cast (fp8).
    round_to_int = True
    #: Elements per f32 scale (quantize.DEFAULT_BLOCK mirrors this).
    block = 512
    #: Opt-in error-feedback residual, carried in optimizer state by
    #: shard_update (stateless surfaces — plain allreduce — run the same
    #: wire format WITHOUT the residual; see docs/troubleshooting.md).
    error_feedback = False

    @classmethod
    def compress(cls, tensor):
        raise NotImplementedError(
            f"{cls.__name__} is a block-scaled quantized policy: it is "
            "applied at the collective layer (hvd.jax.allreduce / "
            "shard_update / the engine wire format), not via "
            "compress()/decompress() around a sum-on-the-wire collective "
            "— summed int8 payloads would saturate")

    decompress = compress


class Int8Compressor(_QuantCompressor):
    """Block-scaled int8 (EQuARX, arxiv 2506.17615): per 512-element
    block, payload = round(x * 127 / amax) as int8 plus one f32 scale —
    ~3.9x fewer bytes on the wire than f32, scales included."""

    engine_wire = "int8"


class Int8ErrorFeedbackCompressor(Int8Compressor):
    """int8 with an error-feedback residual: the un-transmitted
    quantization error of each rank's contribution is carried in
    optimizer state and added to the next step's gradient, making the
    long-run trajectory unbiased (the convergence guardrail the
    tentpole's training acceptance runs under). Honored by
    :func:`horovod_tpu.jax.shard_update`; stateless surfaces use the
    same wire format without the residual."""

    error_feedback = True


class FP8Compressor(_QuantCompressor):
    """Block-scaled fp8 (e4m3) carried for tensors where the int8 grid
    loses too much: payload keeps a 3-bit mantissa ACROSS the block's
    dynamic range instead of a uniform grid, at the same 1 byte/element.
    Payload dtype rides ml_dtypes.float8_e4m3fn (jax ships it)."""

    engine_wire = "fp8"
    wire_dtype_name = "float8_e4m3fn"
    qmax = 448.0  # float8_e4m3fn max finite
    round_to_int = False


class _PerTensor:
    """Name-based per-tensor policy: ``for_tensor(name)`` resolves the
    first matching fnmatch pattern, else the default. Accepted by the
    name-carrying surfaces (eager ``hvd.jax.allreduce(name=...)``, the
    TF/torch frontends' per-parameter reductions). The packed-buffer
    paths (shard_update / fused buckets) need ONE uniform policy per
    buffer and reject this container with a clear error."""

    quantized = False  # container; resolve per name before use
    engine_wire = None

    def __init__(self, default, overrides):
        self.default = default
        # Insertion order is match priority.
        self.overrides = list(overrides.items())

    def for_tensor(self, name):
        if name is not None:
            for pat, comp in self.overrides:
                if fnmatch.fnmatchcase(str(name), pat):
                    return comp
        return self.default


def for_tensor(compression, name):
    """Resolve a possibly per-tensor policy container for one named
    tensor (identity for plain compressors)."""
    fn = getattr(compression, "for_tensor", None)
    return compression if fn is None else fn(name)


def resolve_in(registry, spec, where="compression"):
    """Shared resolve logic behind every frontend's
    ``Compression.resolve`` (the jax/TF/torch registries differ; the
    validation and rank-attributed fail-fast contract must not)."""
    if spec is None:
        return registry["none"]
    if isinstance(spec, str):
        comp = registry.get(spec)
        if comp is None:
            raise ValueError(
                f"unknown {where} policy {spec!r} on {_where_am_i()}: "
                f"expected one of {sorted(registry)}")
        return comp
    if hasattr(spec, "for_tensor"):
        return spec
    if not (hasattr(spec, "compress") and hasattr(spec, "decompress")):
        raise ValueError(
            f"invalid {where} policy {spec!r} on {_where_am_i()}: "
            f"expected a Compression name ({sorted(registry)}), a "
            "Compressor, or Compression.select(...)")
    return spec


_PINNED_WIRE: dict = {}


def pin_engine_wire(comp):
    """``select()`` members are EXPLICIT choices: a ``'none'`` entry
    must ship full width even under an ``HVD_COMPRESSION`` engine-wide
    default, so members whose ``engine_wire`` is the defer-to-default
    ``None`` get a cached subclass pinning ``engine_wire='none'``.
    (Plain ``Compression.none`` — the implicit default everywhere —
    keeps ``None`` and defers to the env, which is that knob's point.)"""
    if (getattr(comp, "engine_wire", None) is not None
            or not isinstance(comp, type)):
        return comp
    sub = _PINNED_WIRE.get(comp)
    if sub is None:
        sub = _PINNED_WIRE[comp] = type(
            comp.__name__ + "PinnedWire", (comp,),
            {"engine_wire": "none"})
    return sub


def select_in(resolve, default, overrides):
    """Shared ``Compression.select`` construction (members pinned — see
    :func:`pin_engine_wire`)."""
    return _PerTensor(
        pin_engine_wire(resolve(default)),
        {pat: pin_engine_wire(resolve(c))
         for pat, c in overrides.items()})


class Compression:
    """Option pack (reference: compression.py:67-74) + the quantized
    policies and the string registry behind :meth:`resolve`."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int8_ef = Int8ErrorFeedbackCompressor
    fp8 = FP8Compressor

    _registry = {
        "none": NoneCompressor,
        "fp16": FP16Compressor,
        "bf16": BF16Compressor,
        "int8": Int8Compressor,
        "int8_ef": Int8ErrorFeedbackCompressor,
        "fp8": FP8Compressor,
    }

    @classmethod
    def resolve(cls, spec, where: str = "compression"):
        """Normalize a policy spelling — a name from the registry, a
        compressor class/instance, None, or a per-tensor container —
        failing FAST with rank attribution on anything else (a bad
        compressor used to surface as an attribute error mid-step)."""
        return resolve_in(cls._registry, spec, where)

    @classmethod
    def select(cls, default="none", **overrides):
        """Name-based per-tensor policy: ``Compression.select('int8',
        **{'bn*': 'none'})`` quantizes everything except tensors whose
        name matches ``bn*`` (fnmatch; first match wins, keyword order
        is priority). Values resolve through :meth:`resolve`, and every
        member is an EXPLICIT choice — a ``'none'`` entry pins the
        engine wire to full width even under an ``HVD_COMPRESSION``
        default."""
        return select_in(cls.resolve, default, overrides)
