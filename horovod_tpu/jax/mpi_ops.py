"""JAX engine-path async verbs — the host/async twin of the compiled
collectives, with zero-copy donation.

The compiled hot path needs no engine (collectives compile into the
step); this surface exists for host-side async callers that hold jax (or
numpy) arrays — checkpoint shards, metric tensors, host-staged gradient
buckets — the role the reference's framework adapters play over its C++
core (torch/mpi_ops_v2.cc, tensorflow/mpi_ops.cc).

Zero-copy by default where it is safe:

- jax arrays convert through dlpack/``__array_interface__`` into a
  read-only numpy view of the runtime buffer — no host copy.
- ``donate=True`` hands that buffer to the engine outright: the submit
  snapshot is skipped entirely and the engine references the buffer in
  place until completion (reading only — results land in engine-pooled
  buffers), which is always safe for jax arrays because they are
  immutable. The caller must keep its reference semantics in mind: the
  array's buffer is pinned until ``synchronize``.

Without ``donate``, the engine snapshots into a pooled slab (see
core/bufferpool.py) — mutate-after-submit still cannot change what gets
reduced.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.core import get_engine

_name_counter = 0


def _auto_name(prefix: str, name: Optional[str]) -> str:
    global _name_counter
    if name is not None:
        return name
    _name_counter += 1
    return f"jax.{prefix}.noname.{_name_counter}"


def _np_of(tensor) -> np.ndarray:
    """Zero-copy host view of a jax/numpy/dlpack tensor (read-only for
    runtime-owned buffers; never a copy when the protocol allows)."""
    if isinstance(tensor, np.ndarray):
        return tensor
    if hasattr(tensor, "__dlpack__"):
        try:
            return np.from_dlpack(tensor)
        except Exception:
            pass  # device-resident or an old numpy: fall through
    return np.asarray(tensor)


def allreduce_async(tensor, average: bool = True,
                    name: Optional[str] = None,
                    compression: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    """Enqueue an allreduce; returns a handle for :func:`synchronize`.
    ``compression`` is the per-request engine wire policy ('int8'/'fp8');
    ``donate=True`` skips the submit snapshot (ownership handoff);
    ``deadline_ms`` bounds the wait — an overdue request fails its
    waiter with an attributed :class:`CollectiveTimeout` (overrides the
    engine-wide ``HVD_COLLECTIVE_DEADLINE_S`` default); ``priority``
    ('high'/'normal'/'low') is the serving-plane scheduling class —
    higher classes drain first and have their own admission budget
    (overrides the engine-wide ``HVD_PRIORITY`` default)."""
    return get_engine().allreduce_async(
        _auto_name("allreduce", name), _np_of(tensor), average,
        compression=compression, donate=donate, deadline_ms=deadline_ms,
        priority=priority)


def allgather_async(tensor, name: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    return get_engine().allgather_async(
        _auto_name("allgather", name), _np_of(tensor), donate=donate,
        deadline_ms=deadline_ms, priority=priority)


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    donate: bool = False,
                    deadline_ms: Optional[float] = None,
                    priority: Optional[str] = None) -> int:
    return get_engine().broadcast_async(
        _auto_name("broadcast", name), _np_of(tensor), root_rank,
        donate=donate, deadline_ms=deadline_ms, priority=priority)


def allreduce_n_async(tensors, average: bool = True, names=None,
                      compression=None, donate: bool = False,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None) -> list:
    """Batched allreduce submit: the whole list rides ONE engine call
    (``Engine.submit_n`` / ``hvd_engine_enqueue_n``) — one GIL crossing,
    one snapshot pass over name-bound pool slabs, one engine wakeup.
    Returns handles in input order for :func:`synchronize`. ``names``
    aligns per-member engine names with ``tensors`` (auto-named when
    omitted — but pass stable names for slab pre-binding to bite);
    ``compression`` is one engine wire policy for all members or a
    per-member list."""
    from horovod_tpu.core.engine import SubmitRequest

    ts = list(tensors)
    if names is None:
        names = [None] * len(ts)
    comps = (list(compression) if isinstance(compression, (list, tuple))
             else [compression] * len(ts))
    reqs = [SubmitRequest(_auto_name("allreduce", nm), _np_of(t),
                          average=average, compression=c, donate=donate,
                          deadline_ms=deadline_ms, priority=priority)
            for t, nm, c in zip(ts, names, comps)]
    return get_engine().submit_n("allreduce", reqs)


def broadcast_n_async(tensors, root_rank: int, names=None,
                      donate: bool = False,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None) -> list:
    """Batched broadcast submit — the grouped state-sync twin of
    :func:`allreduce_n_async` (one engine call for a whole parameter
    list)."""
    from horovod_tpu.core.engine import SubmitRequest

    ts = list(tensors)
    if names is None:
        names = [None] * len(ts)
    reqs = [SubmitRequest(_auto_name("broadcast", nm), _np_of(t),
                          root_rank=root_rank, donate=donate,
                          deadline_ms=deadline_ms, priority=priority)
            for t, nm in zip(ts, names)]
    return get_engine().submit_n("broadcast", reqs)


def allreduce_n(tensors, average: bool = True, names=None,
                compression=None, donate: bool = False) -> list:
    """Blocking grouped allreduce: batched submit, then drain every
    handle (results in input order)."""
    return [synchronize(h) for h in
            allreduce_n_async(tensors, average, names, compression,
                              donate)]


def broadcast_n(tensors, root_rank: int, names=None,
                donate: bool = False) -> list:
    return [synchronize(h) for h in
            broadcast_n_async(tensors, root_rank, names, donate)]


def poll(handle: int) -> bool:
    return get_engine().poll(handle)


def synchronize(handle: int) -> np.ndarray:
    """Block until completion; returns the host result (a view of an
    engine-pooled buffer — recycled once the caller drops it)."""
    return get_engine().synchronize(handle)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression: Optional[str] = None,
              donate: bool = False) -> np.ndarray:
    return synchronize(allreduce_async(tensor, average, name,
                                       compression, donate))


def allgather(tensor, name: Optional[str] = None,
              donate: bool = False) -> np.ndarray:
    return synchronize(allgather_async(tensor, name, donate))


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              donate: bool = False) -> np.ndarray:
    return synchronize(broadcast_async(tensor, root_rank, name, donate))
