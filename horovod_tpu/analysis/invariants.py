"""AST rule pack enforcing the CLAUDE.md engine contracts.

Each rule mechanizes one load-bearing invariant that previously existed
only as prose plus post-hoc review hardening. Rules are deliberately
narrow: they encode the exact anti-pattern each incident taught us, and
they must exit clean on the live tree — a rule that needs an allowlist
to pass HEAD is mis-specified.

Scanned surfaces: ``horovod_tpu/``, ``examples/``, and ``tests/`` (the
worker scripts spawn real engine worlds), plus the two import-free
entrypoints (``bench.py``, ``horovod_tpu/run.py``).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Set, Tuple

from horovod_tpu.analysis.report import Finding

ASYNC_SUBMITS = ("allreduce_async", "allgather_async", "broadcast_async")

# Methods that mutate a numpy array in place through an attribute call.
_MUTATING_METHODS = {"fill", "sort", "put", "itemset", "partition",
                     "setflags", "resize"}


def _iter_py_files(root: str) -> Iterable[str]:
    for sub in ("horovod_tpu", "examples", "tests"):
        top = os.path.join(root, sub)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _parse(path: str) -> Optional[ast.AST]:
    try:
        return ast.parse(open(path).read(), filename=path)
    except SyntaxError:
        return None


def _attr_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# ---------------------------------------------------------------------------
# Rule: tf-bridge-group
# ---------------------------------------------------------------------------

def _py_function_bodies(tree: ast.AST) -> List[ast.FunctionDef]:
    """FunctionDefs handed to ``tf.py_function`` (by name, anywhere in
    the file — the bridge idiom defines ``fn`` right next to the call)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _attr_name(node.func) == "py_function" and node.args:
            first = node.args[0]
            if isinstance(first, ast.Name):
                names.add(first.id)
        # tf.py_function(func=fn, ...) spelling
        if isinstance(node, ast.Call) and \
                _attr_name(node.func) == "py_function":
            for kw in node.keywords:
                if kw.arg == "func" and isinstance(kw.value, ast.Name):
                    names.add(kw.value.id)
    return [n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name in names]


def check_tf_bridge(tree: ast.AST, rel: str) -> List[Finding]:
    """TF runs py_function bodies strictly sequentially per process, in
    a schedule order that differs across ranks: a loop that submits one
    collective and BLOCKS on it before the next submit (per-tensor
    bridge) wedges cross-rank. Multi-tensor bodies must submit every
    handle first and wait after (``mpi_ops._bridge_group``)."""
    findings = []
    for fn in _py_function_bodies(tree):
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            has_submit = has_wait = False
            for node in ast.walk(loop):
                if isinstance(node, ast.Call):
                    name = _attr_name(node.func)
                    if name in ASYNC_SUBMITS:
                        has_submit = True
                    elif name == "synchronize":
                        has_wait = True
            if has_submit and has_wait:
                findings.append(Finding(
                    "tf-bridge-group", rel, loop.lineno,
                    f"py_function body {fn.name!r} submits and waits on "
                    "engine collectives inside one loop — a per-tensor "
                    "blocking bridge deadlocks cross-rank under TF's "
                    "sequential executor; submit every handle first, "
                    "then wait (see mpi_ops._bridge_group)"))
    return findings


# ---------------------------------------------------------------------------
# Rule: engine-lifecycle
# ---------------------------------------------------------------------------

def check_engine_lifecycle(tree: ast.AST, rel: str) -> List[Finding]:
    """Never destroy the C++ engine (waiters may still be inside
    WaitMeta — quiesce with hvd_engine_join, then leak), and abandon
    paths must not join anything: the whole point of abandon() is that
    a wedged thread never returns."""
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _attr_name(node.func) == "hvd_engine_destroy":
            findings.append(Finding(
                "engine-lifecycle", rel, node.lineno,
                "hvd_engine_destroy() call: destroying the engine can "
                "free a condition variable a synchronize() caller is "
                "still blocked on (UB) — hvd_engine_join then leak"))
    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name.startswith("abandon")):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _attr_name(node.func)
            if name == "hvd_engine_join":
                findings.append(Finding(
                    "engine-lifecycle", rel, node.lineno,
                    f"{fn.name}() calls hvd_engine_join: the loop "
                    "thread is wedged in a dead backend by definition "
                    "of abandonment — the join never returns"))
            elif name == "join" and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Attribute) \
                    and "thread" in node.func.value.attr:
                findings.append(Finding(
                    "engine-lifecycle", rel, node.lineno,
                    f"{fn.name}() joins {node.func.value.attr}: abandon "
                    "paths must signal and PARK, never join a possibly-"
                    "wedged thread"))
    return findings


# ---------------------------------------------------------------------------
# Rule: donate-mutate
# ---------------------------------------------------------------------------

def check_donate_mutate(tree: ast.AST, rel: str) -> List[Finding]:
    """``donate=True`` is an ownership handoff: the engine references
    the buffer in place and the caller must not write it again before
    the handle completes. Catch same-scope mutations between the donate
    submit and the next synchronize."""
    findings = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        # Line spans of ``with pytest.raises(...)`` blocks: a donate
        # submit in one is EXPECTED to be rejected, after which the
        # ownership handoff never happened and the caller may mutate
        # freely (the rejected-donation contract, test_zero_copy.py).
        rejected_spans: List[Tuple[int, int]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.With) and any(
                    isinstance(item.context_expr, ast.Call)
                    and _attr_name(item.context_expr.func) == "raises"
                    for item in node.items):
                last = max((getattr(n, "lineno", node.lineno)
                            for n in ast.walk(node)), default=node.lineno)
                rejected_spans.append((node.lineno, last))
        donates: List[Tuple[str, int]] = []  # (buffer name, lineno)
        sync_lines: List[int] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _attr_name(node.func)
            if name == "synchronize":
                sync_lines.append(node.lineno)
            if name not in ASYNC_SUBMITS:
                continue
            if any(a <= node.lineno <= b for a, b in rejected_spans):
                continue
            if not any(kw.arg == "donate"
                       and isinstance(kw.value, ast.Constant)
                       and kw.value.value is True
                       for kw in node.keywords):
                continue
            buf = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Name):
                buf = node.args[1].id
            else:
                for kw in node.keywords:
                    if kw.arg == "tensor" and isinstance(kw.value, ast.Name):
                        buf = kw.value.id
            if buf is not None:
                donates.append((buf, node.lineno))
        for buf, at in donates:
            horizon = min((s for s in sync_lines if s > at),
                          default=float("inf"))
            for node in ast.walk(fn):
                line = getattr(node, "lineno", 0)
                if not at < line < horizon:
                    continue
                mutated = False
                if isinstance(node, ast.Assign):
                    mutated = any(
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == buf for t in node.targets)
                elif isinstance(node, ast.AugAssign):
                    tgt = node.target
                    mutated = (isinstance(tgt, ast.Name)
                               and tgt.id == buf) or (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == buf)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _MUTATING_METHODS and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id == buf:
                        mutated = True
                    elif _attr_name(f) == "copyto" and node.args and \
                            isinstance(node.args[0], ast.Name) and \
                            node.args[0].id == buf:
                        mutated = True
                    elif any(kw.arg == "out"
                             and isinstance(kw.value, ast.Name)
                             and kw.value.id == buf
                             for kw in node.keywords):
                        mutated = True
                if mutated:
                    findings.append(Finding(
                        "donate-mutate", rel, line,
                        f"{buf!r} was handed to the engine with "
                        f"donate=True at line {at} and is mutated "
                        "before synchronize — the engine may still be "
                        "reading it (donate-then-mutate is documented "
                        "UB)"))
    return findings


# ---------------------------------------------------------------------------
# Rule: eager-drain
# ---------------------------------------------------------------------------

def check_eager_drain(tree: ast.AST, rel: str) -> List[Finding]:
    """Trainer ``broadcast_state`` methods must broadcast HOST leaves
    and drain before returning: mesh-sharded inputs with async work in
    flight recompile the eager broadcast programs mid-flight and wedge
    the 8-device rendezvous (the r4 second-fit hang). The host-first
    pattern is: jax.device_get first, broadcast, block_until_ready."""
    findings = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "broadcast_state"):
                continue
            bcasts = [n.lineno for n in ast.walk(fn)
                      if isinstance(n, ast.Call)
                      and _attr_name(n.func) == "broadcast_pytree"]
            if not bcasts:
                continue
            pulls = [n.lineno for n in ast.walk(fn)
                     if isinstance(n, ast.Call)
                     and _attr_name(n.func) == "device_get"]
            drains = [n.lineno for n in ast.walk(fn)
                      if isinstance(n, ast.Call)
                      and _attr_name(n.func) == "block_until_ready"]
            if not pulls or min(pulls) > min(bcasts):
                findings.append(Finding(
                    "eager-drain", rel, fn.lineno,
                    f"{cls.name}.broadcast_state broadcasts state "
                    "without pulling it to host first (jax.device_get "
                    "before the first broadcast_pytree) — sharded "
                    "inputs recompile the eager programs and wedge the "
                    "rendezvous"))
            if not drains or max(drains) < max(bcasts):
                findings.append(Finding(
                    "eager-drain", rel, fn.lineno,
                    f"{cls.name}.broadcast_state returns without "
                    "draining (block_until_ready after the last "
                    "broadcast_pytree) — async work left in flight "
                    "races the next compile"))
    return findings


# ---------------------------------------------------------------------------
# Rule: lock-order
# ---------------------------------------------------------------------------

# Documented hierarchy (CLAUDE.md / docs/static-analysis.md): rank 1 =
# engine locks (Engine._lock, NativeEngine._stats_lock), rank 2 = pool
# lock (BufferPool._lock), rank 3 = telemetry leaf locks. Lower rank is
# OUTER: acquiring a lower-ranked lock (or calling a method that does)
# while holding a higher-ranked one is an inversion.
_ENGINE_CLASSES = {"Engine", "NativeEngine"}
_POOL_CLASSES = {"BufferPool"}
_TELEMETRY_LEAVES = {"inc", "set", "observe", "push"}
_REGISTRY_FACTORIES = {"counter", "gauge", "histogram"}


def _lock_rank(expr: ast.AST, cls_name: Optional[str]) -> Optional[int]:
    """Rank of a ``with <expr>:`` acquisition, or None if not a known
    lock. ``self._lock`` ranks by the enclosing class; ``<pool>._lock``
    ranks 2 by receiver name."""
    if not (isinstance(expr, ast.Attribute) and "lock" in expr.attr):
        return None
    recv = expr.value
    if isinstance(recv, ast.Name) and recv.id == "self":
        if cls_name in _POOL_CLASSES:
            return 2
        if cls_name in _ENGINE_CLASSES:
            return 1
        return None
    recv_name = ""
    if isinstance(recv, ast.Attribute):
        recv_name = recv.attr
    elif isinstance(recv, ast.Name):
        recv_name = recv.id
    if "pool" in recv_name.lower():
        return 2
    if "engine" in recv_name.lower():
        return 1
    return None


def _acquirer_table(trees: Dict[str, ast.AST]) -> Dict[str, int]:
    """Method name -> rank of the lock its body acquires directly (the
    table the call-under-lock check resolves names against)."""
    table: Dict[str, int] = {}
    for tree in trees.values():
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            r = _lock_rank(item.context_expr, cls.name)
                            if r is not None:
                                prev = table.get(fn.name)
                                table[fn.name] = (r if prev is None
                                                  else min(prev, r))
    # One-level transitive closure: wrappers that call an acquirer of
    # their own class (BufferPool.checkout -> checkout_tracked).
    for tree in trees.values():
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef) or \
                        fn.name in table:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        callee = _attr_name(node.func)
                        if callee in table:
                            table[fn.name] = table[callee]
    return table


def check_lock_order(trees: Dict[str, ast.AST]) -> List[Finding]:
    findings = []
    acquirers = _acquirer_table(trees)
    for rel, tree in trees.items():
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in ast.walk(cls):
                if not isinstance(fn, ast.FunctionDef):
                    continue
                for w in ast.walk(fn):
                    if not isinstance(w, ast.With):
                        continue
                    held = [r for item in w.items
                            for r in [_lock_rank(item.context_expr,
                                                 cls.name)]
                            if r is not None]
                    if not held:
                        continue
                    rank = min(held)
                    for node in [n for stmt in w.body
                                 for n in ast.walk(stmt)]:
                        inner: Optional[int] = None
                        where = getattr(node, "lineno", w.lineno)
                        what = ""
                        if isinstance(node, ast.With):
                            for item in node.items:
                                r = _lock_rank(item.context_expr, cls.name)
                                if r is not None:
                                    inner = r
                                    what = ast.unparse(item.context_expr)
                        elif isinstance(node, ast.Call):
                            callee = _attr_name(node.func)
                            if callee in _TELEMETRY_LEAVES or \
                                    callee in _REGISTRY_FACTORIES:
                                inner = 3
                                what = f"{callee}() [telemetry]"
                            elif callee in acquirers and \
                                    callee != fn.name:
                                inner = acquirers[callee]
                                what = f"{callee}()"
                        if inner is not None and inner < rank:
                            findings.append(Finding(
                                "lock-order", rel, where,
                                f"{cls.name}.{fn.name} acquires rank-"
                                f"{inner} lock via {what} while holding "
                                f"a rank-{rank} lock — inverts the "
                                "documented hierarchy (engine > pool > "
                                "telemetry)"))
    return findings


# ---------------------------------------------------------------------------
# Rule: fault-site-registry
# ---------------------------------------------------------------------------

# A faultline SPEC reference: site:mode:count with the real grammar's
# count shapes (N / * / P%, optional @M) — the count anchor is what keeps
# prose like "docs/running.md:32" from matching. Sites are dotted
# lowercase words, exactly as core/faultline.py declares them.
_FAULT_SPEC_RE = re.compile(
    r"\b([a-z_]+(?:\.[a-z_]+)+):([a-z]+):(\*|\d+%?)(?:@\d+)?")

# Text surfaces where chaos specs are referenced (beyond the python
# files the invariant scan already walks).
_FAULT_DOC_GLOBS = ("docs", "CLAUDE.md")


def _fault_registry(root: str):
    """Parse SITES, _MODES and the site->guard-helper map out of
    core/faultline.py. None when the file is absent (fixture roots)."""
    path = os.path.join(root, "horovod_tpu", "core", "faultline.py")
    if not os.path.exists(path):
        return None
    tree = _parse(path)
    if tree is None:
        return None
    sites: Tuple[str, ...] = ()
    modes: Dict[str, Tuple[str, ...]] = {}
    for node in tree.body:  # type: ignore[attr-defined]
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        if tgt == "SITES" and isinstance(node.value, (ast.Tuple, ast.List)):
            sites = tuple(e.value for e in node.value.elts
                          if isinstance(e, ast.Constant))
        elif tgt == "_MODES" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(v, (ast.Tuple, ast.List)):
                    modes[k.value] = tuple(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant))
    helpers: Dict[str, str] = {}  # site -> guard helper function name
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    _attr_name(node.func) == "check" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in sites:
                helpers.setdefault(node.args[0].value, fn.name)
    return sites, modes, helpers


def check_fault_sites(root: str) -> List[Finding]:
    """Every faultline site string referenced in tests/docs/specs must
    resolve to a site (and mode) the registry declares, and every
    declared site must actually be THREADED — its guard helper called
    from real source outside faultline.py. A renamed or unthreaded site
    would otherwise turn the chaos tests that reference it inert while
    they keep passing."""
    reg = _fault_registry(root)
    if reg is None:
        return []
    sites, modes, helpers = reg
    rel_flt = os.path.join("horovod_tpu", "core", "faultline.py")
    findings: List[Finding] = []
    for site in sites:
        if site not in helpers:
            findings.append(Finding(
                "fault-site-registry", rel_flt, 0,
                f"fault site {site!r} is declared in SITES but has no "
                "check(\"<site>\") guard helper in faultline.py"))
    # Threading: each guard helper must be invoked from non-faultline
    # source (horovod_tpu/ only — tests exercising a helper directly do
    # not make the site threaded in the product).
    called: Set[str] = set()
    pkg = os.path.join(root, "horovod_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py") or fn == "faultline.py":
                continue
            tree = _parse(os.path.join(dirpath, fn))
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    name = _attr_name(node.func)
                    if name:
                        called.add(name)
    for site, helper in sorted(helpers.items()):
        if helper not in called:
            findings.append(Finding(
                "fault-site-registry", rel_flt, 0,
                f"fault site {site!r} has a guard helper {helper}() "
                "that is never called from horovod_tpu/ source — the "
                "site is declared but not threaded, so chaos specs "
                "naming it inject nothing"))
    # Spec references: python files under the scanned trees plus the
    # markdown docs; every site:mode:count string must resolve.
    scan = list(_iter_py_files(root))
    docs_dir = os.path.join(root, _FAULT_DOC_GLOBS[0])
    if os.path.isdir(docs_dir):
        scan += [os.path.join(docs_dir, f)
                 for f in sorted(os.listdir(docs_dir))
                 if f.endswith(".md")]
    claude = os.path.join(root, _FAULT_DOC_GLOBS[1])
    if os.path.exists(claude):
        scan.append(claude)
    for path in scan:
        try:
            text = open(path).read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        # Negative-grammar fixtures: a function that references
        # FaultSpecError is TESTING rejection — the deliberately-invalid
        # specs inside it are not site references.
        exempt: List[Tuple[int, int]] = []
        if path.endswith(".py"):
            tree = _parse(path)
            if tree is not None:
                for fn in ast.walk(tree):
                    if isinstance(fn, ast.FunctionDef) and any(
                            isinstance(n, (ast.Name, ast.Attribute))
                            and _attr_name(n) == "FaultSpecError"
                            for n in ast.walk(fn)):
                        last = max((getattr(n, "lineno", fn.lineno)
                                    for n in ast.walk(fn)),
                                   default=fn.lineno)
                        exempt.append((fn.lineno, last))
        for m in _FAULT_SPEC_RE.finditer(text):
            site, mode = m.group(1), m.group(2)
            line = text.count("\n", 0, m.start()) + 1
            if any(a <= line <= b for a, b in exempt):
                continue
            if site not in sites:
                findings.append(Finding(
                    "fault-site-registry", rel, line,
                    f"fault spec references site {site!r}, which "
                    "core/faultline.py SITES does not declare — a "
                    "renamed site silently turns this chaos spec "
                    "inert"))
            elif mode not in modes.get(site, ()):
                findings.append(Finding(
                    "fault-site-registry", rel, line,
                    f"fault spec references mode {mode!r} for site "
                    f"{site!r}; valid modes: "
                    f"{', '.join(modes.get(site, ()))}"))
    return findings


# ---------------------------------------------------------------------------
# Rule: entrypoint-imports
# ---------------------------------------------------------------------------

def check_entrypoint_imports(root: str,
                             entrypoints: Optional[List[str]] = None
                             ) -> List[Finding]:
    """``bench.py --help/--dry`` and ``run.py`` (the launcher) must not
    import jax or any framework at module level: argparse errors must
    never pay the multi-second import, and the launcher must survive on
    hosts where the frameworks are absent. tests/test_bench_contract.py
    proves the runtime behavior with a poisoned sys.path; this rule
    fails the diff at analysis time instead of the subprocess tier."""
    findings = []
    stdlib = getattr(sys, "stdlib_module_names", frozenset())
    for rel in entrypoints or ("bench.py",
                               os.path.join("horovod_tpu", "run.py")):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                "entrypoint-imports", rel, 0,
                "import-free entrypoint is missing"))
            continue
        tree = _parse(path)
        if tree is None:
            findings.append(Finding("entrypoint-imports", rel, 0,
                                    "entrypoint does not parse"))
            continue
        for node in tree.body:
            mods: List[Tuple[str, int]] = []
            if isinstance(node, ast.Import):
                mods = [(a.name.split(".")[0], node.lineno)
                        for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module:
                    mods = [(node.module.split(".")[0], node.lineno)]
            for mod, line in mods:
                if mod not in stdlib:
                    findings.append(Finding(
                        "entrypoint-imports", rel, line,
                        f"module-level import of non-stdlib {mod!r} — "
                        "this entrypoint must stay import-free (defer "
                        "the import into the function that needs it)"))
    return findings


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------

# Files whose lock usage participates in the documented hierarchy.
LOCK_SCOPE = (
    os.path.join("horovod_tpu", "core", "engine.py"),
    os.path.join("horovod_tpu", "core", "native_engine.py"),
    os.path.join("horovod_tpu", "core", "bufferpool.py"),
)


def check(root: str,
          files: Optional[List[str]] = None,
          lock_files: Optional[List[str]] = None,
          entrypoints: Optional[List[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    paths = files if files is not None else list(_iter_py_files(root))
    for path in paths:
        tree = _parse(path)
        if tree is None:
            continue
        rel = os.path.relpath(path, root)
        findings.extend(check_tf_bridge(tree, rel))
        findings.extend(check_engine_lifecycle(tree, rel))
        findings.extend(check_donate_mutate(tree, rel))
        findings.extend(check_eager_drain(tree, rel))
    lock_trees: Dict[str, ast.AST] = {}
    for rel in lock_files or LOCK_SCOPE:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            tree = _parse(path)
            if tree is not None:
                lock_trees[rel] = tree
    findings.extend(check_lock_order(lock_trees))
    findings.extend(check_entrypoint_imports(root, entrypoints))
    findings.extend(check_fault_sites(root))
    return findings
