"""C-ABI consistency checker: ``hvdcore.cc`` vs the ctypes binding.

The two sides of the native-engine ABI are maintained by hand in two
languages (``struct hvd_*`` + exported ``hvd_engine_*`` signatures in
C++; ``ctypes.Structure`` mirrors + ``argtypes``/``restype`` in
``core/native/__init__.py``). A skew — a field added on one side, an
argument widened, an order swap — corrupts silently at runtime because
ctypes trusts the declarations. This checker parses BOTH sides
independently (cparse.py for the C subset, ``ast`` for the Python) and
diffs them field-by-field and argument-by-argument.

Path conventions (overridable for fixture tests): the C source is
``horovod_tpu/core/native/hvdcore.cc`` and the binding is
``horovod_tpu/core/native/__init__.py`` under the given root.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from horovod_tpu.analysis import cparse
from horovod_tpu.analysis.report import Finding

# C struct name -> ctypes.Structure mirror class name.
STRUCT_MIRRORS = {
    "hvd_request": "HvdRequest",
    "hvd_result": "HvdResult",
    "hvd_engine_stats": "HvdStats",
    "hvd_engine_latency": "HvdLatency",
}

# C typedef name -> CFUNCTYPE constant name.
CALLBACK_MIRRORS = {
    "hvd_exec_fn": "EXEC_FN",
    "hvd_negotiate_fn": "NEG_FN",
}

# Canonical C type -> acceptable ctypes tokens (argument position).
# Pointer-to-struct params map through the mirror classes; ``char**``
# accepts the binding's deliberate ``POINTER(c_void_p)`` (the decision
# string travels as a raw hvd_alloc pointer) as well as the natural
# spelling.
_ARG_MAP: Dict[str, Tuple[str, ...]] = {
    "int": ("c_int",),
    "double": ("c_double",),
    "long long": ("c_longlong",),
    "char*": ("c_char_p",),
    "const char*": ("c_char_p",),
    "void*": ("c_void_p",),
    "const void*": ("c_void_p",),
    "int*": ("POINTER(c_int)",),
    "double*": ("POINTER(c_double)",),
    "long long*": ("POINTER(c_longlong)",),
    "const long long*": ("POINTER(c_longlong)",),
    "char**": ("POINTER(c_void_p)", "POINTER(c_char_p)"),
    "hvd_exec_fn": ("EXEC_FN",),
    "hvd_negotiate_fn": ("NEG_FN",),
    "hvd_request*": ("POINTER(HvdRequest)",),
    "hvd_result*": ("POINTER(HvdResult)",),
    "hvd_engine_stats*": ("POINTER(HvdStats)",),
    "hvd_engine_latency*": ("POINTER(HvdLatency)",),
}

# Canonical C type -> ctypes token inside a Structure (by-value field).
_FIELD_MAP: Dict[str, str] = {
    "int": "c_int",
    "double": "c_double",
    "long long": "c_longlong",
    "char": "c_char",
    "const char*": "c_char_p",
    "char*": "c_char_p",
    "void*": "c_void_p",
}


def _ctypes_token(node: ast.AST) -> str:
    """Canonical string for a ctypes type expression in the binding:
    ``ctypes.c_int`` -> ``c_int``; ``ctypes.c_longlong * 8`` ->
    ``c_longlong*8``; ``ctypes.POINTER(ctypes.c_int)`` ->
    ``POINTER(c_int)``; bare names (EXEC_FN, HvdStats) pass through."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        left = _ctypes_token(node.left)
        if isinstance(node.right, ast.Constant):
            return f"{left}*{node.right.value}"
    if isinstance(node, ast.Call):
        fn = _ctypes_token(node.func)
        args = ", ".join(_ctypes_token(a) for a in node.args)
        return f"{fn}({args})"
    return ast.dump(node)


class Binding:
    """The ctypes side, parsed from core/native/__init__.py via ast."""

    def __init__(self, path: str):
        self.path = path
        src = open(path).read()
        tree = ast.parse(src, filename=path)
        # Structure mirrors: class X(ctypes.Structure) with _fields_.
        self.structs: Dict[str, List[Tuple[str, str, int]]] = {}
        # lib.<name>.argtypes / restype assignments anywhere in the file.
        self.argtypes: Dict[str, List[str]] = {}
        self.restypes: Dict[str, str] = {}
        self.lines: Dict[str, int] = {}
        # CFUNCTYPE constants: NAME = ctypes.CFUNCTYPE(ret, args...).
        self.callbacks: Dict[str, Tuple[str, List[str]]] = {}
        # argtypes/restype declarations are read from load_library()
        # ONLY: other builders in the module (load_termshield) declare
        # different libraries' symbols, which are not this ABI.
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._read_class(node)
            elif isinstance(node, ast.Assign):
                self._read_assign(node)
            elif (isinstance(node, ast.FunctionDef)
                    and node.name == "load_library"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        self._read_assign(sub)

    def _read_class(self, node: ast.ClassDef):
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_fields_"
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                fields = []
                for elt in stmt.value.elts:
                    if not (isinstance(elt, ast.Tuple)
                            and len(elt.elts) == 2
                            and isinstance(elt.elts[0], ast.Constant)):
                        continue
                    fields.append((elt.elts[0].value,
                                   _ctypes_token(elt.elts[1]),
                                   elt.lineno))
                self.structs[node.name] = fields
                self.lines[node.name] = node.lineno

    def _read_assign(self, node: ast.Assign):
        if len(node.targets) != 1:
            return
        tgt = node.targets[0]
        # lib.<fn>.argtypes / lib.<fn>.restype
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Attribute)
                and isinstance(tgt.value.value, ast.Name)):
            fn = tgt.value.attr
            if tgt.attr == "argtypes" and isinstance(node.value, ast.List):
                self.argtypes[fn] = [_ctypes_token(e)
                                     for e in node.value.elts]
                self.lines[fn] = node.lineno
            elif tgt.attr == "restype":
                self.restypes[fn] = _ctypes_token(node.value)
                self.lines.setdefault(fn, node.lineno)
        # EXEC_FN = ctypes.CFUNCTYPE(...)
        elif (isinstance(tgt, ast.Name) and isinstance(node.value, ast.Call)
              and _ctypes_token(node.value.func) == "CFUNCTYPE"):
            toks = [_ctypes_token(a) for a in node.value.args]
            self.callbacks[tgt.id] = (toks[0], toks[1:])
            self.lines[tgt.id] = node.lineno


def _field_token(f: cparse.Field) -> Optional[str]:
    base = _FIELD_MAP.get(f.ctype)
    if base is None:
        return None
    return f"{base}*{f.array}" if f.array is not None else base


def check(root: str,
          cc_path: Optional[str] = None,
          binding_path: Optional[str] = None) -> List[Finding]:
    cc_path = cc_path or os.path.join(
        root, "horovod_tpu", "core", "native", "hvdcore.cc")
    binding_path = binding_path or os.path.join(
        root, "horovod_tpu", "core", "native", "__init__.py")
    cc_rel = os.path.relpath(cc_path, root)
    py_rel = os.path.relpath(binding_path, root)
    src = open(cc_path).read()
    findings: List[Finding] = []

    structs = cparse.parse_structs(src)
    funcs = cparse.parse_extern_c_functions(src)
    typedefs = cparse.parse_fn_typedefs(src)
    binding = Binding(binding_path)

    # -- structs vs ctypes.Structure mirrors -------------------------------
    for cname, pyname in STRUCT_MIRRORS.items():
        cfields = structs.get(cname)
        pyfields = binding.structs.get(pyname)
        if cfields is None:
            findings.append(Finding(
                "abi-struct", cc_rel, 0,
                f"struct {cname} not found in {cc_rel}"))
            continue
        if pyfields is None:
            findings.append(Finding(
                "abi-struct", py_rel, 0,
                f"ctypes mirror {pyname} (of struct {cname}) not found"))
            continue
        n = max(len(cfields), len(pyfields))
        for i in range(n):
            if i >= len(cfields):
                name, tok, line = pyfields[i]
                findings.append(Finding(
                    "abi-struct", py_rel, line,
                    f"{pyname}.{name} has no counterpart at index {i} of "
                    f"struct {cname} — the mirror is longer than the C "
                    "struct"))
                continue
            if i >= len(pyfields):
                f = cfields[i]
                findings.append(Finding(
                    "abi-struct", cc_rel, f.line,
                    f"struct {cname}.{f.name} (index {i}) is missing "
                    f"from the ctypes mirror {pyname}"))
                continue
            f = cfields[i]
            name, tok, line = pyfields[i]
            expect = _field_token(f)
            if expect is None:
                findings.append(Finding(
                    "abi-struct", cc_rel, f.line,
                    f"struct {cname}.{f.name}: C type {f.ctype!r} is "
                    "outside the checked ABI vocabulary — extend "
                    "analysis/abi.py if this is intentional"))
                continue
            if name != f.name:
                findings.append(Finding(
                    "abi-struct", py_rel, line,
                    f"{pyname} field {i} is {name!r} but struct "
                    f"{cname} declares {f.name!r} at that index — "
                    "order/name skew"))
            if tok != expect:
                findings.append(Finding(
                    "abi-struct", py_rel, line,
                    f"{pyname}.{name} is declared {tok} but struct "
                    f"{cname}.{f.name} is {f.ctype}"
                    f"{f'[{f.array}]' if f.array else ''} "
                    f"(expected {expect})"))

    # -- exported signatures vs argtypes/restype ---------------------------
    for name, fn in sorted(funcs.items()):
        declared = binding.argtypes.get(name)
        if declared is None:
            findings.append(Finding(
                "abi-signature", py_rel, 0,
                f"exported symbol {name} has no argtypes declaration in "
                "load_library() — ctypes would default every argument "
                "to int"))
            continue
        line = binding.lines.get(name, 0)
        expect = []
        bad = False
        for arg in fn.args:
            acc = _ARG_MAP.get(arg)
            if acc is None:
                findings.append(Finding(
                    "abi-signature", cc_rel, fn.line,
                    f"{name}: C argument type {arg!r} is outside the "
                    "checked ABI vocabulary — extend analysis/abi.py"))
                bad = True
                break
            expect.append(acc)
        if bad:
            continue
        if len(declared) != len(expect):
            findings.append(Finding(
                "abi-signature", py_rel, line,
                f"{name}: C declares {len(expect)} argument(s) but "
                f"argtypes lists {len(declared)}"))
        else:
            for i, (tok, acc) in enumerate(zip(declared, expect)):
                if tok not in acc:
                    findings.append(Finding(
                        "abi-signature", py_rel, line,
                        f"{name} argument {i}: argtypes says {tok} but "
                        f"the C signature says {fn.args[i]!r} (expected "
                        f"{' or '.join(acc)})"))
        # Return type: void -> no restype required (ctypes' default int
        # return is discarded); anything else must be declared exactly.
        restype = binding.restypes.get(name)
        if fn.ret == "void":
            if restype not in (None, "None"):
                findings.append(Finding(
                    "abi-signature", py_rel, line,
                    f"{name} returns void but restype is declared "
                    f"{restype}"))
        elif fn.ret == "int":
            if restype not in (None, "c_int"):
                findings.append(Finding(
                    "abi-signature", py_rel, line,
                    f"{name} returns int but restype is declared "
                    f"{restype}"))
        else:
            acc = _ARG_MAP.get(fn.ret)
            if acc is None:
                findings.append(Finding(
                    "abi-signature", cc_rel, fn.line,
                    f"{name}: C return type {fn.ret!r} is outside the "
                    "checked ABI vocabulary"))
            elif restype is None:
                findings.append(Finding(
                    "abi-signature", py_rel, line,
                    f"{name} returns {fn.ret} but load_library() never "
                    "declares a restype — ctypes would truncate it to "
                    "int"))
            elif restype not in acc:
                findings.append(Finding(
                    "abi-signature", py_rel, line,
                    f"{name}: restype is {restype} but the C return "
                    f"type is {fn.ret!r} (expected {' or '.join(acc)})"))

    # Binding declarations for symbols the C side no longer exports.
    for name in binding.argtypes:
        if name not in funcs:
            findings.append(Finding(
                "abi-signature", py_rel, binding.lines.get(name, 0),
                f"load_library() declares argtypes for {name}, which "
                f"{cc_rel} does not export"))

    # -- callback typedefs vs CFUNCTYPE shapes -----------------------------
    for cname, pyname in CALLBACK_MIRRORS.items():
        td = typedefs.get(cname)
        cb = binding.callbacks.get(pyname)
        if td is None:
            findings.append(Finding(
                "abi-callback", cc_rel, 0,
                f"typedef {cname} not found in {cc_rel}"))
            continue
        if cb is None:
            findings.append(Finding(
                "abi-callback", py_rel, 0,
                f"CFUNCTYPE constant {pyname} (mirror of {cname}) not "
                "found"))
            continue
        ret, args = td
        pyret, pyargs = cb
        line = binding.lines.get(pyname, 0)
        if (ret, pyret) != ("int", "c_int"):
            findings.append(Finding(
                "abi-callback", py_rel, line,
                f"{pyname}: return type {pyret} does not match typedef "
                f"{cname}'s {ret!r}"))
        if len(args) != len(pyargs):
            findings.append(Finding(
                "abi-callback", py_rel, line,
                f"{pyname}: {len(pyargs)} argument(s) declared but "
                f"typedef {cname} has {len(args)}"))
        else:
            for i, (carg, parg) in enumerate(zip(args, pyargs)):
                acc = _ARG_MAP.get(carg)
                if acc is None:
                    findings.append(Finding(
                        "abi-callback", cc_rel, 0,
                        f"{cname} argument {i}: C type {carg!r} is "
                        "outside the checked ABI vocabulary"))
                elif parg not in acc:
                    findings.append(Finding(
                        "abi-callback", py_rel, line,
                        f"{pyname} argument {i}: {parg} does not match "
                        f"typedef {cname}'s {carg!r} (expected "
                        f"{' or '.join(acc)})"))
    return findings
