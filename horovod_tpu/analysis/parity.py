"""Cross-engine parity checker: python engine vs libhvdcore.

The two engines owe byte-identical observable surfaces (the Horovod
timeline/telemetry contract): every telemetry counter one engine feeds
must be fed by the other, timeline span vocabularies must match, the
negotiation decision grammar the python control plane emits must be
handled by the C++ parser, and the small value tables both sides
re-declare (dtype names, wire-policy codes, op codes) must not skew.
Before this checker, that parity was pinned only where a test happened
to look; here both sides are read independently from source.

Sources (under the given root, overridable for fixture tests):
- python emit sites: ``core/engine.py`` + ``core/bufferpool.py``
- native emit sites: ``core/native_engine.py`` (direct emits, the
  ``_STAT_COUNTERS`` stats sync, and whatever shared helpers it imports
  from ``core/engine.py``)
- C++ literals/tables: ``core/native/hvdcore.cc``
- span vocabulary: ``core/timeline.py`` module constants
- latency bucket edges: ``core/telemetry.py`` (LATENCY_BUCKETS_S vs the
  C++ ``kLatencyBucketsS`` array — world rollups merge per-rank
  histograms exactly, so the edges must be bit-identical)
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from horovod_tpu.analysis import cparse
from horovod_tpu.analysis.report import Finding

# Span names legitimately written by only ONE side: RANK_READY and the
# HVD_CLOCK metadata are python-computed and pass through the C++
# timeline hooks verbatim (the C++ writer never spells them).
PY_ONLY_SPANS = {"RANK_READY", "HVD_CLOCK"}

# Span-args keys computed python-side and passed through the C++ hooks
# (clock metadata + negotiation readiness), excluded from the span-args
# key diff.
PASS_THROUGH_ARG_KEYS = {"process", "rank", "epoch_wall_us", "offset_us",
                         "rtt_us"}


def _registry_names(tree: ast.AST) -> Set[str]:
    """Telemetry names in ``REGISTRY.counter/gauge/histogram("...")``
    calls. f-strings canonicalize to ``<literal prefix>*``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and node.args):
            continue
        stack = [node.args[0]]
        while stack:
            arg = stack.pop()
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.add(arg.value)
            elif isinstance(arg, ast.JoinedStr) and arg.values and \
                    isinstance(arg.values[0], ast.Constant):
                names.add(str(arg.values[0].value) + "*")
            elif isinstance(arg, ast.IfExp):
                # counter("engine.errors" if err else "engine.completed")
                stack.extend((arg.body, arg.orelse))
    return names


def _function_defs(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)}


def _imported_engine_helpers(native_tree: ast.AST) -> Set[str]:
    """Names native_engine.py imports from core.engine — shared helpers
    whose telemetry emits count as native-fed too (the native engine
    enqueues through them)."""
    names: Set[str] = set()
    for node in ast.walk(native_tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.endswith("core.engine"):
            names.update(a.name for a in node.names)
    return names


def _pair_table(tree: ast.AST, var_name: str) -> List[Tuple[str, str, int]]:
    """A ``VAR = ((reg_name, c_field), ...)`` mapping table read via ast
    (``_STAT_COUNTERS`` and ``_LATENCY_HISTS`` in native_engine.py)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == var_name and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            out = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2 and \
                        all(isinstance(e, ast.Constant) for e in elt.elts):
                    out.append((elt.elts[0].value, elt.elts[1].value,
                                elt.lineno))
            return out
    return []


def _stat_counters(native_tree: ast.AST) -> List[Tuple[str, str, int]]:
    """The ``_STAT_COUNTERS`` (registry name, C stats field) table."""
    return _pair_table(native_tree, "_STAT_COUNTERS")


def _latency_buckets(telemetry_tree: ast.AST) -> List[float]:
    """``LATENCY_BUCKETS_S`` from core/telemetry.py as floats."""
    for node in ast.walk(telemetry_tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "LATENCY_BUCKETS_S" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            return [float(e.value) for e in node.value.elts
                    if isinstance(e, ast.Constant)]
    return []


def _timeline_constants(timeline_tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "SPAN"`` constants of core/timeline.py."""
    out: Dict[str, str] = {}
    for node in timeline_tree.body:  # type: ignore[attr-defined]
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            name = node.targets[0].id
            if name.isupper() and re.fullmatch(r"[A-Z][A-Z_]+",
                                               node.value.value):
                out[name] = node.value.value
    return out


def _py_span_arg_keys(tree: ast.AST) -> Set[str]:
    """String keys of every dict literal in the python engine source.
    Span-args dicts are frequently built away from the timeline call
    (``pool_args = {"pooled": ...}`` then reused on several ends), so
    call-site-only extraction would miss them; engine.py keeps no other
    string-keyed dict literals, which makes the file-wide sweep exact."""
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Assign):
            # Conditional additions: args["wire"] = policy
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.slice, ast.Constant) and \
                        isinstance(tgt.slice.value, str):
                    keys.add(tgt.slice.value)
    return keys


def _cc_span_arg_keys(src: str) -> Set[str]:
    """Arg keys the C++ writer interpolates itself. The engine renders
    args bodies with a space after the colon (``\\"pooled\\": true``)
    and every OTHER JSON it builds (the chrome event skeleton, the
    negotiation table) without one — that formatting convention is what
    separates span-args keys from wire-protocol keys here, and
    hvdcore.cc documents it next to TensorArgs."""
    keys: Set[str] = set()
    for lit, _ in cparse.string_literals(src):
        keys.update(re.findall(r'"([a-z_]+)": ', lit))
    return keys


def _decision_kinds_emitted(native_tree: ast.AST) -> Set[str]:
    """Decision-grammar line kinds emitted inside _make_negotiator:
    f-strings / literals whose constant head matches ``<kind> ``."""
    kinds: Set[str] = set()
    fns = _function_defs(native_tree)
    neg = fns.get("_make_negotiator")
    if neg is None:
        return kinds
    for node in ast.walk(neg):
        head: Optional[str] = None
        if isinstance(node, ast.JoinedStr) and node.values and \
                isinstance(node.values[0], ast.Constant):
            head = str(node.values[0].value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            head = node.value
        if head is not None:
            m = re.match(r"^([a-z]) ", head)
            if m:
                kinds.add(m.group(1))
    return kinds


def _dtype_table(native_tree: ast.AST) -> List[str]:
    """The _DTYPES wire-dtype table of native_engine.py, as dtype-name
    strings in code order (incl. the ml_dtypes.bfloat16 append)."""
    names: List[str] = []
    for node in ast.walk(native_tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_DTYPES" and \
                isinstance(node.value, ast.List):
            for elt in node.value.elts:
                # np.dtype(np.float32) -> "float32"
                if isinstance(elt, ast.Call) and elt.args and \
                        isinstance(elt.args[0], ast.Attribute):
                    names.append(elt.args[0].attr.rstrip("_"))
    # The conditional append: _DTYPES.append(np.dtype(ml_dtypes.bfloat16))
    for node in ast.walk(native_tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "append" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "_DTYPES" and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call) and inner.args and \
                    isinstance(inner.args[0], ast.Attribute):
                names.append(inner.args[0].attr.rstrip("_"))
    return names


def _wire_policies(engine_tree: ast.AST) -> List[str]:
    """ENGINE_WIRE_POLICIES from core/engine.py (code = index)."""
    for node in ast.walk(engine_tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "ENGINE_WIRE_POLICIES" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)]
    return []


def _str_tuple(tree: ast.AST, var_name: str) -> List[str]:
    """A module-level ``VAR = ("a", "b", ...)`` tuple of strings, in
    code order (ENGINE_INSPECT_KEYS, VERDICT_KINDS, _DOCTOR_KINDS)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == var_name and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def _py_inspect_record_keys(engine_tree: ast.AST) -> List[str]:
    """Keyword names, in order, of the ``dict(...)`` record builder
    inside ``Engine.inspect`` — the record shape the python engine
    actually writes (the keyword-call form is deliberate: dict literals
    in engine.py belong to the span-args sweep)."""
    fns = _function_defs(engine_tree)
    fn = fns.get("inspect")
    if fn is None:
        return []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "dict" and node.keywords:
            return [kw.arg for kw in node.keywords if kw.arg]
    return []


def _cc_inspect_record_keys(src: str) -> List[str]:
    """Record keys, in order, of the C++ ``Engine::Inspect`` writer —
    the escaped ``\\"key\\":`` (no space: wire-protocol JSON, not
    span-args) spellings in its body, deduplicated in first-seen order
    (``deadline_remaining_us`` is written in two branches)."""
    try:
        body = cparse.function_body(src, "long long Inspect")
    except cparse.CParseError:
        return []
    keys: List[str] = []
    for key in re.findall(r'\\"([a-z_]+)\\":', body):
        if key not in keys:
            keys.append(key)
    return keys


def _ops_table(native_tree: ast.AST) -> Dict[str, int]:
    for node in ast.walk(native_tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_OPS" and \
                isinstance(node.value, ast.Dict):
            return {k.value: v.value for k, v in
                    zip(node.value.keys, node.value.values)
                    if isinstance(k, ast.Constant)
                    and isinstance(v, ast.Constant)}
    return {}


def check(root: str,
          cc_path: Optional[str] = None,
          engine_path: Optional[str] = None,
          native_path: Optional[str] = None,
          bufferpool_path: Optional[str] = None,
          timeline_path: Optional[str] = None,
          telemetry_path: Optional[str] = None,
          doctor_path: Optional[str] = None,
          stats_path: Optional[str] = None) -> List[Finding]:
    core = os.path.join(root, "horovod_tpu", "core")
    cc_path = cc_path or os.path.join(core, "native", "hvdcore.cc")
    engine_path = engine_path or os.path.join(core, "engine.py")
    native_path = native_path or os.path.join(core, "native_engine.py")
    bufferpool_path = bufferpool_path or os.path.join(core, "bufferpool.py")
    timeline_path = timeline_path or os.path.join(core, "timeline.py")
    telemetry_path = telemetry_path or os.path.join(core, "telemetry.py")
    doctor_path = doctor_path or os.path.join(core, "doctor.py")
    stats_path = stats_path or os.path.join(
        root, "horovod_tpu", "utils", "stats.py")

    cc_rel = os.path.relpath(cc_path, root)
    native_rel = os.path.relpath(native_path, root)
    engine_rel = os.path.relpath(engine_path, root)

    src = open(cc_path).read()
    engine_tree = ast.parse(open(engine_path).read(), filename=engine_path)
    native_tree = ast.parse(open(native_path).read(), filename=native_path)
    pool_tree = ast.parse(open(bufferpool_path).read(),
                          filename=bufferpool_path)
    tl_tree = ast.parse(open(timeline_path).read(), filename=timeline_path)
    tel_tree = ast.parse(open(telemetry_path).read(),
                         filename=telemetry_path)

    findings: List[Finding] = []

    # -- telemetry counters ------------------------------------------------
    # Python engine's full surface: everything engine.py + bufferpool.py
    # emit. Native-fed surface: native_engine.py's own emits + the
    # _STAT_COUNTERS stats sync + the shared engine.py helpers it
    # imports + bufferpool.py (the native engine's python-side pool).
    py_set = _registry_names(engine_tree) | _registry_names(pool_tree)
    engine_fns = _function_defs(engine_tree)
    shared: Set[str] = set()
    for helper in _imported_engine_helpers(native_tree):
        fn = engine_fns.get(helper)
        if fn is not None:
            shared |= _registry_names(fn)
    stat_counters = _stat_counters(native_tree)
    latency_hists = _pair_table(native_tree, "_LATENCY_HISTS")
    native_set = (_registry_names(native_tree) | shared
                  | _registry_names(pool_tree)
                  | {name for name, _, _ in stat_counters}
                  | {name for name, _, _ in latency_hists})
    for name in sorted(py_set - native_set):
        findings.append(Finding(
            "parity-counters", engine_rel, 0,
            f"telemetry name {name!r} is fed by the python engine but "
            "has no native-engine feed (stats sync, shared helper, or "
            "direct emit)"))
    for name in sorted(native_set - py_set):
        findings.append(Finding(
            "parity-counters", native_rel, 0,
            f"telemetry name {name!r} is fed by the native engine but "
            "never by the python engine"))

    # -- stats-sync fields exist in the C struct ---------------------------
    stats_fields = {f.name for f in
                    cparse.parse_structs(src).get("hvd_engine_stats", [])}
    for reg_name, field, line in stat_counters:
        if field not in stats_fields:
            findings.append(Finding(
                "parity-stats-fields", native_rel, line,
                f"_STAT_COUNTERS maps {reg_name!r} to stats field "
                f"{field!r}, which struct hvd_engine_stats does not "
                "declare"))

    # -- latency histograms: bucket edges + C-struct field targets ---------
    py_buckets = _latency_buckets(tel_tree)
    try:
        cc_buckets: Optional[List[float]] = cparse.parse_double_array(
            src, "kLatencyBucketsS")
    except cparse.CParseError:
        cc_buckets = None
    if cc_buckets is None:
        findings.append(Finding(
            "parity-latency", cc_rel, 0,
            "kLatencyBucketsS (the latency histogram bucket edges) not "
            "found in hvdcore.cc"))
    elif cc_buckets != py_buckets:
        findings.append(Finding(
            "parity-latency", cc_rel, 0,
            f"C++ kLatencyBucketsS {cc_buckets} does not match "
            f"telemetry.LATENCY_BUCKETS_S {py_buckets} — per-rank "
            "histograms only merge exactly on identical edges, a skew "
            "corrupts every fleet quantile silently"))
    latency_fields = {f.name for f in
                      cparse.parse_structs(src).get("hvd_engine_latency",
                                                    [])}
    for reg_name, field, line in latency_hists:
        if field not in latency_fields:
            findings.append(Finding(
                "parity-latency", native_rel, line,
                f"_LATENCY_HISTS maps {reg_name!r} to latency field "
                f"{field!r}, which struct hvd_engine_latency does not "
                "declare"))

    # -- timeline span vocabulary ------------------------------------------
    tl_consts = _timeline_constants(tl_tree)
    py_spans = set(tl_consts.values())
    # engine.py's f"NEGOTIATE_{e.op.upper()}" expands over the op table.
    for op in ("allreduce", "allgather", "broadcast"):
        py_spans.add(f"NEGOTIATE_{op.upper()}")
    cc_spans = {lit for lit, _ in cparse.string_literals(src)
                if re.fullmatch(r"[A-Z][A-Z_]{2,}", lit)
                and not lit.startswith("HVD_")}  # HVD_* = env knobs
    for span in sorted(cc_spans - py_spans):
        findings.append(Finding(
            "parity-spans", cc_rel, 0,
            f"C++ timeline span {span!r} has no counterpart constant in "
            "core/timeline.py"))
    for span in sorted((py_spans - PY_ONLY_SPANS) - cc_spans):
        findings.append(Finding(
            "parity-spans", cc_rel, 0,
            f"python timeline span {span!r} is never written by the C++ "
            "timeline (only RANK_READY/HVD_CLOCK may ride the python-"
            "side hooks)"))

    # -- span-args keys ----------------------------------------------------
    py_keys = _py_span_arg_keys(engine_tree)
    cc_keys = _cc_span_arg_keys(src) - PASS_THROUGH_ARG_KEYS
    for key in sorted(cc_keys - py_keys):
        findings.append(Finding(
            "parity-span-args", cc_rel, 0,
            f"C++ span-args key {key!r} is never emitted by the python "
            "engine's timeline calls"))
    for key in sorted((py_keys - PASS_THROUGH_ARG_KEYS) - cc_keys):
        findings.append(Finding(
            "parity-span-args", engine_rel, 0,
            f"python span-args key {key!r} is never emitted by the C++ "
            "timeline writer"))

    # -- negotiation decision grammar --------------------------------------
    emitted = _decision_kinds_emitted(native_tree)
    handled = set(cparse.decision_kinds_handled(src))
    for kind in sorted(emitted - handled):
        findings.append(Finding(
            "parity-grammar", native_rel, 0,
            f"decision line kind {kind!r} is emitted by the python "
            "negotiator but not handled by hvdcore's ParseAndExecute"))
    for kind in sorted(handled - emitted):
        findings.append(Finding(
            "parity-grammar", cc_rel, 0,
            f"decision line kind {kind!r} is handled by hvdcore's "
            "ParseAndExecute but never emitted by the python negotiator"))

    # -- dtype-name table --------------------------------------------------
    cc_dtypes = cparse.parse_string_array(src, "DtypeName")
    py_dtypes = _dtype_table(native_tree)
    if cc_dtypes != py_dtypes:
        findings.append(Finding(
            "parity-dtypes", cc_rel, 0,
            f"C++ dtype table {cc_dtypes} does not match "
            f"native_engine._DTYPES {py_dtypes} — codes are positional, "
            "a skew mislabels every timeline dtype arg"))

    # -- wire-policy codes -------------------------------------------------
    cc_wire = cparse.parse_case_string_map(src, "WireName")
    py_wire = _wire_policies(engine_tree)
    expect_wire = {i: name for i, name in enumerate(py_wire)
                   if name != "none"}
    if cc_wire != expect_wire:
        findings.append(Finding(
            "parity-wire-codes", cc_rel, 0,
            f"C++ WireName map {cc_wire} does not match "
            f"ENGINE_WIRE_POLICIES {py_wire} (expected {expect_wire}; "
            "code 0 = full width, no arg)"))

    # -- hang-doctor contracts ---------------------------------------------
    # (1) Inspect record shape: ENGINE_INSPECT_KEYS (the published
    # contract), the dict(...) record Engine.inspect actually builds,
    # and the C++ Inspect writer's JSON keys must agree, names AND
    # order — the doctor's cross-rank diff compares these records
    # across engines, so a skewed field silently breaks attribution.
    declared_keys = _str_tuple(engine_tree, "ENGINE_INSPECT_KEYS")
    py_rec_keys = _py_inspect_record_keys(engine_tree)
    cc_rec_keys = _cc_inspect_record_keys(src)
    if not declared_keys:
        findings.append(Finding(
            "parity-doctor", engine_rel, 0,
            "ENGINE_INSPECT_KEYS (the inspect-record shape contract) "
            "not found in core/engine.py"))
    else:
        if py_rec_keys != declared_keys:
            findings.append(Finding(
                "parity-doctor", engine_rel, 0,
                f"Engine.inspect builds record keys {py_rec_keys} but "
                f"ENGINE_INSPECT_KEYS declares {declared_keys} (names "
                "and order must match)"))
        if cc_rec_keys != declared_keys:
            findings.append(Finding(
                "parity-doctor", cc_rel, 0,
                f"C++ Inspect writes record keys {cc_rec_keys} but "
                f"ENGINE_INSPECT_KEYS declares {declared_keys} — the "
                "doctor diffs these records across engines, a skewed "
                "field breaks attribution silently"))
    # (2) Verdict vocabulary: the classifier's VERDICT_KINDS and the
    # stats CLI's _DOCTOR_KINDS consumer table (rendering priority)
    # must agree, names and order.
    if os.path.exists(doctor_path) or os.path.exists(stats_path):
        doctor_rel = os.path.relpath(doctor_path, root)
        try:
            doctor_tree = ast.parse(open(doctor_path).read(),
                                    filename=doctor_path)
            stats_tree = ast.parse(open(stats_path).read(),
                                   filename=stats_path)
        except OSError as exc:
            findings.append(Finding(
                "parity-doctor", doctor_rel, 0,
                f"cannot read the doctor vocabulary pair: {exc}"))
        else:
            kinds = _str_tuple(doctor_tree, "VERDICT_KINDS")
            consumed = _str_tuple(stats_tree, "_DOCTOR_KINDS")
            if not kinds:
                findings.append(Finding(
                    "parity-doctor", doctor_rel, 0,
                    "VERDICT_KINDS (the classification vocabulary) not "
                    "found in core/doctor.py"))
            elif kinds != consumed:
                findings.append(Finding(
                    "parity-doctor", doctor_rel, 0,
                    f"doctor.VERDICT_KINDS {kinds} does not match "
                    f"stats._DOCTOR_KINDS {consumed} — a renamed or "
                    "reordered verdict kind renders as unknown on every "
                    "console"))

    # -- op codes ----------------------------------------------------------
    cc_ops = cparse.parse_enum(src, "HvdOp")
    py_ops = _ops_table(native_tree)
    expect_ops = {f"HVD_{name.upper()}": code
                  for name, code in py_ops.items()}
    for name, code in expect_ops.items():
        if cc_ops.get(name) != code:
            findings.append(Finding(
                "parity-ops", cc_rel, 0,
                f"HvdOp.{name} is {cc_ops.get(name)} in C++ but "
                f"native_engine._OPS says {code}"))
    return findings
