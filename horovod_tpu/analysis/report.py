"""Findings + run harness for the hvdcheck static-analysis suite.

Every checker returns a list of :class:`Finding`; the CLI and the tier-1
tests consume the same structures. Exit-code contract (pinned by
tests/test_analysis.py): 0 = clean tree, 2 = findings, 1 = the analysis
itself crashed (a parser stepped outside its subset — fix the parser or
the code that outgrew it; silence is never an option)."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List


@dataclass
class Finding:
    rule: str      # rule id from RULE_CATALOG, e.g. "abi-struct"
    path: str      # repo-relative file the finding is anchored in
    line: int      # 1-based; 0 when the finding spans the whole file
    message: str

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


# rule id -> one-line description (docs/static-analysis.md renders this
# catalog; tests pin that every emitted rule id is declared here).
RULE_CATALOG: Dict[str, str] = {
    "abi-struct": "C ABI struct fields must match the ctypes mirrors "
                  "field-for-field (name, order, width)",
    "abi-signature": "exported hvd_* C signatures must match the "
                     "argtypes/restype declarations in load_library()",
    "abi-callback": "C function-pointer typedefs must match the "
                    "CFUNCTYPE shapes (EXEC_FN/NEG_FN)",
    "parity-counters": "telemetry counter/gauge names must be fed by "
                       "both engines (python emit sites vs the native "
                       "stats sync)",
    "parity-stats-fields": "every native stats-sync field must exist in "
                           "the C hvd_engine_stats struct",
    "parity-spans": "timeline span names must match across the python "
                    "and C++ timeline writers",
    "parity-span-args": "timeline span-args keys must match across the "
                        "two engines' writers",
    "parity-grammar": "negotiation decision-grammar kinds emitted by "
                      "the python control plane must be handled by the "
                      "C++ parser",
    "parity-dtypes": "the C++ dtype-name table must match the python "
                     "wire-dtype table in order and spelling",
    "parity-wire-codes": "the C++ wire-policy code map must match "
                         "WIRE_CODES in core/engine.py",
    "parity-ops": "the C++ HvdOp enum must match the python op codes",
    "parity-latency": "latency histogram bucket edges (kLatencyBucketsS "
                      "vs telemetry.LATENCY_BUCKETS_S) and the native "
                      "_LATENCY_HISTS field targets must match — world "
                      "rollups merge per-rank histograms exactly",
    "parity-doctor": "the inspect record shape (ENGINE_INSPECT_KEYS vs "
                     "Engine.inspect vs the C++ Inspect writer) and the "
                     "hang-doctor verdict vocabulary (doctor."
                     "VERDICT_KINDS vs stats._DOCTOR_KINDS) must match, "
                     "names and order — the doctor diffs records across "
                     "engines and ranks",
    "tf-bridge-group": "no per-tensor blocking engine bridge inside a "
                       "TF py_function loop (use _bridge_group: "
                       "submit-all-then-wait)",
    "engine-lifecycle": "never destroy the C++ engine; abandon paths "
                        "must not join a wedged engine",
    "donate-mutate": "a buffer handed over with donate=True must not "
                     "be mutated before synchronize in the same scope",
    "eager-drain": "trainer broadcast_state methods must pull state to "
                   "host first and drain before returning",
    "lock-order": "lock acquisitions must follow the documented "
                  "hierarchy: engine lock > pool lock > telemetry locks",
    "entrypoint-imports": "bench.py and run.py must stay import-free at "
                          "module level (stdlib only)",
    "fault-site-registry": "every faultline site referenced in "
                           "tests/docs/specs must resolve to a declared "
                           "site+mode, and every declared site must be "
                           "threaded (its guard called from source)",
}


def repo_root(start: str = None) -> str:
    """The repository root: the directory holding ``horovod_tpu/``.
    Resolved from this file so the CLI works from any cwd."""
    if start is not None:
        return start
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def run_all(root: str = None) -> List[Finding]:
    """Every checker against the live tree rooted at ``root``."""
    from horovod_tpu.analysis import abi, invariants, parity

    root = repo_root(root)
    findings: List[Finding] = []
    findings.extend(abi.check(root))
    findings.extend(parity.check(root))
    findings.extend(invariants.check(root))
    for f in findings:
        if f.rule not in RULE_CATALOG:
            raise AssertionError(
                f"checker emitted undeclared rule id {f.rule!r} — add it "
                "to RULE_CATALOG (and docs/static-analysis.md)")
    return findings


def render(findings: List[Finding], as_json: bool) -> str:
    if as_json:
        return json.dumps({
            "findings": [vars(f) for f in findings],
            "count": len(findings),
            "rules": sorted({f.rule for f in findings}),
        })
    if not findings:
        return "hvdcheck: clean (0 findings)"
    lines = [f.format() for f in findings]
    lines.append(f"hvdcheck: {len(findings)} finding"
                 f"{'' if len(findings) == 1 else 's'}")
    return "\n".join(lines)
