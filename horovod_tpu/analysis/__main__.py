"""CLI: ``python -m horovod_tpu.analysis [--json] [--root DIR]``.

Exit codes (pinned by tests/test_analysis.py): 0 clean, 2 findings,
1 the analysis itself failed (a parser outgrown by the code it reads —
that is a red run, not a pass)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdcheck — cross-language ABI/invariant static "
                    "analysis for the engine core")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--root", default=None,
                   help="repository root (default: resolved from the "
                        "installed package location)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    from horovod_tpu.analysis import report

    if args.list_rules:
        for rule, desc in sorted(report.RULE_CATALOG.items()):
            print(f"{rule}: {desc}")
        return 0
    findings = report.run_all(args.root)
    print(report.render(findings, as_json=args.json))
    return 2 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
