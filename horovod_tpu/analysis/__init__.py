"""hvdcheck — cross-language static analysis for the engine core.

A stdlib-only checker suite that reads BOTH sides of every hand-twinned
surface independently and fails the tree on drift:

- :mod:`.abi` — the C ABI of ``hvdcore.cc`` vs the ctypes mirrors
  (struct fields, exported signatures, callback typedefs);
- :mod:`.parity` — cross-engine observable parity (telemetry counter
  names, timeline span vocabulary and span-args keys, the negotiation
  decision grammar, the dtype/wire/op code tables);
- :mod:`.invariants` — an ``ast`` rule pack for the CLAUDE.md engine
  contracts (TF bridge grouping, engine lifecycle, donate-then-mutate,
  eager-drain host-first broadcast, lock ordering, import-free
  entrypoints).

CLI: ``python -m horovod_tpu.analysis [--json] [--root DIR]`` — exit 0
on a clean tree, 2 on findings. The same checks run in tier-1 CI via
``tests/test_analysis.py``, so a drift fails the commit it lands in.
Rule catalog + how to add a rule: ``docs/static-analysis.md``.
"""

from horovod_tpu.analysis.report import (  # noqa: F401
    Finding,
    RULE_CATALOG,
    render,
    repo_root,
    run_all,
)
