"""Regex-grade C subset parser for ``hvdcore.cc`` (stdlib-only).

Reads the native engine's translation unit WITHOUT compiling it and
extracts exactly the surfaces the cross-language checkers diff against
the Python side:

- ``struct hvd_*`` field lists (name, C type, array length) in
  declaration order — the C ABI the ctypes mirrors in
  ``core/native/__init__.py`` must match field-for-field;
- ``extern "C"`` function definitions (return type + parameter types)
  — the ``argtypes``/``restype`` contract of ``load_library``;
- function-pointer ``typedef``s (the executor / negotiator callback
  shapes behind ``CFUNCTYPE``);
- ``enum`` bodies, lookup-table string arrays (``DtypeName``), and
  ``switch`` case→string maps (``WireName``) — small value tables the
  Python twin re-declares and must not skew;
- string literals (timeline span names, span-args keys, decision-
  grammar kind chars) for the cross-engine parity checks.

This is deliberately NOT a C parser: it understands only the idioms the
engine core actually uses (single file, no preprocessor conditionals
around the ABI, no nested struct definitions). If hvdcore.cc ever grows
past that subset the parsers below fail LOUDLY (raise), which turns the
analysis run red rather than silently checking nothing.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple


class CParseError(RuntimeError):
    """The C source stepped outside the subset these parsers understand."""


def strip_comments(src: str) -> str:
    """Remove ``//`` and ``/* */`` comments, preserving string/char
    literals (tensor-name escapes like ``\\"`` included) and line
    numbers (newlines inside block comments are kept)."""
    out: List[str] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == '"' or c == "'":
            quote = c
            out.append(c)
            i += 1
            while i < n:
                out.append(src[i])
                if src[i] == "\\":
                    if i + 1 < n:
                        out.append(src[i + 1])
                    i += 2
                    continue
                if src[i] == quote:
                    i += 1
                    break
                i += 1
            continue
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            if j < 0:
                raise CParseError("unterminated block comment")
            out.append("\n" * src.count("\n", i, j + 2))
            i = j + 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _line_of(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


# A struct field: ``<type tokens> <name>;`` or ``<type tokens> <name>[N];``
# The extra-word repetition is LAZY with a required trailing name: regex
# backtracking then yields the shortest valid type, so multi-word types
# (``long long count``) split correctly instead of donating their last
# letter to the name.
_FIELD_RE = re.compile(
    r"^\s*((?:const\s+)?[A-Za-z_][A-Za-z0-9_]*(?:\s+[A-Za-z_][A-Za-z0-9_]*)*?"
    r"\s*\**)\s+([A-Za-z_][A-Za-z0-9_]*)\s*(?:\[\s*(\d+)\s*\])?\s*$")


def _norm_type(t: str) -> str:
    """Canonical spelling of a C type: single spaces, ``*`` attached
    (``const char *`` -> ``const char*``)."""
    t = re.sub(r"\s+", " ", t).strip()
    t = re.sub(r"\s*\*\s*", "*", t)
    return t


class Field:
    __slots__ = ("ctype", "name", "array", "line")

    def __init__(self, ctype: str, name: str, array: Optional[int],
                 line: int):
        self.ctype = ctype
        self.name = name
        self.array = array
        self.line = line

    def __repr__(self):  # pragma: no cover - debugging aid
        arr = f"[{self.array}]" if self.array else ""
        return f"<{self.ctype} {self.name}{arr}>"


def parse_structs(src: str,
                  name_re: str = r"hvd_\w+") -> Dict[str, List[Field]]:
    """Every ``struct <name> { ... };`` whose name matches ``name_re``
    (flat plain-old-data bodies only — the default filter selects the C
    ABI structs; internal C++ classes/structs are not part of the ABI
    and use idioms outside this subset)."""
    clean = strip_comments(src)
    structs: Dict[str, List[Field]] = {}
    want = re.compile(name_re)
    for m in re.finditer(r"\bstruct\s+([A-Za-z_][A-Za-z0-9_]*)\s*\{", clean):
        name = m.group(1)
        if not want.fullmatch(name):
            continue
        body_start = m.end()
        depth = 1
        i = body_start
        while i < len(clean) and depth:
            if clean[i] == "{":
                depth += 1
            elif clean[i] == "}":
                depth -= 1
            i += 1
        if depth:
            raise CParseError(f"unterminated struct {name}")
        body = clean[body_start:i - 1]
        if "{" in body:
            raise CParseError(
                f"struct {name} has a nested brace body — outside the "
                "parsed C subset")
        fields: List[Field] = []
        offset = body_start
        for decl in body.split(";"):
            stripped = decl.strip()
            offset_here = offset
            offset += len(decl) + 1
            if not stripped:
                continue
            fm = _FIELD_RE.match(stripped)
            if not fm:
                # Methods/ctors would land here; hvd_* ABI structs are
                # plain-old-data by contract.
                raise CParseError(
                    f"unparseable field in struct {name}: {stripped!r}")
            fields.append(Field(
                _norm_type(fm.group(1)), fm.group(2),
                int(fm.group(3)) if fm.group(3) else None,
                _line_of(clean, offset_here)))
        structs[name] = fields
    return structs


class CFunc:
    __slots__ = ("ret", "name", "args", "line")

    def __init__(self, ret: str, name: str, args: List[str], line: int):
        self.ret = ret
        self.name = name
        self.args = args
        self.line = line


# Lazy extra-words + required name for the same backtracking reason as
# _FIELD_RE: ``long long hvd_engine_enqueue(`` must split type/name at
# the last identifier.
_FUNC_RE = re.compile(
    r"^[ \t]*((?:const\s+)?[A-Za-z_][A-Za-z0-9_]*(?:\s+[A-Za-z_]"
    r"[A-Za-z0-9_]*)*?\s*\**)\s*\n?\s*([A-Za-z_][A-Za-z0-9_]*)\s*\(",
    re.M)


# ``<type tokens> <name>``: the name is REQUIRED (every parameter in the
# engine core is named), which lets regex backtracking split multi-word
# types (``long long fusion_bytes``) correctly.
_PARAM_RE = re.compile(
    r"^((?:const\s+)?[A-Za-z_][A-Za-z0-9_]*(?:\s+[A-Za-z_][A-Za-z0-9_]*)*?"
    r"\s*\**)\s+([A-Za-z_][A-Za-z0-9_]*)$")


def _split_args(argtext: str) -> List[str]:
    argtext = argtext.strip()
    if not argtext or argtext == "void":
        return []
    args = []
    for piece in argtext.split(","):
        piece = re.sub(r"\s+", " ", piece).strip()
        if not piece:
            raise CParseError(f"empty parameter in {argtext!r}")
        m = _PARAM_RE.match(piece)
        if not m:
            raise CParseError(f"unparseable parameter {piece!r}")
        args.append(_norm_type(m.group(1)))
    return args


def parse_extern_c_functions(src: str) -> Dict[str, CFunc]:
    """Function definitions inside ``extern \"C\" { ... }`` blocks."""
    clean = strip_comments(src)
    funcs: Dict[str, CFunc] = {}
    for m in re.finditer(r'extern\s+"C"\s*\{', clean):
        depth = 1
        i = m.end()
        start = i
        while i < len(clean) and depth:
            if clean[i] == "{":
                depth += 1
            elif clean[i] == "}":
                depth -= 1
            i += 1
        block = clean[start:i - 1]
        # Function-pointer typedefs share the block but are not exported
        # symbols (parse_fn_typedefs reads them); blank them out, keeping
        # offsets/line numbers intact for the remaining matches.
        block = re.sub(r"typedef[^;]*;",
                       lambda m: re.sub(r"[^\n]", " ", m.group(0)), block)
        base = start
        for fm in _FUNC_RE.finditer(block):
            name = fm.group(2)
            ret = fm.group(1).strip()
            if name in ("if", "while", "for", "switch", "return",
                        "sizeof") or ret.startswith(("return", "typedef")):
                continue
            if not name.startswith("hvd_"):
                # The C ABI namespace is hvd_*; anything else at a line
                # start is a statement, not an exported definition.
                continue
            # Parameter list runs to the matching ')'.
            j = fm.end()
            depth_p = 1
            while j < len(block) and depth_p:
                if block[j] == "(":
                    depth_p += 1
                elif block[j] == ")":
                    depth_p -= 1
                j += 1
            argtext = block[fm.end():j - 1].replace("\n", " ")
            funcs[name] = CFunc(_norm_type(fm.group(1)), name,
                                _split_args(argtext),
                                _line_of(clean, base + fm.start()))
    return funcs


def parse_fn_typedefs(src: str) -> Dict[str, Tuple[str, List[str]]]:
    """``typedef <ret> (*<name>)(<args>);`` -> name: (ret, [arg types])."""
    clean = strip_comments(src)
    out: Dict[str, Tuple[str, List[str]]] = {}
    for m in re.finditer(
            r"typedef\s+([A-Za-z_][A-Za-z0-9_ ]*\**)\s*\(\s*\*\s*"
            r"([A-Za-z_][A-Za-z0-9_]*)\s*\)\s*\(([^)]*)\)\s*;",
            clean, re.S):
        out[m.group(2)] = (_norm_type(m.group(1)),
                           _split_args(m.group(3).replace("\n", " ")))
    return out


def parse_enum(src: str, name: str) -> Dict[str, int]:
    """A sequential/explicit-value C enum body."""
    clean = strip_comments(src)
    m = re.search(r"\benum\s+" + re.escape(name) + r"\s*\{([^}]*)\}", clean)
    if not m:
        raise CParseError(f"enum {name} not found")
    values: Dict[str, int] = {}
    nxt = 0
    for entry in m.group(1).split(","):
        entry = entry.strip()
        if not entry:
            continue
        em = re.match(r"^([A-Za-z_][A-Za-z0-9_]*)\s*(?:=\s*(\d+))?$", entry)
        if not em:
            raise CParseError(f"unparseable enum entry {entry!r}")
        if em.group(2) is not None:
            nxt = int(em.group(2))
        values[em.group(1)] = nxt
        nxt += 1
    return values


def parse_string_array(src: str, marker: str) -> List[str]:
    """The string-literal initializer list of the array declared nearest
    AFTER ``marker`` (e.g. the ``kNames`` table inside ``DtypeName``)."""
    clean = strip_comments(src)
    at = clean.find(marker)
    if at < 0:
        raise CParseError(f"marker {marker!r} not found")
    m = re.search(r"\{((?:\s*\"[^\"]*\"\s*,?)+)\}", clean[at:])
    if not m:
        raise CParseError(f"no string array after {marker!r}")
    return re.findall(r'"([^"]*)"', m.group(1))


def parse_double_array(src: str, marker: str) -> List[float]:
    """The numeric initializer list of the array declared nearest AFTER
    ``marker`` (e.g. the ``kLatencyBucketsS`` bucket edges the
    parity-latency rule diffs against telemetry.LATENCY_BUCKETS_S)."""
    clean = strip_comments(src)
    at = clean.find(marker)
    if at < 0:
        raise CParseError(f"marker {marker!r} not found")
    m = re.search(r"\{([^{}]*)\}", clean[at:])
    if not m:
        raise CParseError(f"no initializer list after {marker!r}")
    vals: List[float] = []
    for piece in m.group(1).split(","):
        piece = piece.strip()
        if not piece:
            continue
        try:
            vals.append(float(piece))
        except ValueError:
            raise CParseError(
                f"non-numeric entry {piece!r} in array after {marker!r}")
    return vals


def parse_case_string_map(src: str, fn_name: str) -> Dict[int, str]:
    """``case N: return "name";`` pairs inside one function body."""
    clean = strip_comments(src)
    at = clean.find(fn_name)
    if at < 0:
        raise CParseError(f"function {fn_name!r} not found")
    brace = clean.find("{", at)
    depth = 0
    i = brace
    while i < len(clean):
        if clean[i] == "{":
            depth += 1
        elif clean[i] == "}":
            depth -= 1
            if depth == 0:
                break
        i += 1
    body = clean[brace:i]
    return {int(n): s for n, s in
            re.findall(r'case\s+(\d+)\s*:\s*return\s+"([^"]*)"', body)}


def function_body(src: str, marker: str) -> str:
    """The brace-matched body of the function declared nearest AFTER
    ``marker`` (comments stripped) — e.g. ``"long long Inspect"`` for
    the inspect-record writer the parity-doctor rule reads."""
    clean = strip_comments(src)
    at = clean.find(marker)
    if at < 0:
        raise CParseError(f"marker {marker!r} not found")
    brace = clean.find("{", at)
    if brace < 0:
        raise CParseError(f"no function body after {marker!r}")
    depth = 0
    i = brace
    while i < len(clean):
        if clean[i] == "{":
            depth += 1
        elif clean[i] == "}":
            depth -= 1
            if depth == 0:
                break
        i += 1
    return clean[brace:i]


def string_literals(src: str) -> List[Tuple[str, int]]:
    """Every double-quoted string literal (decoded for the escapes the
    engine actually uses) with its line number. Comments excluded, and
    CHAR literals are skipped by a stateful scan — a regex would pair a
    quote inside ``'"'`` with the next real string's opening quote and
    silently swallow genuine literals (JsonEscape's switch is exactly
    that shape)."""
    clean = strip_comments(src)
    out: List[Tuple[str, int]] = []
    i, n = 0, len(clean)
    while i < n:
        c = clean[i]
        if c == "'":  # char literal: skip it, escapes included
            i += 1
            while i < n:
                if clean[i] == "\\":
                    i += 2
                    continue
                if clean[i] == "'":
                    i += 1
                    break
                i += 1
            continue
        if c == '"':
            start = i
            i += 1
            raw: List[str] = []
            while i < n:
                if clean[i] == "\\" and i + 1 < n:
                    raw.append(clean[i:i + 2])
                    i += 2
                    continue
                if clean[i] == '"':
                    i += 1
                    break
                raw.append(clean[i])
                i += 1
            body = "".join(raw)
            decoded = (body.replace('\\"', '"').replace("\\n", "\n")
                       .replace("\\\\", "\\"))
            out.append((decoded, _line_of(clean, start)))
            continue
        i += 1
    return out


def decision_kinds_handled(src: str) -> List[str]:
    """The decision-grammar line kinds the C++ parser compares against
    (``kind == 'g'`` / ``kind != 'e'`` in ``ParseAndExecute``)."""
    clean = strip_comments(src)
    return sorted(set(re.findall(r"kind\s*[!=]=\s*'([a-z])'", clean)))
