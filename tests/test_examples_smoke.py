"""Every example script runs end-to-end on the virtual CPU mesh.

The reference ships runnable examples as its de-facto integration tier
(SURVEY §2.4); nothing in its CI runs them, and they bit-rot. Here each
script is executed as a real subprocess (the user's invocation,
docs/running.md) with a seconds-scale configuration — including the
bert/hybrid benchmarks at toy sizes. The imagenet/tensorflow variants
without a seconds-scale knob are exercised through their training cores
elsewhere (the Trainer/engine paths of the mnist variants and the
frontend suites).
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CASES = {
    "jax_mnist.py": ["--epochs", "1", "--batch-size", "16", "--synthetic"],
    "haiku_mnist.py": ["--epochs", "1", "--batch-size", "16"],
    "pytorch_mnist.py": ["--epochs", "1", "--batch-size", "64"],
    "keras_mnist.py": ["--epochs", "1", "--batch-size", "16"],
    "jax_word2vec.py": ["--steps", "30", "--batch-size", "64"],
    "jax_synthetic_benchmark.py": [
        "--model", "mnist_mlp", "--batch-size", "8",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
        "--num-iters", "1", "--image-size", "8"],
    # Dropout model through the full bench step: pins the rngs plumbing
    # (vgg/inception need a dropout stream; mnist/resnet ignore it).
    "jax_synthetic_benchmark.py --model vgg16": [
        "--model", "vgg16", "--batch-size", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1", "--image-size", "32", "--steps-per-call",
        "1"],
    "bert_pretraining_benchmark.py": [
        "--layers", "1", "--hidden", "64", "--heads", "2", "--vocab",
        "128", "--seq-len", "32", "--batch-size", "2", "--steps", "2",
        "--warmup", "1", "--steps-per-call", "1"],
    "hybrid_parallel_transformer.py": [],
    "allreduce_benchmark.py": ["--sizes-mb", "0.25", "--iters", "2",
                               "--warmup", "1"],
    # Exercises the multi-chip mechanics (subset re-init, per-n meshes)
    # the docstring promises are known-good for real hardware.
    "scaling_benchmark.py": ["--sizes-mb", "0.25", "--model", "mnist_mlp",
                             "--image-size", "28", "--batch-size", "8",
                             "--steps", "2", "--chips", "1", "2", "8"],
    # The 5 examples the r3 verdict flagged as never CI-executed
    # (missing #3) plus the estimator-role script (missing #1).
    "tensorflow_mnist.py": ["--steps", "5", "--batch-size", "8"],
    "tensorflow_mnist_estimator.py": ["--steps", "24", "--batch-size", "8"],
    "keras_mnist_advanced.py": ["--epochs", "1", "--warmup-epochs", "1",
                                "--batch-size", "8"],
    "keras_imagenet_resnet50.py": ["--epochs", "1", "--warmup-epochs", "1",
                                   "--steps", "2", "--batch-size", "2",
                                   "--image-size", "32"],
    "pytorch_imagenet_resnet50.py": ["--epochs", "1", "--warmup-epochs", "1",
                                     "--steps", "2", "--batch-size", "2"],
    "pytorch_synthetic_benchmark.py": ["--batch-size", "2",
                                       "--num-warmup-batches", "1",
                                       "--num-batches-per-iter", "1",
                                       "--num-iters", "1"],
    # Serving plane (ISSUE 20): sharded inference with a high-class
    # deadline'd metric reduction, and the mixed-priority load harness
    # with a deliberately tiny low-class budget so admission rejections
    # actually fire in the smoke (exit is nonzero on digest failures).
    "batched_inference.py": ["--batches", "3", "--background-mb", "0.5"],
    "serving_load_harness.py": ["--requests", "30", "--wave", "8",
                                "--max-inflight-low", "2"],
}


# Per-case timeout overrides (seconds): ResNet-50's XLA:CPU compile alone
# runs 2-3 minutes on a loaded host.
_TIMEOUTS = {"keras_imagenet_resnet50.py": 900,
             "pytorch_imagenet_resnet50.py": 600}

# Opt-in tier (HVD_SLOW_TESTS=1): the two imagenet scripts cost ~7 min
# of XLA:CPU ResNet-50 compile/engine time — measured as the default
# suite's single biggest slice — while their training cores (Trainer
# pipeline, torch engine loop) are exercised every run by the frontend
# suites and the mnist variants. The scripts still smoke end-to-end
# whenever the slow tier is enabled (CI nightly / pre-release).
_SLOW = {"keras_imagenet_resnet50.py", "pytorch_imagenet_resnet50.py"}


@pytest.mark.parametrize("case", sorted(_CASES), ids=lambda s: s)
def test_example_runs(case):
    script = case.split()[0]  # keys may carry a variant suffix for ids
    slow_on = (os.environ.get("HVD_SLOW_TESTS", "").lower()
               not in ("", "0", "false", "off"))
    if script in _SLOW and not slow_on:
        pytest.skip("multi-minute XLA:CPU ResNet-50 case; set "
                    "HVD_SLOW_TESTS=1 to run (core paths covered by the "
                    "frontend suites)")
    env = dict(os.environ)
    # Force the virtual CPU mesh. JAX_PLATFORMS alone is NOT enough: the
    # TPU-plugin site dir on PYTHONPATH pre-imports jax and preempts the
    # env var (CLAUDE.md gotcha — verified: with it present the examples
    # ride the real tunneled chip). These children are deliberately
    # CPU-only, so the plugin dir is stripped; on-chip example numbers
    # live in docs/benchmarks.md.
    site_free = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + site_free)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    # Persistent XLA compilation cache. Measured saving is modest
    # (~35 s/run: this jax's XLA:CPU cannot serialize the big resnet
    # executables, so only the smaller programs cache), but it is free
    # and helps local dev iteration on the lighter examples.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(_REPO, ".cache", "jax"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "10")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script),
         *_CASES[case]],
        capture_output=True, text=True, timeout=_TIMEOUTS.get(case, 420),
        env=env, cwd=_REPO)
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-2500:]}\n{proc.stderr[-1500:]}")
