"""Elastic worlds (ISSUE 9): survive rank loss, shrink the mesh, regrow
on rejoin.

Tiers in this file:

- unit: heartbeat-lease verdicts on a LocalKV, KV poll backoff, KVTimeout
  attribution, the launcher/elastic exit-code contract, and the
  topology ``shutdown() -> init()`` re-entry that reconfiguration needs;
- launcher: the non-elastic death report + exit-status propagation;
- ``chaos`` marker: the 2-process SIGKILL / shrink / rejoin scenario for
  BOTH engines, driven through ``run.py --elastic`` (the supervisor).

(The file name sorts last in the suite on purpose: the chaos worlds are
the most expensive tier and must not displace earlier coverage under a
wall-clock cap.)
"""

import json
import math
import os
import signal
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "elastic_worker.py")


# ---------------------------------------------------------------------------
# units: lease verdicts, poll backoff, exit-code contract
# ---------------------------------------------------------------------------


def test_restart_exit_code_in_sync():
    """run.py hardcodes the code (importing core.elastic would drag jax
    into the launcher); the two must never drift."""
    from horovod_tpu import run as run_mod
    from horovod_tpu.core import elastic

    assert run_mod.RESTART_EXIT_CODE == elastic.RESTART_EXIT_CODE == 77


def test_kvtimeout_names_key_and_world_epoch():
    from horovod_tpu.core import coordinator as coord

    coord.set_world_epoch(0)
    err = coord.KVTimeout("hvd/neg/g0/r3/p1")
    assert "hvd/neg/g0/r3/p1" in str(err) and "world epoch 0" in str(err)
    try:
        coord.set_world_epoch(4)
        err = coord.KVTimeout("some/key")
        assert "world epoch 4" in str(err)
        # LocalKV's blocking get raises the same attributed timeout.
        kv = coord.LocalKV({})
        with pytest.raises(coord.KVTimeout, match="some/other.*epoch 4"):
            kv.get("some/other", timeout_s=0.05)
    finally:
        coord.set_world_epoch(0)


def test_poll_slices_back_off_with_jitter():
    import random

    from horovod_tpu.core import coordinator as coord

    gen = coord._poll_slices(random.Random(7))
    slices = [next(gen) for _ in range(12)]
    # Grows from the short first slice toward the cap...
    assert slices[0] < 0.2
    assert max(slices) <= coord._POLL_SLICE_MAX_S * 1.25 + 1e-9
    assert slices[-1] > coord._POLL_SLICE_MAX_S * 0.7
    # ...monotone-ish growth then a jittered plateau, never a fixed spin.
    assert slices[3] > slices[0]
    assert len({round(s, 6) for s in slices[-6:]}) > 1  # jitter alive


def test_heartbeat_lease_verdicts(tmp_path, monkeypatch):
    """The missed-heartbeat KV lease: a stalled counter (or a missing
    one past the startup grace) hardens into a death verdict with a
    tombstone, a death note, and an attributed flight dump."""
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.2")
    monkeypatch.setenv("HVD_ELASTIC_GRACE_S", "30")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    store = {}
    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc = 0, 2
    w.live = [0, 1]
    w._kv = coord.LocalKV(store)

    # Peer beating: no verdict, our own beat published.
    store["hvd/elastic/g0/hb/p1"] = "1"
    assert w._beat_once() is True
    assert w.dead == {} and not w.world_changed()
    assert store.get("hvd/elastic/g0/hb/p0") == "1"

    # Counter advances -> lease refreshed on OUR clock.
    store["hvd/elastic/g0/hb/p1"] = "2"
    w._beat_once()
    time.sleep(0.25)  # > lease without an advance
    w._beat_once()
    assert 1 in w.dead and "lease expired" in w.dead[1]
    assert w.world_changed()
    assert w.peer_is_dead(1)
    # Tombstone + death note + flight dump all attribute process 1.
    assert "hvd/elastic/g0/dead/p1" in store
    note = json.load(open(tmp_path / "death" / "p1.json"))
    assert note["process"] == 1 and "lease" in note["reason"]
    dumps = list((tmp_path / "flight").glob("*.json"))
    assert dumps, "no flight dump for the death verdict"
    assert any("process 1" in json.load(open(d))["reason"]
               for d in dumps)

    # A verdicted peer is not re-verdicted (idempotent).
    n = len(w.dead)
    w._beat_once()
    assert len(w.dead) == n


def test_heartbeat_grace_for_silent_peer(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.2")
    monkeypatch.setenv("HVD_ELASTIC_GRACE_S", "10")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc, w.live = 0, 2, [0, 1]
    w._kv = coord.LocalKV({})
    w._beat_once()
    assert w.dead == {}  # silent peer inside the startup grace
    w._started_at -= 11.0  # grace elapsed
    w._beat_once()
    assert 1 in w.dead and "grace" in w.dead[1]


def test_announced_done_peer_is_retired_not_verdicted(tmp_path,
                                                      monkeypatch):
    """A rank that announced completion and then went silent is a
    finished rank, not a casualty: retired from the lease, no verdict,
    no reconfiguration (the last ranks of a finishing job must not
    shrink the world out from under each other)."""
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.1")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    store = {}
    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc, w.live = 0, 2, [0, 1]
    w._kv = coord.LocalKV(store)
    store["hvd/elastic/g0/hb/p1"] = "5"
    w._beat_once()
    store["hvd/elastic/g0/done/p1"] = "123.0"  # peer announces + exits
    time.sleep(0.15)  # heartbeat silent past the lease
    w._beat_once()
    assert w.dead == {} and not w.world_changed()
    assert 1 in w._done_peers
    # Revocation (a later fit calls announce_active): a fresh lease is
    # granted — no instant verdict for the time spent marked done...
    del store["hvd/elastic/g0/done/p1"]
    store["hvd/elastic/g0/hb/p1"] = "6"
    w._beat_once()
    assert w.dead == {} and 1 not in w._done_peers
    # ...but normal leasing has resumed: silence now verdicts again.
    time.sleep(0.15)
    w._beat_once()
    assert 1 in w.dead
    # And our own announce publishes/retracts the key peers look for.
    w.announce_done()
    assert store.get("hvd/elastic/g0/done/p0") is not None
    w.announce_active()
    assert store.get("hvd/elastic/g0/done/p0") is None


def test_restart_request_protocol(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    assert w.restart_requested() is None
    w.request_restart("below min-np")
    assert "below min-np" in w.restart_requested()
    os.unlink(tmp_path / "restart.json")
    # The supervisor's rejoin request is also a restart trigger.
    os.makedirs(tmp_path / "rejoin")
    json.dump({"process": 1}, open(tmp_path / "rejoin" / "p1.json", "w"))
    assert "p1.json" in w.restart_requested()


def test_liveness_probe_fails_negotiation_early():
    """A blocked negotiation read consults the elastic lease and raises
    PeerLost immediately instead of waiting out the negotiation
    timeout."""
    from horovod_tpu.core import coordinator as coord

    store = {}
    c = coord.Coordinator(coord.LocalKV(store), 2, 0, 0.005, 0,
                          timeout_s=30.0)
    coord.set_liveness_probe(
        lambda p: "lease expired" if p == 1 else None)
    try:
        t0 = time.monotonic()
        with pytest.raises(coord.PeerLost, match="process 1 declared"):
            c.negotiate([])
        assert time.monotonic() - t0 < 5.0  # not the 30 s timeout
        assert coord.is_shutdownish(coord.PeerLost(1, "x")) is False
    finally:
        coord.set_liveness_probe(None)


# ---------------------------------------------------------------------------
# topology re-entry (required by in-process reconfiguration)
# ---------------------------------------------------------------------------


def test_topology_shutdown_init_reentry_shrink_then_regrow(hvd):
    """shutdown() -> init() must rebuild the mesh in-process without
    leaking the old Mesh/two-tier state: shrink the 8-device virtual
    mesh to 4, run eager + compiled collectives, then regrow to 8."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu.jax as hj
    from horovod_tpu.common import topology as topo
    from horovod_tpu.ops import collectives as C

    assert hvd.size() == 8
    all_devices = jax.devices()
    try:
        topo.shutdown()
        assert not topo.is_initialized()
        assert topo._state.mesh is None and topo._state.two_tier is None
        assert topo._state.devices == []  # nothing pins the old Mesh
        assert C._ranked_program.cache_info().currsize == 0

        topo.init(devices=all_devices[:4])
        assert hvd.size() == 4
        out = np.asarray(hvd.allreduce(jnp.ones((3,)), average=False))
        np.testing.assert_allclose(out, np.full((3,), 4.0))

        @hj.jit(in_specs=(P(hj.HVD_AXIS),), out_specs=P())
        def f(x):
            return C.allreduce(x[0], average=False)

        mesh = hvd.mesh()
        shards = [jax.device_put(jnp.full((1, 2), 2.0), d)
                  for d in all_devices[:4]]
        x = jax.make_array_from_single_device_arrays(
            (4, 2), NamedSharding(mesh, P(hj.HVD_AXIS)), shards)
        np.testing.assert_allclose(np.asarray(f(x)), np.full((2,), 8.0))

        # Regrow back to the full world in the same process.
        topo.shutdown()
        topo.init()
        assert hvd.size() == 8
        out = np.asarray(hvd.allreduce(jnp.ones((2,)), average=False))
        np.testing.assert_allclose(out, np.full((2,), 8.0))
    finally:
        # Leave the session world exactly as the other tests expect.
        if not topo.is_initialized() or topo.size() != 8:
            topo.shutdown()
            topo.init()


# ---------------------------------------------------------------------------
# launcher: non-elastic death attribution + exit-status propagation
# ---------------------------------------------------------------------------


def _clean_env(extra=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def test_launcher_reports_signal_death_and_propagates_status():
    """Non-elastic satellite: a child killed by a signal is reported —
    rank, pid, signal name — BEFORE the rest is torn down, and the
    launcher exits 128+signum (the raw negative returncode used to win,
    which the shell mangled)."""
    script = ("import os, signal, time\n"
              "if os.environ['HVD_PROCESS_ID'] == '1':\n"
              "    time.sleep(0.5)\n"
              "    os.kill(os.getpid(), signal.SIGKILL)\n"
              "time.sleep(60)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=_clean_env(),
        cwd=_REPO)
    assert proc.returncode == 128 + signal.SIGKILL, (
        proc.returncode, proc.stderr[-1000:])
    assert "rank 1 (pid " in proc.stderr and "SIGKILL" in proc.stderr, \
        proc.stderr[-1000:]
    assert "terminating the remaining processes" in proc.stderr


# ---------------------------------------------------------------------------
# chaos tier: SIGKILL -> shrink -> continuous loss -> rejoin -> regrow
# ---------------------------------------------------------------------------

ENGINES = ["native", "python"]


def _parse_losses(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ENGINES)
def test_chaos_sigkill_shrink_and_rejoin(engine, tmp_path):
    """ISSUE 9 acceptance, both engines: SIGKILL one of 2 ranks
    mid-training. The survivor must emit a RECONFIGURE epoch bump,
    resume from the newest checkpoint and keep a continuous loss curve
    (no NaN, no restart-from-scratch); the flight dump attributes the
    death; the restarted rank rejoins after the blacklist and
    ``hvd.check_consistency`` passes on the regrown world."""
    edir = str(tmp_path / f"elastic_{engine}")
    os.makedirs(edir)
    env = _clean_env({
        "HVD_ENGINE": engine,
        "HVD_NUMERICS": "warn",
        # One CPU core runs both ranks: a sub-second lease would flake
        # on GIL/compile contention. 5 s detection still exercises the
        # mid-training verdict; the blacklist leaves the survivor time
        # to demonstrably train on the shrunk world before readmission.
        "HVD_ELASTIC_LEASE_S": "5",
        "HVD_ELASTIC_GRACE_S": "120",
        "HVD_ELASTIC_BLACKLIST_S": "15",
        "HVD_NEGOTIATION_TIMEOUT": "60",
        "HVD_FLIGHT_DIR": os.path.join(edir, "flight"),
        "HVD_FLIGHT_MIN_INTERVAL": "0",
        "HVD_TEST_EPOCHS": "30",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--elastic", "--min-np", "1", "--max-restarts", "2",
         "--elastic-dir", edir, "--", sys.executable, _WORKER],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO)
    out, err = proc.stdout, proc.stderr
    assert proc.returncode == 0, (proc.returncode, out[-4000:],
                                  err[-3000:])

    # The chaos actually happened, and the supervisor attributed it.
    assert "CHAOS rank=1 dying" in out, out[-3000:]
    assert "rank 1 (pid " in err and "SIGKILL" in err, err[-2000:]
    assert "elastic world continues degraded" in err, err[-2000:]

    # Shrink: epoch bump + the survivor kept TRAINING on the 4-rank
    # world (at least one epoch completed at size=4 in generation 0).
    assert "RECONFIGURE: world epoch 0 -> 1" in out, out[-4000:]
    gen0_shrunk = [ln for ln in out.splitlines()
                   if ln.startswith("[0] EPOCH gen=0") and "size=4" in ln]
    assert gen0_shrunk, out[-4000:]

    # Flight dump attributes the dead process.
    import glob

    dumps = glob.glob(os.path.join(edir, "flight", "*.json"))
    reasons = []
    for d in dumps:
        try:
            reasons.append(json.load(open(d)).get("reason", ""))
        except (OSError, ValueError):
            pass
    assert any("process 1" in r for r in reasons), reasons

    # Rejoin: blacklist expired -> request filed -> coordinated restart
    # -> generation 1 resumes from the newest checkpoint on the FULL
    # regrown mesh, and the consistency digests agree on every rank.
    assert "rejoin request filed" in err, err[-2000:]
    assert "relaunching the world: generation 1" in err, err[-2000:]
    assert "RESUMED gen=1" in out, out[-3000:]
    assert out.count("CONSISTENCY OK gen=1") == 2, out[-3000:]
    done = [ln for ln in out.splitlines() if "ELASTIC DONE gen=1" in ln]
    assert len(done) == 2 and all("size=8" in ln for ln in done), done

    # Loss continuity on the survivor's curve: no NaN anywhere, no
    # restart-from-scratch jump at either boundary (shrink, regrow),
    # and net progress end to end.
    recs = _parse_losses(os.path.join(edir, "losses.rank0.jsonl"))
    assert len(recs) >= 5, recs
    losses = [r["loss"] for r in recs]
    assert all(math.isfinite(v) for v in losses), losses
    for prev, cur in zip(recs, recs[1:]):
        if cur["epoch"] <= prev["epoch"]:
            continue  # an epoch re-run after recovery may repeat a value
        assert cur["loss"] <= prev["loss"] * 1.35 + 0.05, (prev, cur)
    assert losses[-1] < losses[0], losses
    # Both boundaries are present in the curve: full -> shrunk -> full.
    sizes = [r["size"] for r in recs]
    assert 8 in sizes and 4 in sizes and sizes[-1] == 8, sizes
    # The world epoch advanced across the shrink.
    assert max(r["world_epoch"] for r in recs) >= 1, recs
