"""Elastic worlds (ISSUE 9 + 11): survive rank loss, shrink the mesh,
regrow on rejoin — now including KV-plane failover and multi-survivor
shrink.

Tiers in this file:

- unit: heartbeat-lease verdicts on a LocalKV, KV poll backoff, KVTimeout
  attribution, the launcher/elastic exit-code contract, the
  multi-survivor rendezvous fallbacks, supervisor-dir hygiene, and the
  topology ``shutdown() -> init()`` re-entry that reconfiguration needs;
- launcher: the non-elastic death report + exit-status propagation;
- ``chaos`` marker: the 2-process SIGKILL / shrink / rejoin scenario,
  the 3-process rank-0 (coordination host) SIGKILL with KV failover,
  and the frozen-heartbeat (process alive, beats stopped — injected via
  ``--faults``) scenario, each for BOTH engines, driven through
  ``run.py --elastic`` (the supervisor).

(The file name sorts last in the suite on purpose: the chaos worlds are
the most expensive tier and must not displace earlier coverage under a
wall-clock cap.)
"""

import json
import math
import os
import signal
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "elastic_worker.py")


# ---------------------------------------------------------------------------
# units: lease verdicts, poll backoff, exit-code contract
# ---------------------------------------------------------------------------


def test_restart_exit_code_in_sync():
    """run.py hardcodes the code (importing core.elastic would drag jax
    into the launcher); the two must never drift."""
    from horovod_tpu import run as run_mod
    from horovod_tpu.core import elastic

    assert run_mod.RESTART_EXIT_CODE == elastic.RESTART_EXIT_CODE == 77


def test_kvtimeout_names_key_and_world_epoch():
    from horovod_tpu.core import coordinator as coord

    coord.set_world_epoch(0)
    err = coord.KVTimeout("hvd/neg/g0/r3/p1")
    assert "hvd/neg/g0/r3/p1" in str(err) and "world epoch 0" in str(err)
    try:
        coord.set_world_epoch(4)
        err = coord.KVTimeout("some/key")
        assert "world epoch 4" in str(err)
        # LocalKV's blocking get raises the same attributed timeout.
        kv = coord.LocalKV({})
        with pytest.raises(coord.KVTimeout, match="some/other.*epoch 4"):
            kv.get("some/other", timeout_s=0.05)
    finally:
        coord.set_world_epoch(0)


def test_poll_slices_back_off_with_jitter():
    import random

    from horovod_tpu.core import coordinator as coord

    gen = coord._poll_slices(random.Random(7))
    slices = [next(gen) for _ in range(12)]
    # Grows from the short first slice toward the cap...
    assert slices[0] < 0.2
    assert max(slices) <= coord._POLL_SLICE_MAX_S * 1.25 + 1e-9
    assert slices[-1] > coord._POLL_SLICE_MAX_S * 0.7
    # ...monotone-ish growth then a jittered plateau, never a fixed spin.
    assert slices[3] > slices[0]
    assert len({round(s, 6) for s in slices[-6:]}) > 1  # jitter alive


def test_heartbeat_lease_verdicts(tmp_path, monkeypatch):
    """The missed-heartbeat KV lease: a stalled counter (or a missing
    one past the startup grace) hardens into a death verdict with a
    tombstone, a death note, and an attributed flight dump."""
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.2")
    monkeypatch.setenv("HVD_ELASTIC_GRACE_S", "30")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    store = {}
    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc = 0, 2
    w.live = [0, 1]
    w._kv = coord.LocalKV(store)

    # Peer beating: no verdict, our own beat published.
    store["hvd/elastic/g0/hb/p1"] = "1"
    assert w._beat_once() is True
    assert w.dead == {} and not w.world_changed()
    assert store.get("hvd/elastic/g0/hb/p0") == "1"

    # Counter advances -> lease refreshed on OUR clock.
    store["hvd/elastic/g0/hb/p1"] = "2"
    w._beat_once()
    time.sleep(0.25)  # > lease without an advance
    w._beat_once()
    assert 1 in w.dead and "lease expired" in w.dead[1]
    assert w.world_changed()
    assert w.peer_is_dead(1)
    # Tombstone + death note + flight dump all attribute process 1.
    assert "hvd/elastic/g0/dead/p1" in store
    note = json.load(open(tmp_path / "death" / "p1.json"))
    assert note["process"] == 1 and "lease" in note["reason"]
    dumps = list((tmp_path / "flight").glob("*.json"))
    assert dumps, "no flight dump for the death verdict"
    assert any("process 1" in json.load(open(d))["reason"]
               for d in dumps)

    # A verdicted peer is not re-verdicted (idempotent).
    n = len(w.dead)
    w._beat_once()
    assert len(w.dead) == n


def test_heartbeat_grace_for_silent_peer(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.2")
    monkeypatch.setenv("HVD_ELASTIC_GRACE_S", "10")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc, w.live = 0, 2, [0, 1]
    w._kv = coord.LocalKV({})
    w._beat_once()
    assert w.dead == {}  # silent peer inside the startup grace
    w._started_at -= 11.0  # grace elapsed
    w._beat_once()
    assert 1 in w.dead and "grace" in w.dead[1]


def test_announced_done_peer_is_retired_not_verdicted(tmp_path,
                                                      monkeypatch):
    """A rank that announced completion and then went silent is a
    finished rank, not a casualty: retired from the lease, no verdict,
    no reconfiguration (the last ranks of a finishing job must not
    shrink the world out from under each other)."""
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", "0.1")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    from horovod_tpu.core import coordinator as coord
    from horovod_tpu.core import elastic

    store = {}
    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc, w.live = 0, 2, [0, 1]
    w._kv = coord.LocalKV(store)
    store["hvd/elastic/g0/hb/p1"] = "5"
    w._beat_once()
    store["hvd/elastic/g0/done/p1"] = "123.0"  # peer announces + exits
    time.sleep(0.15)  # heartbeat silent past the lease
    w._beat_once()
    assert w.dead == {} and not w.world_changed()
    assert 1 in w._done_peers
    # Revocation (a later fit calls announce_active): a fresh lease is
    # granted — no instant verdict for the time spent marked done...
    del store["hvd/elastic/g0/done/p1"]
    store["hvd/elastic/g0/hb/p1"] = "6"
    w._beat_once()
    assert w.dead == {} and 1 not in w._done_peers
    # ...but normal leasing has resumed: silence now verdicts again.
    time.sleep(0.15)
    w._beat_once()
    assert 1 in w.dead
    # And our own announce publishes/retracts the key peers look for.
    w.announce_done()
    assert store.get("hvd/elastic/g0/done/p0") is not None
    w.announce_active()
    assert store.get("hvd/elastic/g0/done/p0") is None


def test_restart_request_protocol(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    assert w.restart_requested() is None
    w.request_restart("below min-np")
    assert "below min-np" in w.restart_requested()
    os.unlink(tmp_path / "restart.json")
    # The supervisor's rejoin request is also a restart trigger.
    os.makedirs(tmp_path / "rejoin")
    json.dump({"process": 1}, open(tmp_path / "rejoin" / "p1.json", "w"))
    assert "p1.json" in w.restart_requested()


def test_liveness_probe_fails_negotiation_early():
    """A blocked negotiation read consults the elastic lease and raises
    PeerLost immediately instead of waiting out the negotiation
    timeout."""
    from horovod_tpu.core import coordinator as coord

    store = {}
    c = coord.Coordinator(coord.LocalKV(store), 2, 0, 0.005, 0,
                          timeout_s=30.0)
    coord.set_liveness_probe(
        lambda p: "lease expired" if p == 1 else None)
    try:
        t0 = time.monotonic()
        with pytest.raises(coord.PeerLost, match="process 1 declared"):
            c.negotiate([])
        assert time.monotonic() - t0 < 5.0  # not the 30 s timeout
        assert coord.is_shutdownish(coord.PeerLost(1, "x")) is False
    finally:
        coord.set_liveness_probe(None)


# ---------------------------------------------------------------------------
# multi-survivor shrink: rendezvous protocol fallbacks (no worlds spawned;
# every path below raises BEFORE any backend teardown)
# ---------------------------------------------------------------------------


def _multi_world(monkeypatch, tmp_path, pid):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc, w.live = pid, 3, [0, 1, 2]
    w.dead = {0: "heartbeat lease expired (test)"}
    w._changed.set()
    return w


def test_multi_survivor_requires_file_plane(monkeypatch, tmp_path):
    """Survivors spanning multiple controllers need the file plane for
    the rebuild rendezvous; without HVD_ELASTIC_DIR the transition
    stays a coordinated restart (the PR 9 behavior)."""
    from horovod_tpu.core import elastic

    w = _multi_world(monkeypatch, tmp_path, pid=1)
    monkeypatch.delenv("HVD_ELASTIC_DIR")
    with pytest.raises(elastic.ElasticRestartRequired,
                       match="no HVD_ELASTIC_DIR"):
        w.reconfigure()
    assert not w._reconfiguring  # flag released on the fallback path


def test_multi_survivor_rendezvous_timeout_falls_back(monkeypatch,
                                                      tmp_path):
    """A non-root survivor that never sees the elected root's address
    falls back to exit-77 territory instead of hanging."""
    from horovod_tpu.core import elastic

    monkeypatch.setenv("HVD_ELASTIC_REBUILD_TIMEOUT_S", "0.3")
    w = _multi_world(monkeypatch, tmp_path, pid=2)  # root would be 1
    t0 = time.monotonic()
    with pytest.raises(elastic.ElasticRestartRequired,
                       match="rendezvous timed out.*root 1"):
        w.reconfigure()
    assert time.monotonic() - t0 < 5.0


def test_multi_survivor_set_divergence_falls_back(monkeypatch,
                                                  tmp_path):
    """Root and a survivor disagreeing on WHO survived is unresolvable
    in place — the coordinated restart re-synchronizes the world."""
    from horovod_tpu.core import elastic

    w = _multi_world(monkeypatch, tmp_path, pid=2)
    fkv = w._get_file_kv()
    fkv.set("hvd/elastic/g0/rebuild/e1/addr", json.dumps(
        {"addr": "127.0.0.1:1", "survivors": [1], "epoch": 1,
         "root": 1}))
    with pytest.raises(elastic.ElasticRestartRequired,
                       match="survivor sets diverged"):
        w.reconfigure()


def test_lowest_live_rank_is_root_and_publishes(monkeypatch, tmp_path):
    """The election is deterministic: the lowest live rank roots the
    rebuild and publishes the rendezvous record to the file plane
    (teardown is stubbed out — the protocol half is what's pinned)."""
    from horovod_tpu.core import elastic

    w = _multi_world(monkeypatch, tmp_path, pid=1)
    # Stub the destructive half: the publish happens first, the
    # timeline mark (the step right before backend teardown) raises a
    # marker so nothing real is torn down.
    def _stop():
        raise RuntimeError("stop-before-teardown")

    monkeypatch.setattr(w, "_mark_reconfigure_on_timeline", _stop)
    with pytest.raises(RuntimeError, match="stop-before-teardown"):
        w.reconfigure()
    assert not w._reconfiguring  # flag released even on a blown rebuild
    rec = json.loads(
        w._get_file_kv().try_get("hvd/elastic/g0/rebuild/e1/addr"))
    assert rec["root"] == 1 and rec["survivors"] == [1, 2]
    assert rec["epoch"] == 1
    assert rec["addr"].rsplit(":", 1)[1].isdigit()


def test_kv_probe_worker_is_bounded_and_recovers():
    """A hung primary plane costs ONE parked probe thread, not one per
    tick: while the timed-out call is still blocked, further probes
    fail fast without stacking threads; if the blocked RPC eventually
    returns (the plane was merely slow), probing resumes on the same
    worker."""
    import threading

    from horovod_tpu.core.elastic import (_AbandonableWorker,
                                          KVPlaneTimeout)

    w = _AbandonableWorker()
    assert w.call(lambda: 42, 1.0) == 42
    release = threading.Event()
    with pytest.raises(KVPlaneTimeout):
        w.call(lambda: (release.wait(), "late")[1], 0.2)
    t0 = time.monotonic()
    with pytest.raises(KVPlaneTimeout, match="still blocked"):
        w.call(lambda: 1, 5.0)  # fails FAST: no new thread, no wait
    assert time.monotonic() - t0 < 0.5
    release.set()
    time.sleep(0.1)  # the stale 'late' result lands
    assert w.call(lambda: 7, 1.0) == 7


def test_epoch_scoped_heartbeat_namespace():
    from horovod_tpu.core import elastic

    w = elastic.ElasticWorld()
    assert w._ns() == "hvd/elastic/g0"
    w.epoch = 2
    assert w._ns() == "hvd/elastic/g0/e2"
    assert w._hb_key(1) == "hvd/elastic/g0/e2/hb/p1"


def test_supervisor_prunes_stale_generations(tmp_path):
    """Satellite: death notes and fallback-KV keys from generation N-2
    and older are pruned at relaunch (rejoin requests are consumed
    wholesale by the supervisor loop itself); newer control files,
    checkpoints and the epoch journal survive."""
    from horovod_tpu.run import _prune_elastic_dir

    edir = str(tmp_path)
    os.makedirs(os.path.join(edir, "death"))
    os.makedirs(os.path.join(edir, "kv"))
    os.makedirs(os.path.join(edir, "ckpt"))
    for gen in (0, 1, 2):
        json.dump({"process": 1, "generation": gen},
                  open(os.path.join(edir, "death",
                                    f"p1.g{gen}.json"), "w"))
        open(os.path.join(edir, "kv",
                          f"hvd~elastic~g{gen}~hb~p0"), "w").write("9")
    open(os.path.join(edir, "ckpt", "checkpoint_3.msgpack"),
         "wb").write(b"x")
    json.dump({"epoch": 3}, open(os.path.join(edir, "epoch.json"), "w"))

    _prune_elastic_dir(edir, generation=2)
    left = {os.path.relpath(os.path.join(r, f), edir)
            for r, _, fs in os.walk(edir) for f in fs}
    assert "death/p1.g0.json" not in left
    assert "kv/hvd~elastic~g0~hb~p0" not in left
    # Generation N-1 kept (forensics), current kept, resume state kept.
    assert "death/p1.g1.json" in left and "death/p1.g2.json" in left
    assert "kv/hvd~elastic~g1~hb~p0" in left
    assert "ckpt/checkpoint_3.msgpack" in left
    assert "epoch.json" in left


# ---------------------------------------------------------------------------
# topology re-entry (required by in-process reconfiguration)
# ---------------------------------------------------------------------------


def test_topology_shutdown_init_reentry_shrink_then_regrow(hvd):
    """shutdown() -> init() must rebuild the mesh in-process without
    leaking the old Mesh/two-tier state: shrink the 8-device virtual
    mesh to 4, run eager + compiled collectives, then regrow to 8."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu.jax as hj
    from horovod_tpu.common import topology as topo
    from horovod_tpu.ops import collectives as C

    assert hvd.size() == 8
    all_devices = jax.devices()
    try:
        topo.shutdown()
        assert not topo.is_initialized()
        assert topo._state.mesh is None and topo._state.two_tier is None
        assert topo._state.devices == []  # nothing pins the old Mesh
        assert C._ranked_program.cache_info().currsize == 0

        topo.init(devices=all_devices[:4])
        assert hvd.size() == 4
        out = np.asarray(hvd.allreduce(jnp.ones((3,)), average=False))
        np.testing.assert_allclose(out, np.full((3,), 4.0))

        @hj.jit(in_specs=(P(hj.HVD_AXIS),), out_specs=P())
        def f(x):
            return C.allreduce(x[0], average=False)

        mesh = hvd.mesh()
        shards = [jax.device_put(jnp.full((1, 2), 2.0), d)
                  for d in all_devices[:4]]
        x = jax.make_array_from_single_device_arrays(
            (4, 2), NamedSharding(mesh, P(hj.HVD_AXIS)), shards)
        np.testing.assert_allclose(np.asarray(f(x)), np.full((2,), 8.0))

        # Regrow back to the full world in the same process.
        topo.shutdown()
        topo.init()
        assert hvd.size() == 8
        out = np.asarray(hvd.allreduce(jnp.ones((2,)), average=False))
        np.testing.assert_allclose(out, np.full((2,), 8.0))
    finally:
        # Leave the session world exactly as the other tests expect.
        if not topo.is_initialized() or topo.size() != 8:
            topo.shutdown()
            topo.init()


# ---------------------------------------------------------------------------
# launcher: non-elastic death attribution + exit-status propagation
# ---------------------------------------------------------------------------


def _clean_env(extra=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def test_launcher_reports_signal_death_and_propagates_status():
    """Non-elastic satellite: a child killed by a signal is reported —
    rank, pid, signal name — BEFORE the rest is torn down, and the
    launcher exits 128+signum (the raw negative returncode used to win,
    which the shell mangled)."""
    script = ("import os, signal, time\n"
              "if os.environ['HVD_PROCESS_ID'] == '1':\n"
              "    time.sleep(0.5)\n"
              "    os.kill(os.getpid(), signal.SIGKILL)\n"
              "time.sleep(60)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=_clean_env(),
        cwd=_REPO)
    assert proc.returncode == 128 + signal.SIGKILL, (
        proc.returncode, proc.stderr[-1000:])
    assert "rank 1 (pid " in proc.stderr and "SIGKILL" in proc.stderr, \
        proc.stderr[-1000:]
    assert "terminating the remaining processes" in proc.stderr


# ---------------------------------------------------------------------------
# chaos tier: SIGKILL -> shrink -> continuous loss -> rejoin -> regrow
# ---------------------------------------------------------------------------

ENGINES = ["native", "python"]


def _parse_losses(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ENGINES)
def test_chaos_sigkill_shrink_and_rejoin(engine, tmp_path):
    """ISSUE 9 acceptance, both engines: SIGKILL one of 2 ranks
    mid-training. The survivor must emit a RECONFIGURE epoch bump,
    resume from the newest checkpoint and keep a continuous loss curve
    (no NaN, no restart-from-scratch); the flight dump attributes the
    death; the restarted rank rejoins after the blacklist and
    ``hvd.check_consistency`` passes on the regrown world.

    De-flake policy: same class as the frozen-heartbeat scenarios
    (documented in CLAUDE.md) — two ranks pace 5 s leases plus a full
    blacklist->rejoin->regrow ladder on this one-core host, and a noisy
    neighbor (most often another chaos world's leftovers in the same
    pytest session) can starve the ladder past the subprocess timeout
    or race the regrown world's final eager consistency gather against
    a peer's exit. ONE automatic same-process retry with a loud note;
    a double failure is a real regression."""
    try:
        _sigkill_shrink_scenario(engine, str(tmp_path / "try1"))
    except (AssertionError, subprocess.TimeoutExpired) as exc:
        print(f"\n[RETRY] chaos sigkill-shrink-rejoin ({engine}) failed "
              f"its first attempt — retrying once in-process; a second "
              f"failure is a real regression. First failure: "
              f"{str(exc)[:500]}", file=sys.stderr, flush=True)
        _reap_stray_world_children()
        _sigkill_shrink_scenario(engine, str(tmp_path / "try2"))


def _sigkill_shrink_scenario(engine, base_dir):
    edir = os.path.join(base_dir, f"elastic_{engine}")
    os.makedirs(edir)
    env = _clean_env({
        "HVD_ENGINE": engine,
        "HVD_NUMERICS": "warn",
        # One CPU core runs both ranks: a sub-second lease would flake
        # on GIL/compile contention. 5 s detection still exercises the
        # mid-training verdict; the blacklist leaves the survivor time
        # to demonstrably train on the shrunk world before readmission.
        "HVD_ELASTIC_LEASE_S": "5",
        "HVD_ELASTIC_GRACE_S": "120",
        "HVD_ELASTIC_BLACKLIST_S": "15",
        "HVD_NEGOTIATION_TIMEOUT": "60",
        "HVD_FLIGHT_DIR": os.path.join(edir, "flight"),
        "HVD_FLIGHT_MIN_INTERVAL": "0",
        "HVD_TEST_EPOCHS": "30",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--elastic", "--min-np", "1", "--max-restarts", "2",
         "--elastic-dir", edir, "--", sys.executable, _WORKER],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO)
    out, err = proc.stdout, proc.stderr
    assert proc.returncode == 0, (proc.returncode, out[-4000:],
                                  err[-3000:])

    # The chaos actually happened, and the supervisor attributed it.
    assert "CHAOS rank=1 dying" in out, out[-3000:]
    assert "rank 1 (pid " in err and "SIGKILL" in err, err[-2000:]
    assert "elastic world continues degraded" in err, err[-2000:]

    # Shrink: epoch bump + the survivor kept TRAINING on the 4-rank
    # world (at least one epoch completed at size=4 in generation 0).
    assert "RECONFIGURE: world epoch 0 -> 1" in out, out[-4000:]
    gen0_shrunk = [ln for ln in out.splitlines()
                   if ln.startswith("[0] EPOCH gen=0") and "size=4" in ln]
    assert gen0_shrunk, out[-4000:]

    # Zero-copy data plane: the SIGKILL mid-cycle (and the shrink's
    # engine abandonment, which poisons the wedged engine's buffer
    # pool) must not poison the survivor's pool — its fresh engine
    # round-trips with a flat steady-state miss counter.
    assert "POOLCHECK gen=0 rank=0 misses_flat=True" in out, out[-4000:]

    # Flight dump attributes the dead process.
    import glob

    dumps = glob.glob(os.path.join(edir, "flight", "*.json"))
    reasons = []
    for d in dumps:
        try:
            reasons.append(json.load(open(d)).get("reason", ""))
        except (OSError, ValueError):
            pass
    assert any("process 1" in r for r in reasons), reasons

    # Rejoin: blacklist expired -> request filed -> coordinated restart
    # -> generation 1 resumes from the newest checkpoint on the FULL
    # regrown mesh, and the consistency digests agree on every rank.
    assert "rejoin request filed" in err, err[-2000:]
    assert "relaunching the world: generation 1" in err, err[-2000:]
    assert "RESUMED gen=1" in out, out[-3000:]
    assert out.count("CONSISTENCY OK gen=1") == 2, out[-3000:]
    done = [ln for ln in out.splitlines() if "ELASTIC DONE gen=1" in ln]
    assert len(done) == 2 and all("size=8" in ln for ln in done), done

    # Loss continuity on the survivor's curve: no NaN anywhere, no
    # restart-from-scratch jump at either boundary (shrink, regrow),
    # and net progress end to end.
    recs = _parse_losses(os.path.join(edir, "losses.rank0.jsonl"))
    assert len(recs) >= 5, recs
    losses = [r["loss"] for r in recs]
    assert all(math.isfinite(v) for v in losses), losses
    for prev, cur in zip(recs, recs[1:]):
        if cur["epoch"] <= prev["epoch"]:
            continue  # an epoch re-run after recovery may repeat a value
        assert cur["loss"] <= prev["loss"] * 1.35 + 0.05, (prev, cur)
    assert losses[-1] < losses[0], losses
    # Both boundaries are present in the curve: full -> shrunk -> full.
    sizes = [r["size"] for r in recs]
    assert 8 in sizes and 4 in sizes and sizes[-1] == 8, sizes
    # The world epoch advanced across the shrink.
    assert max(r["world_epoch"] for r in recs) >= 1, recs


def _assert_continuous(recs):
    losses = [r["loss"] for r in recs]
    assert all(math.isfinite(v) for v in losses), losses
    for prev, cur in zip(recs, recs[1:]):
        if cur["epoch"] <= prev["epoch"]:
            continue  # a replayed epoch may repeat a value
        assert cur["loss"] <= prev["loss"] * 1.35 + 0.05, (prev, cur)
    assert losses[-1] < losses[0], losses


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ENGINES)
def test_chaos_rank0_sigkill_kv_failover(engine, tmp_path):
    """ISSUE 11 acceptance, both engines: SIGKILL rank 0 — the
    coordination host — in a 3-process world mid-training. Its death
    takes the KV plane with it, so both survivors must produce an
    ATTRIBUTED verdict through the HVD_ELASTIC_DIR fallback file KV and
    resume at a bumped world epoch: either IN PLACE over the two
    survivors (multi-survivor shrink — root election + backend rebuild,
    no supervisor relaunch) or via one coordinated exit-77 restart —
    with a continuous loss curve either way.

    De-flake policy: same load-sensitive chaos class as the other
    scenarios in this tier (documented in CLAUDE.md): ONE automatic
    same-process retry with a loud note; a double failure is a real
    regression."""
    try:
        _rank0_failover_scenario(engine, str(tmp_path / "try1"))
    except (AssertionError, subprocess.TimeoutExpired) as exc:
        print(f"\n[RETRY] chaos rank0-kv-failover ({engine}) failed its "
              f"first attempt — retrying once in-process; a second "
              f"failure is a real regression. First failure: "
              f"{str(exc)[:500]}", file=sys.stderr, flush=True)
        _reap_stray_world_children()
        _rank0_failover_scenario(engine, str(tmp_path / "try2"))


def _rank0_failover_scenario(engine, base_dir):
    edir = os.path.join(base_dir, f"elastic0_{engine}")
    os.makedirs(edir)
    env = _clean_env({
        "HVD_ENGINE": engine,
        "HVD_NUMERICS": "warn",
        # One CPU core runs all three ranks: leases sized like the
        # 2-proc scenario's. Failover adds its own window on top: the
        # verdict lands ~(failover + lease) after the death.
        "HVD_ELASTIC_LEASE_S": "5",
        "HVD_ELASTIC_GRACE_S": "120",
        "HVD_ELASTIC_KV_FAILOVER_S": "4",
        "HVD_ELASTIC_REBUILD_TIMEOUT_S": "45",
        # Blacklist past the test horizon: the dead coordination host
        # must not be readmitted mid-scenario (the in-place world runs
        # to completion degraded).
        "HVD_ELASTIC_BLACKLIST_S": "600",
        "HVD_NEGOTIATION_TIMEOUT": "60",
        "HVD_FLIGHT_DIR": os.path.join(edir, "flight"),
        "HVD_FLIGHT_MIN_INTERVAL": "0",
        "HVD_TEST_KILL_RANK": "0",
        "HVD_TEST_EPOCHS": "10",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "3", "--cpu",
         "--ncpus-per-proc", "2", "--elastic", "--min-np", "1",
         "--max-restarts", "1", "--elastic-dir", edir, "--",
         sys.executable, _WORKER],
        capture_output=True, text=True, timeout=540, env=env, cwd=_REPO)
    out, err = proc.stdout, proc.stderr
    assert proc.returncode == 0, (proc.returncode, out[-4000:],
                                  err[-3000:])
    assert "CHAOS rank=0 dying" in out, out[-3000:]
    assert "rank 0 (pid " in err and "SIGKILL" in err, err[-2000:]

    # Both survivors cut the lease plane over to the file KV and
    # verdicted the coordination host THROUGH it.
    assert out.count("KV-plane failover") >= 2, out[-4000:]
    assert "process 0 is dead" in out, out[-4000:]
    assert "fallback file KV plane" in out, out[-4000:]
    note = os.path.join(edir, "death", "p0.json")
    assert os.path.exists(note), os.listdir(edir)
    assert "fallback file KV plane" in json.load(open(note))["reason"]

    # The world resumed at a bumped epoch — in place, or via ONE
    # coordinated restart; both are acceptance-valid.
    in_place = "IN PLACE with 2/3" in out
    restarted = "relaunching the world: generation 1" in err
    assert in_place or restarted, (out[-4000:], err[-2000:])
    if in_place:
        done = [ln for ln in out.splitlines() if "ELASTIC DONE" in ln]
        assert len(done) == 2, done
        assert all("np=2" in ln and "size=4" in ln for ln in done), done
        assert out.count("CONSISTENCY OK") == 2, out[-3000:]
    else:
        assert "RESUMED gen=1" in out, out[-3000:]
        done = [ln for ln in out.splitlines()
                if "ELASTIC DONE gen=1" in ln]
        assert len(done) == 3 and all("size=6" in ln for ln in done), \
            done

    # Continuous curves on BOTH survivors, world epoch bumped.
    for rank in (1, 2):
        recs = _parse_losses(
            os.path.join(edir, f"losses.rank{rank}.jsonl"))
        assert len(recs) >= 3, (rank, recs)
        _assert_continuous(recs)
        assert max(r["world_epoch"] for r in recs) >= 1, recs


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ENGINES)
def test_chaos_frozen_heartbeat(engine, tmp_path):
    """Frozen heartbeat, both engines: rank 1's process stays ALIVE but
    its beats stop (injected via ``run.py --faults`` — the launcher-side
    chaos entry point). The lease must distinguish this from death/
    no-show ('lease expired', not 'grace' or 'vanished'), the survivor
    shrinks and keeps training, the supervisor kills the wedged process
    and its death report names the active injections, and the
    survivor's flight dumps attribute its own injected faults.

    De-flake policy: these two variants are load-sensitive at ANY
    revision on this one-core host (documented in CLAUDE.md) — the
    whole scenario paces three processes against 5 s leases, so a noisy
    neighbor can starve a heartbeat or the post-rejoin consistency
    digest past its window. They get ONE automatic same-process retry
    with a loud note; a double failure is a real regression."""
    try:
        _frozen_heartbeat_scenario(engine, str(tmp_path / "try1"))
    except (AssertionError, subprocess.TimeoutExpired) as exc:
        print(f"\n[RETRY] chaos frozen-heartbeat ({engine}) failed its "
              f"first attempt — retrying once in-process; a second "
              f"failure is a real regression. First failure: "
              f"{str(exc)[:500]}", file=sys.stderr, flush=True)
        # A timed-out attempt SIGKILLed only the launcher: its rank
        # workers keep training and would starve the retry's 5 s leases
        # on this one-core host (the stale-world hazard conftest guards
        # against). Reap them before going again.
        _reap_stray_world_children()
        _frozen_heartbeat_scenario(engine, str(tmp_path / "try2"))


def _reap_stray_world_children():
    """SIGKILL leftover rank/launcher processes from a failed chaos
    attempt (cmdline-marked, never an ancestor of this process), then
    give the scheduler a beat. Best-effort: /proc races are fine."""
    import conftest

    for pid, _cmd in conftest._stale_world_processes():
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    time.sleep(1.0)


def _frozen_heartbeat_scenario(engine, base_dir):
    edir = os.path.join(base_dir, f"elastic_fz_{engine}")
    os.makedirs(edir)
    env = _clean_env({
        "HVD_ENGINE": engine,
        "HVD_NUMERICS": "warn",
        "HVD_ELASTIC_LEASE_S": "5",
        "HVD_ELASTIC_GRACE_S": "120",
        "HVD_ELASTIC_BLACKLIST_S": "10",
        "HVD_NEGOTIATION_TIMEOUT": "60",
        "HVD_FLIGHT_DIR": os.path.join(edir, "flight"),
        "HVD_FLIGHT_MIN_INTERVAL": "0",
        "HVD_TEST_KILL_MODE": "none",   # no SIGKILL: the fault IS the chaos
        "HVD_TEST_EPOCHS": "40",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--elastic", "--min-np", "1", "--max-restarts", "2",
         "--elastic-dir", edir,
         # Rank 1 beats healthily ~7 ticks (~9 s: past startup, into
         # training), then goes silent forever while the process lives.
         # Rank 0 gets two benign injected KV delays: its own telemetry
         # and flight dumps must attribute them as injected.
         "--faults", "1:hb.beat:skip:*@8",
         "--faults", "0:kv.try_get:delay:2:0.01",
         "--", sys.executable, _WORKER],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO)
    out, err = proc.stdout, proc.stderr
    assert proc.returncode == 0, (proc.returncode, out[-4000:],
                                  err[-3000:])

    # The verdict distinguishes a frozen peer from a dead/no-show one.
    assert "process 1 is dead" in out, out[-4000:]
    verdict = json.load(open(os.path.join(edir, "death", "p1.json")))
    assert "lease expired" in verdict["reason"], verdict
    assert "grace" not in verdict["reason"], verdict

    # The supervisor reaped the live-but-verdicted process and its
    # report names the injections it ran with.
    assert "still running (wedged); killing it" in err, err[-2000:]
    assert "active fault injections" in err, err[-2000:]
    assert "hb.beat:skip:*@8" in err, err[-2000:]

    # Shrink + keep training, then regrow after the blacklist.
    assert "RECONFIGURE: world epoch 0 -> 1" in out, out[-4000:]
    gen0_shrunk = [ln for ln in out.splitlines()
                   if ln.startswith("[0] EPOCH gen=0") and "size=4" in ln]
    assert gen0_shrunk, out[-4000:]
    assert "relaunching the world: generation 1" in err, err[-2000:]
    assert out.count("CONSISTENCY OK gen=1") == 2, out[-3000:]

    # Survivor-side attribution: rank 0's injected kv delays are in its
    # telemetry counters AND in its flight dumps' faults section.
    import glob

    dumped = []
    for d in glob.glob(os.path.join(edir, "flight", "*.json")):
        try:
            payload = json.load(open(d))
        except (OSError, ValueError):
            continue
        if payload.get("rank") == 0 and payload.get("faults"):
            dumped.append(payload)
    assert dumped, "no rank-0 flight dump carries the faults section"
    assert any(
        any(r["site"] == "kv.try_get" for r in p["faults"]["injected"])
        for p in dumped), dumped
    # ...and in the same dumps' telemetry snapshot (the acceptance:
    # every injected fault appears in fault.injected{site}).
    assert any(
        "fault.injected.kv.try_get" in json.dumps(p.get("telemetry", {}))
        for p in dumped), [p.get("telemetry") for p in dumped][:1]
    # Loss continuity on the survivor across shrink AND regrow.
    recs = _parse_losses(os.path.join(edir, "losses.rank0.jsonl"))
    assert len(recs) >= 5, recs
    _assert_continuous(recs)
    sizes = [r["size"] for r in recs]
    assert 8 in sizes and 4 in sizes and sizes[-1] == 8, sizes
