"""The unified telemetry core (core/telemetry.py): registry semantics,
eager-path counters, engine-path counters with native/python parity,
compiled-path rings, and the exposition/stats-CLI surfaces (reference
rationale: Horovod's production observability — timeline + stall/straggler
analysis, arxiv 1802.05799 §5)."""

import json
import threading
import time

import numpy as np
import pytest

from horovod_tpu.core import telemetry as tele


def _counters():
    return dict(tele.REGISTRY.flat_counters())


def _delta(before, after):
    """Counter deltas between two flat_counters() snapshots (the global
    registry is process-wide and monotonic, so tests compare deltas)."""
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# ---------------------------------------------------------------------------
# Registry unit semantics
# ---------------------------------------------------------------------------

def test_registry_metric_kinds():
    r = tele.Registry()
    r.counter("a.count").inc()
    r.counter("a.count").inc(4)
    r.gauge("a.depth").set(7)
    h = r.histogram("a.lat", bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)  # overflow bucket
    ring = r.ring("a.ring", size=2)
    for v in (1.0, 2.0, 3.0):
        ring.push(v)

    snap = r.snapshot()
    assert snap["a"]["count"] == 5
    assert snap["a"]["depth"] == 7
    assert snap["a"]["lat"]["count"] == 3
    assert snap["a"]["lat"]["sum"] == pytest.approx(5.55)
    # Ring keeps the window (2) but counts everything (3).
    assert snap["a"]["ring"]["count"] == 3
    assert snap["a"]["ring"]["last"] == 3.0
    assert snap["a"]["ring"]["window"] == 2
    # get-or-create returns the same object; kind mismatches are errors.
    assert r.counter("a.count").snapshot() == 5
    with pytest.raises(TypeError):
        r.gauge("a.count")


def test_registry_thread_safety():
    r = tele.Registry()
    c = r.counter("n")

    def spin():
        for _ in range(10000):
            c.inc()

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.snapshot() == 40000


def test_straggler_tracker_blames_latest():
    s = tele.StragglerTracker()
    # grad/0 and grad/1 aggregate into one class; process 1 is late.
    s.observe("grad/0", {0: 10.0, 1: 10.5})
    s.observe("grad/1", {0: 20.0, 1: 20.25})
    s.observe("loss", {0: 30.1, 1: 30.0})
    pid, us = s.worst()
    assert pid == 1
    assert us == pytest.approx(750000, abs=2)
    snap = s.snapshot()
    assert snap["tensors"] == 3
    assert set(snap["by_class"]) == {"grad/#", "loss"}
    assert snap["by_class"]["grad/#"][1] == pytest.approx(750000, abs=2)
    assert snap["by_class"]["loss"][0] == pytest.approx(100000, abs=2)
    assert any("process 1" in ln for ln in s.report_lines())
    # Single-participant observations carry no blame.
    s2 = tele.StragglerTracker()
    s2.observe("x", {0: 1.0})
    assert s2.worst() is None


def test_prometheus_round_trip_through_stats_cli():
    from horovod_tpu.utils import stats

    r = tele.Registry()
    r.counter("engine.completed").inc(3)
    r.gauge("engine.queue_depth").set(2)
    h = r.histogram("engine.negotiation_s", bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    r.ring("jax.dispatch_s").push(0.01)
    text = r.prometheus()
    samples = stats.parse_prometheus(text)
    by_name = {n: v for n, _, v in samples}
    assert by_name["hvd_engine_completed"] == 3
    assert by_name["hvd_engine_queue_depth"] == 2
    assert by_name["hvd_engine_negotiation_s_count"] == 2
    assert by_name["hvd_engine_negotiation_s_sum"] == pytest.approx(0.55)
    assert by_name["hvd_jax_dispatch_s_count"] == 1
    # Cumulative bucket counts parse with their labels.
    buckets = [(l, v) for n, l, v in samples
               if n == "hvd_engine_negotiation_s_bucket"]
    assert ({"le": "0.1"}, 1.0) in buckets
    assert ({"le": "+Inf"}, 2.0) in buckets
    table = stats.render(samples)
    assert "hvd_engine_completed" in table
    assert "hvd_engine_negotiation_s" in table


def test_telemetry_file_exposition(tmp_path):
    from horovod_tpu.utils import stats

    path = str(tmp_path / "telemetry.prom")
    tele.REGISTRY.counter("engine.completed").inc(0)  # ensure it exists
    tele.flush_to_file(path)
    samples = stats.parse_prometheus(open(path).read())
    assert any(n == "hvd_engine_completed" for n, _, _ in samples)
    # The stats CLI over the file prints a table.
    rc = stats.main([path])
    assert rc == 0


# ---------------------------------------------------------------------------
# (a) eager path
# ---------------------------------------------------------------------------

def test_eager_collectives_counted(hvd):
    import jax.numpy as jnp

    before = _counters()
    hvd.allreduce(jnp.ones((16,), jnp.float32), average=False)
    hvd.broadcast(jnp.ones((4,), jnp.float32), 0)
    hvd.allgather(jnp.ones((2, 3), jnp.float32))
    d = _delta(before, _counters())
    assert d["eager.allreduce.count"] == 1
    assert d["eager.allreduce.bytes"] == 64
    assert d["eager.broadcast.count"] == 1
    assert d["eager.allgather.count"] == 1
    # 8-rank world: nothing elided.
    assert "eager.allreduce.elided" not in d

    snap = hvd.telemetry()
    assert snap["eager"]["allreduce"]["count"] >= 1
    assert isinstance(hvd.telemetry_report(), str)
    assert "eager.allreduce.count" in hvd.telemetry_report()


# ---------------------------------------------------------------------------
# (b) engine path — python and native, real executor
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("impl", ["python", "native"])
def test_engine_async_counters_real_executor(hvd, impl):
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine

    before = _counters()
    e = (NativeEngine(timeline_path="") if impl == "native"
         else Engine(timeline=tl.Timeline(None)))
    try:
        h = e.allreduce_async("tele/x", np.ones((8,), np.float32), False)
        np.testing.assert_allclose(e.synchronize(h), np.full((8,), 8.0))
    finally:
        e.shutdown()
    d = _delta(before, _counters())
    assert d["engine.submitted.allreduce"] == 1
    assert d["engine.submitted.bytes"] == 32
    assert d["engine.completed"] == 1
    assert d.get("engine.cycles", 0) >= 1
    assert "engine.errors" not in d


class _EchoExecutor:
    """Deterministic local data plane (no mesh): identity results."""

    def allreduce(self, flat, average):
        return flat.copy()

    def allgather(self, t):
        return np.tile(t, (2,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        return t.copy()


def _submit_sequence(engine):
    """Identical submit sequence for the parity contract: synchronize
    after each enqueue so batching is deterministic (one entry per
    cycle)."""
    engine.synchronize(
        engine.allreduce_async("p/a", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allreduce_async("p/b", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allgather_async("p/g", np.ones((2, 3), np.float32)))
    engine.synchronize(
        engine.broadcast_async("p/c", np.ones((5,), np.float32), 0))
    engine.shutdown()


def test_native_python_counter_parity(hvd):
    """Same counter names, same values, for an identical submit sequence
    on both engines (the ISSUE's parity criterion). Wall-clock-dependent
    counters (cycles, cycle_seconds) are compared by presence, not
    value."""
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine

    TIMING = ("engine.cycles", "engine.cycle_seconds_total")

    before = _counters()
    _submit_sequence(Engine(executor=_EchoExecutor(),
                            timeline=tl.Timeline(None)))
    d_py = _delta(before, _counters())

    before = _counters()
    _submit_sequence(NativeEngine(executor=_EchoExecutor(),
                                  timeline_path=""))
    d_nat = _delta(before, _counters())

    # Buffer-pool event counts are implementation-scoped (the C++ engine
    # pools its entry/fusion/result buffers, the python engine its
    # snapshot/fusion/output buffers), so engine.pool.* is compared by
    # presence, not value — both engines must FEED the same names.
    def _core(d):
        return {k: v for k, v in d.items()
                if not k.startswith("engine.pool.")}

    assert set(_core(d_py)) == set(_core(d_nat)), (d_py, d_nat)
    for k in set(_core(d_py)) - set(TIMING):
        if k.endswith("seconds_total"):
            continue
        assert d_py[k] == d_nat[k], (k, d_py[k], d_nat[k])
    for d in (d_py, d_nat):
        assert d.get("engine.pool.checkouts", 0) > 0, d
    expected = {
        "engine.submitted.allreduce": 2,
        "engine.submitted.allgather": 1,
        "engine.submitted.broadcast": 1,
        "engine.submitted.bytes": 16 + 16 + 24 + 20,
        "engine.completed": 4,
    }
    for k, v in expected.items():
        assert d_py[k] == v, (k, d_py[k])
    for d in (d_py, d_nat):
        assert d.get("engine.cycles", 0) >= 1
        assert "engine.errors" not in d


class _PlugExecutor:
    """First allreduce blocks until released — tensors enqueued meanwhile
    pile up and fuse on the next drain (the deterministic fusion driver
    from test_timeline_profiler.py)."""

    def __init__(self):
        self.gate = threading.Event()
        self.started = threading.Event()
        self.calls = 0

    def allreduce(self, flat, average):
        self.calls += 1
        if self.calls == 1:
            self.started.set()
            self.gate.wait(5.0)
        return flat.copy()


@pytest.mark.parametrize("impl", ["python", "native"])
def test_fusion_counters(hvd, impl):
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine

    ex = _PlugExecutor()
    before = _counters()
    if impl == "native":
        e = NativeEngine(executor=ex, timeline_path="")
    else:
        e = Engine(executor=ex, timeline=tl.Timeline(None))
    h0 = e.allreduce_async("f/plug", np.ones((2,), np.float32), False)
    assert ex.started.wait(5.0)
    ha = e.allreduce_async("f/a", np.ones((4,), np.float32), False)
    hb = e.allreduce_async("f/b", np.ones((4,), np.float32), False)
    ex.gate.set()
    for h in (h0, ha, hb):
        e.synchronize(h)
    e.shutdown()
    d = _delta(before, _counters())
    assert d["engine.fused.batches"] == 1
    assert d["engine.fused.tensors"] == 2
    assert d["engine.fused.bytes"] == 32
    assert d["engine.completed"] == 3


def test_error_counter(hvd):
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine, EngineError

    class Boom:
        def allreduce(self, flat, average):
            raise RuntimeError("boom")

    before = _counters()
    e = Engine(executor=Boom(), timeline=tl.Timeline(None))
    try:
        h = e.allreduce_async("err/x", np.ones((2,), np.float32), False)
        with pytest.raises(EngineError):
            e.synchronize(h)
    finally:
        e.shutdown()
    d = _delta(before, _counters())
    assert d["engine.errors"] == 1
    assert "engine.completed" not in d


# ---------------------------------------------------------------------------
# (c) compiled path — jit dispatch ring + Trainer step ring
# ---------------------------------------------------------------------------

def test_jit_dispatch_ring(hvd):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hj
    from horovod_tpu.ops import collectives as C

    @hj.jit(in_specs=(P(hj.HVD_AXIS),), out_specs=P())
    def step(x):
        return C.allreduce(x[0], average=False)

    x = C.make_ranked([jnp.full((3,), float(i)) for i in range(hvd.size())])
    before = _counters()
    n0 = tele.REGISTRY.ring("jax.dispatch_s").count
    np.testing.assert_allclose(np.asarray(step(x)),
                               np.full((3,), float(sum(range(8)))))
    d = _delta(before, _counters())
    assert d["jax.dispatches"] == 1
    assert tele.REGISTRY.ring("jax.dispatch_s").count == n0 + 1
    # AOT surface still reachable through the wrapper (bench.py relies
    # on .lower/.compile bypassing instrumentation).
    assert "all-reduce" in step.lower(x).compile().as_text()


def test_trainer_step_telemetry(hvd):
    import optax

    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    rng = np.random.RandomState(0)
    x = rng.randn(32, 8, 8, 1).astype(np.float32)
    y = (rng.rand(32) * 10).astype(np.int32) % 10

    before = _counters()
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1))
    t.fit(x, y, batch_size=2, epochs=1)
    d = _delta(before, _counters())
    steps = 32 // (2 * hvd.local_size())
    assert d["trainer.steps"] == steps
    assert d["jax.dispatches"] >= steps
    ring = tele.REGISTRY.ring("trainer.step_s").snapshot()
    assert ring["count"] >= steps and ring["last"] > 0


# ---------------------------------------------------------------------------
# satellites: Metric.avg memoization + MetricAverage routing
# ---------------------------------------------------------------------------

def test_metric_avg_memoized(hvd, monkeypatch):
    from horovod_tpu.ops import collectives as C
    from horovod_tpu.utils import metrics

    calls = {"n": 0}
    real = C.allreduce

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(C, "allreduce", counting)
    m = metrics.Metric("loss")
    m.update(2.0)
    m.update(4.0)
    assert m.avg == pytest.approx(3.0)
    assert m.avg == pytest.approx(3.0)  # memoized: no second collective
    assert calls["n"] == 1
    m.update(6.0)
    assert m.avg == pytest.approx(4.0)  # state advanced: one more
    assert calls["n"] == 2


def test_metric_average_routed_through_registry(hvd):
    from horovod_tpu.utils import metrics

    before = _counters()
    out = metrics.MetricAverage({"loss": 1.0, "acc": 0.5})
    d = _delta(before, _counters())
    assert out["loss"] == pytest.approx(1.0)
    assert d["metrics.averages"] == 1
    assert d["metrics.averaged_values"] == 2
    # The underlying collective is counted with every other eager op.
    assert d["eager.allreduce.count"] == 1
