"""Hierarchical two-tier collectives composed with the int8 wire: the
ICI phase reduce-scatters at the resident dtype and only the 1/L
quantized shard crosses the DCN tier (reference:
HOROVOD_HIERARCHICAL_ALLREDUCE composed with the grpc/compression wire,
operations.cc:1194-1346 + the PR-12 block-scaled int8 pipeline).

Covers, on the simulated 2x4 (dcn, ici) split of the 8-device world:
- the compiled SPMD route, HLO-pinned: the cross-tier all-to-all payload
  is i8 and exactly 1/(L*D) of the full tensor;
- the engines' two-phase chunk route: python and native digests are
  bit-identical, and the new per-tier counters account DCN bytes at
  exactly flat-quantized-wire / L;
- the mutual-exclusion (uniform ``compression`` vs per-tier
  ``compression_dcn``) fail-fast;
- the degenerate-tier elisions (no two-tier mesh; dcn size 1)."""

import re

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.common import topology
from horovod_tpu.core import telemetry as tele
from horovod_tpu.core.engine import Engine, EngineError
from horovod_tpu.core.native_engine import NativeEngine
from horovod_tpu.jax.compression import Compression
from horovod_tpu.ops import collectives as C

D, L = 2, 4  # HVD_TWO_TIER_SHAPE: dcn-major split of the 8-chip world


@pytest.fixture
def two_tier_world(monkeypatch):
    monkeypatch.setenv("HVD_TWO_TIER_SHAPE", f"{D},{L}")
    monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
    hvd.shutdown()
    hvd.init()
    yield hvd
    monkeypatch.undo()
    hvd.shutdown()
    hvd.init()


# ---------------------------------------------------------------------------
# compiled route
# ---------------------------------------------------------------------------


def test_ranked_dcn_wire_matches_flat(two_tier_world):
    """Distinct per-rank values: the quantized cross-tier phase stays
    within the block-scaled int8 tolerance of the exact sum; the
    full-width hierarchical route stays (near-)exact."""
    rng = np.random.default_rng(0)
    vals = [rng.standard_normal(4096).astype(np.float32)
            for _ in range(8)]
    ref = np.sum(vals, axis=0)
    stacked = C.make_ranked(vals)
    full = np.asarray(C.ranked_allreduce(stacked))
    np.testing.assert_allclose(full, ref, rtol=1e-5, atol=1e-5)
    out_q = np.asarray(C.ranked_allreduce(stacked, dcn_wire="int8"))
    rel = np.abs(out_q - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_dcn_wire_rejects_non_quantized(two_tier_world):
    with pytest.raises(ValueError, match="quantized"):
        C.dcn_wire_policy("bf16")


def test_compiled_crosstier_payload_is_i8_and_fractional(two_tier_world):
    """HLO pin (the issue's acceptance bound): with the int8 policy the
    ONLY cross-tier collective payload is i8 sized exactly n/(L*D) per
    participant — the full-width f32 tensor never crosses the DCN tier."""
    n = L * D * 512 * 4  # divisible by every pad unit -> exact shapes
    lowered = hvd_jax.jit(
        lambda x: hvd_jax.allreduce(x, average=False,
                                    compression=Compression.int8),
        in_specs=(P(),), out_specs=P()).lower(jnp.zeros((n,), jnp.float32))
    hlo = lowered.compile().as_text()
    a2a_i8 = [l for l in hlo.splitlines()
              if "all-to-all" in l and "s8[" in l]
    assert a2a_i8, "no i8 cross-tier all-to-all in:\n" + hlo
    shapes = [m.group(1) for l in a2a_i8
              for m in [re.search(r"s8\[([\d,]+)\]", l)] if m]
    sizes = {int(np.prod([int(d) for d in s.split(",")])) for s in shapes}
    assert sizes == {n // (L * D)}, (sizes, n // (L * D))


def test_compiled_full_width_has_no_i8(two_tier_world):
    n = L * D * 512 * 4
    hlo = hvd_jax.jit(
        lambda x: hvd_jax.allreduce(x, average=False),
        in_specs=(P(),), out_specs=P()).lower(
        jnp.zeros((n,), jnp.float32)).compile().as_text()
    assert "s8[" not in hlo


def test_compiled_hier_int8_numerics(two_tier_world):
    @hvd_jax.jit(in_specs=(P(),), out_specs=P())
    def step(x):
        return hvd_jax.allreduce(x, average=False,
                                 compression=Compression.int8)

    y = np.asarray(step(jnp.ones((64, 32), jnp.float32)))
    np.testing.assert_allclose(y, 8.0, rtol=0.05)


# ---------------------------------------------------------------------------
# engine two-phase route
# ---------------------------------------------------------------------------

_KEYS = ("engine.wire_bytes", "engine.wire_bytes.compressed",
         "engine.wire_bytes.dcn", "engine.wire_bytes.ici")


def _run_engine(engine_cls, x, **kw):
    eng = engine_cls()
    try:
        h = eng.allreduce_async("t", x.copy(), average=False, **kw)
        return np.asarray(eng.synchronize(h)).copy()
    finally:
        eng.shutdown()


def _counter_deltas(engine_cls, x, **kw):
    base = tele.REGISTRY.flat_counters()
    out = _run_engine(engine_cls, x, **kw)
    cur = tele.REGISTRY.flat_counters()
    return out, {k: cur.get(k, 0) - base.get(k, 0) for k in _KEYS}


def test_engine_two_phase_bit_identical_and_tier_bytes(two_tier_world):
    """The issue's acceptance bound, asserted from the checked-in
    per-tier counters: with the int8 DCN wire, the cross-tier bytes
    (payload + scales) are exactly the flat quantized wire / L — the
    slow tier carries only the 1/L shard. Python and native engines
    produce bit-identical digests (same eager program underneath)."""
    n = 4096  # divisible by L*D*block -> byte math is exact
    x = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    ref = _run_engine(Engine, x)
    py, c_py = _counter_deltas(Engine, x, compression_dcn="int8")
    nat, c_nat = _counter_deltas(NativeEngine, x, compression_dcn="int8")
    np.testing.assert_array_equal(py, nat)
    assert c_py == c_nat, (c_py, c_nat)
    rel = np.abs(py - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel
    _, c_flat_q = _counter_deltas(Engine, x, compression="int8")
    assert c_py["engine.wire_bytes.dcn"] > 0
    assert (c_py["engine.wire_bytes.dcn"] * L
            == c_flat_q["engine.wire_bytes"]), (c_py, c_flat_q)
    assert c_py["engine.wire_bytes.ici"] == n * 4
    assert c_flat_q["engine.wire_bytes.dcn"] == 0  # flat route: no tiers


@pytest.mark.parametrize("engine_cls", [Engine, NativeEngine])
def test_engine_wire_exclusive_fail_fast(two_tier_world, engine_cls):
    eng = engine_cls()
    try:
        with pytest.raises(EngineError, match="both the uniform"):
            eng.allreduce_async("x", np.ones((4,), np.float32), False,
                                compression="int8",
                                compression_dcn="int8")
    finally:
        eng.shutdown()


def test_engine_dcn_wire_elides_without_two_tier(hvd):
    """Flat (single-tier) world: compression_dcn falls back to the
    full-width route — exact result, zero tier bytes."""
    assert topology.two_tier() is None
    x = np.arange(64, dtype=np.float32)
    _, c_plain = _counter_deltas(Engine, x)
    out, c = _counter_deltas(Engine, x, compression_dcn="int8")
    np.testing.assert_array_equal(out, x * 8)
    assert c["engine.wire_bytes.dcn"] == 0
    assert c["engine.wire_bytes.ici"] == 0
    assert c["engine.wire_bytes"] == c_plain["engine.wire_bytes"]


def test_engine_dcn_wire_elides_on_degenerate_outer_tier(monkeypatch):
    """dcn size 1 (a two-tier mesh with nothing across the slow tier):
    the quantized cross-tier phase elides bit-exactly."""
    monkeypatch.setenv("HVD_TWO_TIER_SHAPE", "1,8")
    monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
    hvd.shutdown()
    hvd.init()
    try:
        assert dict(topology.two_tier().shape)["dcn"] == 1
        x = np.arange(64, dtype=np.float32)
        out, c = _counter_deltas(Engine, x, compression_dcn="int8")
        np.testing.assert_array_equal(out, x * 8)
        assert c["engine.wire_bytes.dcn"] == 0
    finally:
        monkeypatch.undo()
        hvd.shutdown()
        hvd.init()
