"""Worker for the launcher test: relies entirely on the env wiring that
``python -m horovod_tpu.run`` provides (HVD_COORDINATOR_ADDRESS /
HVD_NUM_PROCESSES / HVD_PROCESS_ID / HVD_PLATFORM)."""

import numpy as np
import jax.numpy as jnp

import horovod_tpu as hvd

hvd.init()
assert hvd.num_processes() == 2, hvd.num_processes()
assert hvd.size() == 8, hvd.size()

out = np.asarray(hvd.allreduce(jnp.ones((2,)), average=False))
np.testing.assert_allclose(out, np.full((2,), 8.0))
print(f"rank {hvd.rank()} (proc {hvd.process_index()}): LAUNCHER TEST PASSED",
      flush=True)
