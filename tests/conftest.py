"""Test harness: an 8-device virtual CPU mesh stands in for an 8-chip TPU
slice (the reference's equivalent trick is `mpirun -np N` on one host —
SURVEY.md §4).

jax may already be imported by the interpreter's sitecustomize, so platform
selection must go through jax.config (env vars would be too late); XLA_FLAGS
still applies because the backend itself is not initialized until first use.
"""

import os

# The performance sentinel (core/sentinel.py) defaults ON in production;
# in the suite, hundreds of heterogeneous jit programs share one process
# and every first-call compile would read as a dispatch anomaly — dumps
# and warnings all over the output. Tests that exercise the watchdog
# re-enable it explicitly (tests/test_perfwatch.py resets the
# singleton). setdefault: an operator's explicit env still wins.
os.environ.setdefault("HVD_WATCHDOG", "0")

# The numerics observatory (core/numerics.py) likewise defaults ON
# (warn) in production; in the suite, hundreds of heterogeneous tiny
# models — several of which deliberately produce NaN — would trip
# verdicts/dump files (and the halt policy would abort legitimate
# tests). The numerics tests re-enable it explicitly per-test
# (tests/test_numerics.py sets HVD_NUMERICS and resets the module
# latches). setdefault: an operator's explicit env still wins, and
# spawned multiprocess worlds inherit it.
os.environ.setdefault("HVD_NUMERICS", "off")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Cmdline markers of multiprocess-world processes this suite spawns.
_WORLD_MARKERS = ("multiproc_worker.py", "launcher_worker.py",
                  "elastic_worker.py", "horovod_tpu.run")


def _ancestor_pids() -> set:
    pids = set()
    pid = os.getpid()
    for _ in range(64):  # bounded walk; /proc chains are short
        pids.add(pid)
        try:
            with open(f"/proc/{pid}/status") as fh:
                ppid = next((int(line.split()[1]) for line in fh
                             if line.startswith("PPid:")), 0)
        except (OSError, ValueError):
            break
        if ppid <= 1:
            break
        pid = ppid
    return pids


def _stale_world_processes():
    """Leftover multiprocess-world processes from a previous (crashed or
    still-running) session. The host has ONE CPU core: a stale 2-process
    world silently starves every new 8-device rendezvous until XLA:CPU's
    40 s abort — the documented failure mode (CLAUDE.md). Detect by
    cmdline marker or by HVD_COORDINATOR_ADDRESS in the environment (the
    latter catches orphaned inner pytest workers whose launcher died)."""
    mine = _ancestor_pids()
    stale = []
    try:
        entries = os.listdir("/proc")
    except OSError:
        return stale
    for entry in entries:
        if not entry.isdigit() or int(entry) in mine:
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as fh:
                cmd = fh.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
        except OSError:
            continue  # gone, or not ours to inspect
        hit = any(m in cmd for m in _WORLD_MARKERS)
        if not hit:
            try:
                with open(f"/proc/{entry}/environ", "rb") as fh:
                    hit = b"HVD_COORDINATOR_ADDRESS=" in fh.read()
            except OSError:
                hit = False
        if hit:
            stale.append((int(entry), cmd[:120]))
    return stale


def pytest_configure(config):
    # Declared markers: `slow` gates the opt-in multi-minute tier
    # (ROADMAP tier-1 runs -m 'not slow'); `chaos` tags the elastic
    # failure-injection scenarios (tests/test_world_elastic.py) — they
    # run in tier-1 like the other multiprocess worlds (sequentially;
    # the stale-world preflight below already covers their children).
    config.addinivalue_line(
        "markers", "slow: opt-in multi-minute tier (HVD_SLOW_TESTS=1)")
    config.addinivalue_line(
        "markers", "chaos: elastic chaos-monkey multiprocess scenarios")
    if os.environ.get("HVD_COORDINATOR_ADDRESS") or os.environ.get(
            "HVD_NUM_PROCESSES") or os.environ.get("HVD_PREFLIGHT_SKIP"):
        # We ARE a spawned world member (frontend suites re-run under the
        # launcher) — sibling ranks and the launcher are expected, not
        # stale. HVD_PREFLIGHT_SKIP is the manual override.
        return
    stale = _stale_world_processes()
    if stale:
        listing = "\n".join(f"  pid {pid}: {cmd}" for pid, cmd in stale)
        raise pytest.UsageError(
            "stale multiprocess-world processes are still running from an "
            "earlier session; on this one-core host they would starve "
            "every 8-device rendezvous into 40 s XLA aborts instead of a "
            "clear failure. Kill them (or set HVD_PREFLIGHT_SKIP=1 if "
            f"they are intentional):\n{listing}")


@pytest.fixture(scope="session")
def hvd():
    import horovod_tpu as hvd

    hvd.init()
    return hvd
