"""Test harness: an 8-device virtual CPU mesh stands in for an 8-chip TPU
slice (the reference's equivalent trick is `mpirun -np N` on one host —
SURVEY.md §4).

jax may already be imported by the interpreter's sitecustomize, so platform
selection must go through jax.config (env vars would be too late); XLA_FLAGS
still applies because the backend itself is not initialized until first use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def hvd():
    import horovod_tpu as hvd

    hvd.init()
    return hvd
