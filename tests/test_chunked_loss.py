"""Chunked / pallas LM-head cross-entropy vs the stock logits path.

The reference has no transformer ops; this pins the TPU-native scope
addition (ops/chunked_loss.py) the way test_flash_attention.py pins the
flash kernel: exact forward/gradient agreement with the naive
implementation on CPU (pallas interpret mode), including the padding
edges (vocab not a chunk multiple, tokens not a block multiple)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.chunked_loss import (
    chunked_softmax_cross_entropy,
    fused_softmax_cross_entropy,
)


def _data(n_lead=(3, 5), hdim=16, vocab=70, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(*n_lead, hdim), dtype)
    w = jnp.asarray(rng.randn(hdim, vocab) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(vocab) * 0.1, jnp.float32)
    lab = jnp.asarray(rng.randint(0, vocab, n_lead), jnp.int32)
    return h, w, b, lab


def _ref_losses(h, w, b, lab):
    logits = h.astype(jnp.float32) @ w + b
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, lab[..., None], axis=-1)[..., 0]
    return lse - gold


@pytest.mark.parametrize("impl,kw", [
    (chunked_softmax_cross_entropy, {"chunk": 32}),
    (fused_softmax_cross_entropy, {"block_n": 8, "block_v": 32}),
])
def test_forward_matches_reference(impl, kw):
    h, w, b, lab = _data()  # V=70: not a multiple of 32 -> padding path
    out = impl(h, w, b, lab, **kw)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref_losses(h, w, b, lab)),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("impl,kw", [
    (chunked_softmax_cross_entropy, {"chunk": 32}),
    (fused_softmax_cross_entropy, {"block_n": 8, "block_v": 32}),
])
def test_gradients_match_reference(impl, kw):
    h, w, b, lab = _data()
    # Non-uniform per-token cotangents (the reference test style:
    # multiply by a random tensor before reducing).
    wvec = jnp.asarray(np.random.RandomState(1).rand(3, 5), jnp.float32)

    ref = jax.grad(lambda *a: (_ref_losses(*a, lab) * wvec).mean(),
                   argnums=(0, 1, 2))(h, w, b)
    got = jax.grad(lambda *a: (impl(*a, lab, **kw) * wvec).mean(),
                   argnums=(0, 1, 2))(h, w, b)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=1e-6)


def test_dx_dtype_follows_hidden():
    h, w, b, lab = _data(dtype=jnp.bfloat16)
    g = jax.grad(lambda x: fused_softmax_cross_entropy(
        x, w, b, lab, 8, 32).mean())(h)
    assert g.dtype == jnp.bfloat16


def test_model_level_equivalence():
    """return_hidden + chunked head == stock lm_head -> optax CE, through
    a real TransformerLM (same params, same loss, same grads)."""
    import optax

    from horovod_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=97, num_layers=2, num_heads=2,
                            hidden_dim=32, mlp_dim=64, max_len=16,
                            dtype=jnp.float32)
    model = TransformerLM(cfg)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 97, (4, 16)),
                       jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    tgt = jnp.roll(toks, -1, axis=1)

    def stock(p):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    def fused(p):
        hidden = model.apply({"params": p}, toks, return_hidden=True)
        return chunked_softmax_cross_entropy(
            hidden, p["lm_head"]["kernel"], p["lm_head"]["bias"], tgt,
            32).mean()

    ls, gs = jax.value_and_grad(stock)(params)
    lf, gf = jax.value_and_grad(fused)(params)
    np.testing.assert_allclose(float(lf), float(ls), rtol=1e-6)
    flat_s = {jax.tree_util.keystr(k): x
              for k, x in jax.tree_util.tree_leaves_with_path(gs)}
    flat_f = {jax.tree_util.keystr(k): x
              for k, x in jax.tree_util.tree_leaves_with_path(gf)}
    assert set(flat_s) == set(flat_f)
    for k in flat_s:
        np.testing.assert_allclose(np.asarray(flat_f[k]),
                                   np.asarray(flat_s[k]),
                                   rtol=5e-4, atol=1e-6, err_msg=k)


def test_init_param_tree_unchanged_by_return_hidden():
    """lm_head params exist (init never passes return_hidden) so
    checkpoints and optimizer states are unaffected by the new kwarg."""
    from horovod_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=50, num_layers=1, num_heads=2,
                            hidden_dim=16, mlp_dim=32, max_len=8)
    model = TransformerLM(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    assert "lm_head" in params
    assert params["lm_head"]["kernel"].shape == (16, 50)


@pytest.mark.parametrize("impl,kw", [
    (chunked_softmax_cross_entropy, {"chunk": 32}),
    (fused_softmax_cross_entropy, {"block_n": 8, "block_v": 32}),
])
def test_mask_ignored_labels_via_cotangent(impl, kw):
    """The documented ignore-index convention (op docstring; ADVICE r3):
    clip out-of-range labels into range, weight their per-token losses
    with 0 — the zero cotangent must zero those tokens' gradients, and
    the weighted loss must equal the reference's over kept tokens."""
    h, w, b, lab = _data(n_lead=(6,))
    raw = np.asarray(lab).copy()
    raw[2] = -100  # the usual ignore-index
    keep = jnp.asarray(raw >= 0, jnp.float32)
    clipped = jnp.asarray(np.clip(raw, 0, None), jnp.int32)

    masked = (impl(h, w, b, clipped, **kw) * keep).sum()
    ref = (_ref_losses(h, w, b, clipped) * keep).sum()
    np.testing.assert_allclose(float(masked), float(ref), rtol=1e-5)

    g = jax.grad(lambda h: (impl(h, w, b, clipped, **kw) * keep).sum())(h)
    np.testing.assert_allclose(np.asarray(g[2]), 0.0, atol=1e-7)
    assert np.abs(np.asarray(g)[[0, 1, 3, 4, 5]]).min() > 0
