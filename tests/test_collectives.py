"""Collective correctness, mirroring the reference oracle pattern:
allreduce == tensor * size etc. (reference: test/test_tensorflow.py:56-119,
test/test_torch.py:68-224), plus ranked variants with distinct per-rank
values — the multi-rank case the reference needs mpirun for."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import collectives as C


def test_eager_allreduce_sum(hvd):
    x = jnp.arange(12.0).reshape(3, 4)
    out = hvd.allreduce(x, average=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * hvd.size())


def test_eager_allreduce_average(hvd):
    x = jnp.arange(12.0).reshape(3, 4)
    out = hvd.allreduce(x, average=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def test_eager_allreduce_int(hvd):
    x = jnp.arange(6, dtype=jnp.int32)
    out = hvd.allreduce(x, average=False)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x) * hvd.size())


def test_ranked_allreduce_distinct(hvd):
    vals = [jnp.full((2, 3), float(r)) for r in range(hvd.size())]
    stacked = C.make_ranked(vals)
    out = C.ranked_allreduce(stacked)
    expect = sum(range(hvd.size()))
    np.testing.assert_allclose(np.asarray(out), np.full((2, 3), float(expect)))


def test_eager_allgather(hvd):
    x = jnp.arange(6.0).reshape(2, 3)
    out = hvd.allgather(x)
    assert out.shape == (2 * hvd.size(), 3)
    np.testing.assert_allclose(
        np.asarray(out), np.tile(np.asarray(x), (hvd.size(), 1))
    )


def test_ranked_allgather_distinct(hvd):
    vals = [jnp.full((2,), float(r)) for r in range(hvd.size())]
    out = C.ranked_allgather(C.make_ranked(vals))
    expect = np.repeat(np.arange(hvd.size(), dtype=np.float32), 2)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_eager_broadcast(hvd):
    x = jnp.arange(4.0)
    for root in (0, hvd.size() - 1):
        out = hvd.broadcast(x, root_rank=root)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_ranked_broadcast_distinct(hvd):
    vals = [jnp.full((3,), float(r)) for r in range(hvd.size())]
    stacked = C.make_ranked(vals)
    for root in (0, 3, hvd.size() - 1):
        out = C.ranked_broadcast(stacked, root)
        np.testing.assert_allclose(np.asarray(out), np.full((3,), float(root)))


def test_ranked_reducescatter(hvd):
    n = hvd.size()
    vals = [jnp.arange(n, dtype=jnp.float32) + r for r in range(n)]
    out = C.ranked_reducescatter(C.make_ranked(vals))
    # Sum over ranks of vals = n*arange(n) + sum(r) ; rank r keeps chunk r.
    total = n * np.arange(n) + sum(range(n))
    assert out.shape == (n, 1)
    np.testing.assert_allclose(np.asarray(out)[:, 0], total)


def test_reducescatter_non_divisible_padding_contract(hvd):
    """Dim 0 not divisible by size: zero-pad to the next multiple, rank r
    keeps rows [r*c, (r+1)*c) of the padded sum, c = ceil(n/size) — the
    contract the sharded weight update composes on (allgather then slice
    [:n] recovers the original extent)."""
    n = hvd.size()
    rows = n + 2  # 10 rows over 8 ranks -> c = 2, padded to 16
    x = jnp.arange(rows * 3, dtype=jnp.float32).reshape(rows, 3)
    out = hvd.reducescatter(x)
    c = -(-rows // n)
    assert out.shape == (c, 3)
    # Eager semantics: every local chip contributes this controller's x,
    # so the sum is x * size; this process sees its FIRST rank's chunk.
    np.testing.assert_allclose(np.asarray(out), np.asarray(x[:c]) * n)


def test_ranked_reducescatter_non_divisible(hvd):
    n = hvd.size()
    rows = n + 2
    vals = [jnp.arange(rows, dtype=jnp.float32) + r for r in range(n)]
    out = C.ranked_reducescatter(C.make_ranked(vals))
    c = -(-rows // n)
    assert out.shape == (n, c)
    total = n * np.arange(rows) + sum(range(n))
    padded = np.zeros(n * c, np.float32)
    padded[:rows] = total
    np.testing.assert_allclose(np.asarray(out).ravel(), padded)


def test_spmd_reducescatter_allgather_roundtrip_non_divisible(hvd):
    """In-SPMD: reducescatter -> allgather -> [:n] == allreduce sum, for
    a leading dim the world size does not divide (the sharded-update
    round trip)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    n = hvd.size()
    rows = 2 * n + 3

    def step(x):
        x = x[0]  # this rank's (rows,) contribution
        rs = hvd.reducescatter(x)
        back = hvd.allgather(rs)[:rows]
        return (back - hvd.allreduce(x, average=False))[None]

    xs = jnp.arange(n * rows, dtype=jnp.float32).reshape(n, rows)
    f = jax.jit(shard_map(
        step, mesh=hvd.mesh(), in_specs=P(C.HVD_AXIS, None),
        out_specs=P(C.HVD_AXIS, None), check_vma=False))
    np.testing.assert_allclose(np.asarray(f(xs)), np.zeros((n, rows)),
                               atol=1e-5)


def test_reducescatter_scalar_raises(hvd):
    with pytest.raises(ValueError, match="at least one dimension"):
        hvd.reducescatter(jnp.float32(1.0))


def test_ranked_alltoall(hvd):
    n = hvd.size()
    # rank r's tensor: [r*n, r*n+1, ..., r*n+n-1]; after alltoall rank r
    # holds column r: [r, n+r, 2n+r, ...].
    vals = [jnp.arange(n, dtype=jnp.float32) + r * n for r in range(n)]
    out = C.ranked_alltoall(C.make_ranked(vals))
    expect = np.arange(n * n, dtype=np.float32).reshape(n, n).T
    np.testing.assert_allclose(np.asarray(out), expect)


def test_grouped_allreduce_mixed_dtypes(hvd):
    ts = [
        jnp.ones((4,), jnp.float32),
        jnp.ones((2, 2), jnp.float32) * 2,
        jnp.ones((3,), jnp.int32),
    ]
    out = hvd.grouped_allreduce(ts, average=False)
    np.testing.assert_allclose(np.asarray(out[0]), np.full((4,), hvd.size()))
    np.testing.assert_allclose(np.asarray(out[1]), np.full((2, 2), 2 * hvd.size()))
    np.testing.assert_array_equal(np.asarray(out[2]), np.full((3,), hvd.size()))
    assert out[2].dtype == jnp.int32


def test_allreduce_pytree(hvd):
    tree = {"a": jnp.ones((2,)), "b": [jnp.zeros((3,)), jnp.full((1,), 2.0)]}
    out = hvd.allreduce_pytree(tree, average=False)
    np.testing.assert_allclose(np.asarray(out["a"]), np.full((2,), hvd.size()))
    np.testing.assert_allclose(np.asarray(out["b"][1]), np.full((1,), 2.0 * hvd.size()))


def test_broadcast_pytree(hvd):
    tree = {"w": jnp.arange(4.0), "b": jnp.ones((2,), jnp.int32)}
    out = hvd.broadcast_pytree(tree, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.arange(4.0))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.ones((2,), np.int32))


def test_in_spmd_collectives(hvd):
    """Collectives inside shard_map over the world mesh — the hot path."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    mesh = hvd.mesh()
    n = hvd.size()

    def step(x):
        # x: this rank's shard (1, 4)
        r = hvd.allreduce(x, average=False)
        m = hvd.allreduce(x, average=True)
        g = hvd.allgather(x)
        b = hvd.broadcast(x, root_rank=2)
        return r, m, g, b

    xs = jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4)
    f = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=P(C.HVD_AXIS, None),
            out_specs=(P(C.HVD_AXIS, None),) * 2 + (P(C.HVD_AXIS, None), P(C.HVD_AXIS, None)),
        )
    )
    r, m, g, b = f(xs)
    expect_sum = np.asarray(xs).sum(0, keepdims=True)
    np.testing.assert_allclose(np.asarray(r), np.tile(expect_sum, (n, 1)))
    np.testing.assert_allclose(np.asarray(m), np.tile(expect_sum / n, (n, 1)), rtol=1e-6)
    assert g.shape == (n * n, 4)
    np.testing.assert_allclose(np.asarray(b), np.tile(np.asarray(xs)[2:3], (n, 1)))


def test_jit_without_axis_raises(hvd):
    def f(x):
        return hvd.allreduce(x)

    with pytest.raises(Exception, match="hvd"):
        jax.jit(f)(jnp.ones((2,)))


def test_broadcast_nan_on_nonroot_does_not_poison(hvd):
    """Non-root NaN/Inf must not leak into the broadcast result."""
    vals = [jnp.full((3,), jnp.nan) for _ in range(hvd.size())]
    vals[2] = jnp.arange(3.0)
    out = C.ranked_broadcast(C.make_ranked(vals), 2)
    np.testing.assert_allclose(np.asarray(out), np.arange(3.0))


def test_broadcast_bool(hvd):
    vals = [jnp.zeros((4,), bool) for _ in range(hvd.size())]
    vals[1] = jnp.array([True, False, True, True])
    out = C.ranked_broadcast(C.make_ranked(vals), 1)
    assert out.dtype == bool
    np.testing.assert_array_equal(np.asarray(out), np.array([True, False, True, True]))


def test_broadcast_root_out_of_range(hvd):
    with pytest.raises(ValueError, match="out of range"):
        hvd.broadcast(jnp.arange(4.0), root_rank=hvd.size())


def test_spmd_int_average_preserves_dtype(hvd):
    """Traced and eager integer averaging must agree (floor-div, same dtype)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    n = hvd.size()
    xs = jnp.full((n, 4), 3, dtype=jnp.int32)
    f = jax.jit(
        shard_map(
            lambda x: hvd.allreduce(x[0], average=True)[None],
            mesh=hvd.mesh(),
            in_specs=P(C.HVD_AXIS, None),
            out_specs=P(C.HVD_AXIS, None),
            check_vma=False,
        )
    )
    out = f(xs)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), np.full((n, 4), 3))
