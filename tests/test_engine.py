"""Async engine semantics (reference behaviors: duplicate-name rejection
operations.cc:265-268, fusion operations.cc:2035-2074, stall warnings
operations.cc:1535-1581, timeline timeline.cc, shutdown error path
operations.cc:1833-1848)."""

import json
import logging
import time

import numpy as np
import pytest

from horovod_tpu.core import engine as eng
from horovod_tpu.core import timeline as tl


class RecordingExecutor:
    """Deterministic local executor: allreduce multiplies by world (as if
    every rank contributed the same tensor)."""

    def __init__(self, world=8, delay=0.0):
        self.world = world
        self.delay = delay
        self.calls = []

    def allreduce(self, flat, average):
        if self.delay:
            time.sleep(self.delay)
        self.calls.append(("allreduce", flat.size, average))
        return flat if average else flat * self.world

    def allgather(self, t):
        self.calls.append(("allgather", t.size, None))
        return np.tile(t, (self.world,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        self.calls.append(("broadcast", t.size, root))
        return t.copy()


def _mk(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("timeline", tl.Timeline(None))
    return eng.Engine(executor=executor or RecordingExecutor(), **kw)


def test_allreduce_roundtrip():
    e = _mk()
    try:
        h = e.allreduce_async("t1", np.ones((4,), np.float32), average=False)
        out = e.synchronize(h)
        np.testing.assert_allclose(out, np.full((4,), 8.0))
    finally:
        e.shutdown()


def test_poll_then_synchronize():
    e = _mk()
    try:
        h = e.allreduce_async("t1", np.ones((2,), np.float32), average=True)
        deadline = time.monotonic() + 2
        while not e.poll(h):
            assert time.monotonic() < deadline
            time.sleep(0.001)
        np.testing.assert_allclose(e.synchronize(h), np.ones((2,)))
    finally:
        e.shutdown()


def test_duplicate_name_rejected():
    ex = RecordingExecutor(delay=0.05)
    e = _mk(ex, cycle_time_s=0.001)
    try:
        h1 = e.allreduce_async("same", np.ones((2,), np.float32), False)
        with pytest.raises(eng.DuplicateNameError):
            e.allreduce_async("same", np.ones((2,), np.float32), False)
        e.synchronize(h1)
        # After completion the name is free again.
        h2 = e.allreduce_async("same", np.ones((2,), np.float32), False)
        e.synchronize(h2)
    finally:
        e.shutdown()


def test_fusion_batches_same_dtype(monkeypatch):
    """Many small same-dtype allreduces fuse into one executor call
    (the reference's fusion buffer: test_tensorflow.py:87-119 analogue)."""
    ex = RecordingExecutor()
    e = _mk(ex, cycle_time_s=0.05)  # long cycle so all enqueue in one tick
    try:
        time.sleep(0.06)  # let the first empty cycle pass
        handles = [
            e.allreduce_async(f"t{i}", np.full((8,), float(i), np.float32), False)
            for i in range(16)
        ]
        outs = [e.synchronize(h) for h in handles]
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o, np.full((8,), 8.0 * i))
        ar_calls = [c for c in ex.calls if c[0] == "allreduce"]
        assert len(ar_calls) < 16, f"no fusion happened: {len(ar_calls)} calls"
    finally:
        e.shutdown()


def test_fusion_respects_threshold():
    ex = RecordingExecutor()
    e = _mk(ex, cycle_time_s=0.05, fusion_threshold=8 * 4)  # 8 floats
    try:
        time.sleep(0.06)
        handles = [
            e.allreduce_async(f"t{i}", np.ones((8,), np.float32), False)
            for i in range(4)
        ]
        for h in handles:
            e.synchronize(h)
        ar_calls = [c for c in ex.calls if c[0] == "allreduce"]
        assert all(c[1] <= 8 for c in ar_calls)
    finally:
        e.shutdown()


def test_mixed_dtypes_not_fused():
    ex = RecordingExecutor()
    e = _mk(ex, cycle_time_s=0.05)
    try:
        time.sleep(0.06)
        h1 = e.allreduce_async("f", np.ones((4,), np.float32), False)
        h2 = e.allreduce_async("i", np.ones((4,), np.int32), False)
        e.synchronize(h1)
        e.synchronize(h2)
        ar_calls = [c for c in ex.calls if c[0] == "allreduce"]
        assert len(ar_calls) == 2
    finally:
        e.shutdown()


def test_executor_error_surfaces_at_synchronize():
    class Boom(RecordingExecutor):
        def allreduce(self, flat, average):
            raise RuntimeError("wire fell out")

    e = _mk(Boom())
    try:
        h = e.allreduce_async("t", np.ones((2,), np.float32), False)
        with pytest.raises(eng.EngineError, match="wire fell out"):
            e.synchronize(h)
    finally:
        e.shutdown()


def test_shutdown_fails_outstanding():
    ex = RecordingExecutor(delay=0.2)
    e = _mk(ex, cycle_time_s=0.001)
    h = e.allreduce_async("t", np.ones((2,), np.float32), False)
    h2 = e.allreduce_async("t2", np.ones((2,), np.float32), False)
    e.shutdown()
    # Whatever had not completed fails with the shutdown error; anything
    # already executed may legitimately succeed.
    for hh in (h, h2):
        try:
            e.synchronize(hh)
        except (eng.ShutdownError, eng.EngineError):
            pass


def test_stall_warning(caplog):
    class Never(RecordingExecutor):
        def allreduce(self, flat, average):
            time.sleep(10)
            return flat

    e = eng.Engine(executor=Never(), cycle_time_s=0.001,
                   stall_warning_s=0.05, timeline=tl.Timeline(None))
    try:
        e.allreduce_async("stuck", np.ones((2,), np.float32), False)
        e.allreduce_async("stuck2", np.ones((2,), np.float32), False)
        with caplog.at_level(logging.WARNING, logger="horovod_tpu.engine"):
            time.sleep(0.3)
        assert any("stuck2" in r.message for r in caplog.records)
    finally:
        e._shutdown.set()  # don't join the sleeping thread


def test_timeline_written(tmp_path):
    path = tmp_path / "timeline.json"
    e = eng.Engine(executor=RecordingExecutor(), cycle_time_s=0.002,
                   timeline=tl.Timeline(str(path)))
    h = e.allreduce_async("tensor_a", np.ones((4,), np.float32), False)
    e.synchronize(h)
    h = e.broadcast_async("tensor_b", np.ones((4,), np.float32), 0)
    e.synchronize(h)
    e.shutdown()
    events = json.loads(path.read_text())
    names = {ev.get("name") for ev in events if ev}
    assert tl.ALLREDUCE in names and tl.BROADCAST in names and tl.QUEUE in names
    lanes = {ev["args"]["name"] for ev in events
             if ev and ev.get("ph") == "M"
             and ev.get("name") == "process_name"}
    assert {"tensor_a", "tensor_b"} <= lanes
    # Distributed tracing: the clock mapping rides every trace.
    assert any(ev.get("name") == "HVD_CLOCK" for ev in events if ev)


def test_fused_many_small_beats_unfused(hvd):
    """Runtime tensor fusion must WIN, not just exist: 256 small
    allreduces through the real staging executor complete faster (and in
    far fewer data-plane calls) with the 64 MB fusion buffer than with
    fusion disabled — the reference's raison d'être for C5
    (reference: docs/tensor-fusion.md, parameter_manager.cc:40-60)."""
    import time

    import numpy as np

    from horovod_tpu.core import engine as eng

    import threading

    class CountingJax(eng.JaxExecutor):
        calls = 0
        gate: "threading.Event" = None
        started: "threading.Event" = None

        def allreduce(self, flat, average):
            CountingJax.calls += 1
            if (CountingJax.gate is not None
                    and not CountingJax.started.is_set()):
                CountingJax.started.set()
                CountingJax.gate.wait(5.0)
            return super().allreduce(flat, average)

    def run(threshold):
        CountingJax.calls = 0
        e = eng.Engine(executor=CountingJax(), cycle_time_s=0.002,
                       fusion_threshold=threshold)
        # Many SMALL tensors: the regime fusion exists for (per-call
        # dispatch overhead dominates; on the CPU test mesh large fused
        # payloads are artificially slow because every virtual device
        # holds a full replica, so sizes stay modest here — the on-chip
        # sweep lives in examples/allreduce_benchmark.py --engine).
        tensors = [np.ones((1024,), np.float32) for _ in range(256)]

        def one_round(tag):
            # Plug the dispatch thread so all 256 tensors land in one
            # drain — deterministic fusion composition (same trick as the
            # timeline fusion test).
            CountingJax.gate = threading.Event()
            CountingJax.started = threading.Event()
            hp = e.allreduce_async(f"f/{tag}/plug",
                                   np.ones((4,), np.float32), False)
            assert CountingJax.started.wait(5.0)
            hs = [e.allreduce_async(f"f/{tag}/{i}", t, False)
                  for i, t in enumerate(tensors)]
            CountingJax.gate.set()
            CountingJax.gate = None
            e.synchronize(hp)
            expect = np.full(4, hvd.size())
            for h in hs:
                np.testing.assert_allclose(e.synchronize(h)[:4], expect)
        one_round("warm")  # compile/stage warmup
        t0 = time.perf_counter()
        one_round("hot")
        dt = time.perf_counter() - t0
        calls = CountingJax.calls
        e.shutdown()
        return dt, calls

    t_unfused, calls_unfused = run(0)
    t_fused, calls_fused = run(64 * 1024 * 1024)
    # Fusion collapses the data-plane call count: unfused is one call per
    # tensor per round (256 + 1 plug, two rounds); fused is a handful.
    assert calls_unfused == 514
    assert calls_fused < calls_unfused / 8, (calls_fused, calls_unfused)
    # Generous wall-clock bound (loaded CI machines jitter); the on-chip
    # size sweep lives in examples/allreduce_benchmark.py --engine.
    assert t_fused < t_unfused, (t_fused, t_unfused)


def test_async_submit_snapshots_tensor():
    """Mutating the submitted buffer after *_async must not change what
    gets reduced — the C++ engine memcpys at enqueue, and the python
    twin owes the same observable semantics (CLAUDE.md invariant). The
    contract matters since r4: frontends hand over zero-copy views
    (torch .numpy() / the bf16 bit-reinterpret)."""
    gate = __import__("threading").Event()

    class Gated(RecordingExecutor):
        def allreduce(self, flat, average):
            gate.wait(5.0)  # hold the cycle so the mutation races it
            return super().allreduce(flat, average)

    e = _mk(executor=Gated())
    try:
        buf = np.ones((8,), np.float32)
        h = e.allreduce_async("snap", buf, average=False)
        buf[:] = 777.0  # caller reuses its buffer immediately
        gate.set()
        np.testing.assert_allclose(e.synchronize(h), np.full((8,), 8.0))
    finally:
        gate.set()
        e.shutdown()
