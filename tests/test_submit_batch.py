"""Batched submit plane (ISSUE 16 tentpole): ``Engine.submit_n`` /
``NativeEngine.submit_n`` / ``hvd_engine_enqueue_n`` + the lock-free
MPSC submit ring and the name-bound pool slabs, pinned for BOTH engines:

- a batch reduces bit-identically to the same requests submitted as a
  loop of singles (the acceptance digest check, both engines);
- whole-batch rejections are synchronous: empty batch, unknown op,
  intra-batch duplicate names, C-ABI mixed-op batches;
- duplicate-vs-IN-FLIGHT is deferred: only decidable at the loop's ring
  fold, it fails that handle alone (DuplicateNameError at synchronize)
  while the rest of the batch proceeds;
- a full submit ring falls back to the locked path (correct results,
  ``engine.ring.full`` counts the overflow);
- per-request deadline / cancel semantics hold INSIDE a batch;
- stable names re-hit their pre-bound pool slab (bound_hits, no new
  misses) and the checkout probe limit keeps pool scans bounded.
"""

import ctypes
import hashlib
import threading
import time

import numpy as np
import pytest

from horovod_tpu.core import bufferpool as bpool
from horovod_tpu.core import engine as eng
from horovod_tpu.core import native
from horovod_tpu.core import telemetry as tele
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.native_engine import NativeEngine


class GatedExecutor:
    """Local data plane whose allreduce can be held open — lets a test
    pin work in flight while it publishes batches against the ring."""

    measure_staging = False
    last_stage_s = 0.0
    pool = None
    wire_policy = "none"
    last_wire_bytes = 0
    last_wire_compressed = 0

    def __init__(self, world=8):
        self.world = world
        self.gate = threading.Event()
        self.gate.set()
        self.calls = []

    def allreduce(self, flat, average):
        self.calls.append(flat.size)
        assert self.gate.wait(10.0), "executor gate never released"
        return flat if average else flat * self.world

    def allgather(self, t):
        return np.tile(t, (self.world,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        return t.copy()


def _mk_py(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("timeline", tl.Timeline(None))
    return eng.Engine(executor=executor or GatedExecutor(), **kw)


def _mk_native(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("timeline_path", "")
    return NativeEngine(executor=executor or GatedExecutor(), **kw)


ENGINES = [("python", _mk_py), ("native", _mk_native)]


def _digest(outs):
    return hashlib.sha256(
        b"".join(np.ascontiguousarray(o).tobytes() for o in outs)
    ).hexdigest()


# ---------------------------------------------------------------------------
# batch == loop-of-singles (digest parity, both engines)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_batch_matches_singles_bit_identical(impl, mk):
    tensors = [np.arange(1 + 7 * i, dtype=np.float32) + 0.25
               for i in range(1, 9)]
    e = mk()
    try:
        reqs = [eng.SubmitRequest(f"b/{i}", t, average=False)
                for i, t in enumerate(tensors)]
        hs = e.submit_n("allreduce", reqs)
        batch = _digest([e.synchronize(h) for h in hs])
    finally:
        e.shutdown()
    e = mk()
    try:
        hs = [e.allreduce_async(f"b/{i}", t, average=False)
              for i, t in enumerate(tensors)]
        singles = _digest([e.synchronize(h) for h in hs])
    finally:
        e.shutdown()
    assert batch == singles


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_batch_all_ops_roundtrip(impl, mk):
    """broadcast and allgather ride submit_n too (the state-sync path)."""
    e = mk()
    try:
        hs = e.submit_n("broadcast", [
            eng.SubmitRequest(f"bc/{i}", np.full((3,), float(i)),
                              root_rank=0)
            for i in range(4)])
        for i, h in enumerate(hs):
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((3,), float(i)))
        hs = e.submit_n("allgather", [
            eng.SubmitRequest(f"ag/{i}", np.ones((2,), np.float32))
            for i in range(3)])
        for h in hs:
            assert e.synchronize(h).shape == (16,)
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# synchronous whole-batch rejections
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_batch_synchronous_rejections(impl, mk):
    e = mk()
    try:
        with pytest.raises(eng.EngineError, match="unsupported op"):
            e.submit_n("scatter", [eng.SubmitRequest("x", np.ones(2))])
        with pytest.raises(eng.EngineError, match="at least one"):
            e.submit_n("allreduce", [])
        with pytest.raises(eng.DuplicateNameError,
                           match="appears twice in one batched"):
            e.submit_n("allreduce", [
                eng.SubmitRequest("dup", np.ones(2)),
                eng.SubmitRequest("dup", np.ones(2))])
        # The engine stays fully usable after every rejection.
        hs = e.submit_n("allreduce", [
            eng.SubmitRequest("ok", np.ones((2,), np.float32),
                              average=False)])
        np.testing.assert_allclose(e.synchronize(hs[0]), np.full((2,), 8.0))
    finally:
        e.shutdown()


def test_native_abi_rejects_mixed_op_batch():
    """The C ABI carries per-request op codes; a batch mixing them is
    rejected whole, synchronously (the python surface can't even spell
    this — submit_n takes ONE op — so it's pinned at the ABI)."""
    e = _mk_native()
    try:
        reqs = (native.HvdRequest * 2)()
        tensors = [np.ones((2,), np.float32), np.ones((2,), np.float32)]
        for i, opcode in enumerate((0, 2)):  # allreduce + broadcast
            t = tensors[i]
            reqs[i].op = opcode
            reqs[i].dtype_num = t.dtype.num
            reqs[i].itemsize = t.itemsize
            reqs[i].names = f"mix/{i}".encode()
            reqs[i].data = t.ctypes.data
            reqs[i].out = t.ctypes.data
            reqs[i].count = t.size
            reqs[i].ndim = 1
            reqs[i].shape[0] = t.size
        handles = (ctypes.c_longlong * 2)()
        err = ctypes.create_string_buffer(256)
        rc = e._lib.hvd_engine_enqueue_n(e._ptr, reqs, 2, handles, err)
        assert rc != 0
        assert b"single collective op" in err.value
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# deferred duplicate-vs-in-flight
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_deferred_duplicate_fails_only_that_handle(impl, mk):
    """A batch member whose name is already IN FLIGHT: the verdict only
    exists at the loop's ring fold, so the batch is accepted and that
    handle alone fails — DuplicateNameError at synchronize — while the
    other members reduce normally."""
    ex = GatedExecutor()
    ex.gate.clear()  # hold the first collective in flight
    e = mk(ex)
    try:
        h0 = e.allreduce_async("d", np.ones((4,), np.float32), False)
        deadline = time.monotonic() + 5.0
        while not ex.calls and time.monotonic() < deadline:
            time.sleep(0.002)
        assert ex.calls, "first collective never reached the executor"
        hs = e.submit_n("allreduce", [
            eng.SubmitRequest("d", np.ones((4,), np.float32),
                              average=False),
            eng.SubmitRequest("ok", np.full((4,), 2.0, np.float32),
                              average=False)])
        # Force a fold while 'd' is still pending (any locked call folds
        # the ring; the loop itself is parked inside the executor).
        e.poll(hs[0])
        ex.gate.set()
        np.testing.assert_allclose(e.synchronize(h0), np.full((4,), 8.0))
        np.testing.assert_allclose(e.synchronize(hs[1]),
                                   np.full((4,), 16.0))
        with pytest.raises(eng.DuplicateNameError,
                           match="names must be unique"):
            e.synchronize(hs[0])
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# ring-full fallback (native only: the python twin has no ring)
# ---------------------------------------------------------------------------


def test_ring_full_falls_back_to_locked_path(monkeypatch):
    """With a 2-slot ring and the loop wedged in the executor, a burst
    of batches overflows the ring: the overflow takes the locked
    fallback (fold-first, FIFO preserved), every handle still completes
    correctly, and ``engine.ring.full`` counts the overflow batches."""
    monkeypatch.setenv("HVD_SUBMIT_RING_SIZE", "2")
    ex = GatedExecutor()
    ex.gate.clear()
    e = _mk_native(ex)
    try:
        h0 = e.allreduce_async("w", np.ones((2,), np.float32), False)
        deadline = time.monotonic() + 5.0
        while not ex.calls and time.monotonic() < deadline:
            time.sleep(0.002)
        assert ex.calls, "wedge collective never reached the executor"
        # No waiters now: synchronize/poll would take the lock and fold
        # the ring. 6 publishes into 2 slots -> >=1 locked fallback.
        batches = []
        for b in range(6):
            batches.append(e.submit_n("allreduce", [
                eng.SubmitRequest(f"rb{b}/{i}",
                                  np.full((2,), 1.0 + b, np.float32),
                                  average=False)
                for i in range(3)]))
        st = native.HvdStats()
        e._lib.hvd_engine_get_stats(e._ptr, ctypes.byref(st))
        assert st.ring_full >= 1, st.ring_full
        ex.gate.set()
        np.testing.assert_allclose(e.synchronize(h0), np.full((2,), 8.0))
        for b, hs in enumerate(batches):
            for h in hs:
                np.testing.assert_allclose(
                    e.synchronize(h), np.full((2,), (1.0 + b) * 8.0))
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# per-request deadline / cancel inside a batch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_deadline_and_cancel_are_per_member(impl, mk):
    """One batch: member 0 carries a tight deadline, member 1 gets
    cancelled, member 2 completes — each handle sees only its own
    fate."""
    ex = GatedExecutor()
    ex.gate.clear()  # wedge the loop so the deadline can expire queued
    e = mk(ex, stall_warning_s=0.2)
    try:
        h0 = e.allreduce_async("wedge2", np.ones((2,), np.float32), False)
        deadline = time.monotonic() + 5.0
        while not ex.calls and time.monotonic() < deadline:
            time.sleep(0.002)
        assert ex.calls
        hs = e.submit_n("allreduce", [
            eng.SubmitRequest("m/dl", np.ones((2,), np.float32),
                              average=False, deadline_ms=120),
            eng.SubmitRequest("m/cx", np.ones((2,), np.float32),
                              average=False),
            eng.SubmitRequest("m/ok", np.ones((2,), np.float32),
                              average=False)])
        assert e.cancel(hs[1]) is True
        # The deadline'd waiter fails while the loop is STILL wedged
        # (watchdog-side sweep); the cancelled entry retires at the next
        # live cycle, so the gate opens before its synchronize.
        with pytest.raises(eng.CollectiveTimeout, match="m/dl"):
            e.synchronize(hs[0])
        ex.gate.set()
        with pytest.raises(eng.CancelledError):
            e.synchronize(hs[1])
        np.testing.assert_allclose(e.synchronize(h0), np.full((2,), 8.0))
        np.testing.assert_allclose(e.synchronize(hs[2]),
                                   np.full((2,), 8.0))
    finally:
        ex.gate.set()
        time.sleep(0.05)
        e.shutdown()


# ---------------------------------------------------------------------------
# pre-bound slabs + bounded probing
# ---------------------------------------------------------------------------


def test_snapshot_bound_rebinds_and_hits():
    p = bpool.BufferPool(max_bytes=1 << 20)
    a = np.arange(16, dtype=np.float32)
    s1, tracked = p.snapshot_bound("g/0", a)
    assert tracked
    np.testing.assert_array_equal(s1, a)
    assert p.stats()["bound_hits"] == 0  # first touch binds (a miss)
    del s1
    s2, tracked = p.snapshot_bound("g/0", a + 1)
    assert tracked
    np.testing.assert_array_equal(s2, a + 1)
    assert p.stats()["bound_hits"] == 1  # stable name re-hit its slab
    # A LIVE view of the bound slab forces a fresh (unbound) serve:
    # mutate-after-submit safety can never be traded for the hit.
    s3, _ = p.snapshot_bound("g/0", a + 2)
    assert not np.shares_memory(s2, s3)
    del s2, s3
    # Shape change: rebind (resident accounting swaps the old slab out).
    b = np.ones((64,), np.float64)
    s4, tracked = p.snapshot_bound("g/0", b)
    assert tracked and s4.dtype == np.float64
    del s4


def test_engine_batches_reuse_bound_slabs():
    """Steady-state submit_n with stable names: after the first
    iteration binds, later iterations re-hit their slabs — bound_hits
    climbs and pool misses stay flat (the allocation-free loop)."""
    e = _mk_py(GatedExecutor())
    try:
        names = [f"s/{i}" for i in range(8)]
        ts = [np.full((32,), 1.0, np.float32) for _ in names]

        def it():
            hs = e.submit_n("allreduce", [
                eng.SubmitRequest(nm, t, average=False)
                for nm, t in zip(names, ts)])
            return [e.synchronize(h) for h in hs]

        it()
        misses0 = e.pool.stats()["misses"]
        hits0 = e.pool.stats()["bound_hits"]
        for _ in range(3):
            it()
        st = e.pool.stats()
        assert st["bound_hits"] >= hits0 + 3 * len(names)
        assert st["misses"] == misses0
    finally:
        e.shutdown()


def test_checkout_probe_limit_bounds_scan(monkeypatch):
    """With every slab in the class LIVE, checkout gives up after the
    probe limit (an honest miss) instead of scanning the whole bucket;
    freed slabs are found again within a cursor revolution."""
    monkeypatch.setenv("HVD_POOL_PROBE_LIMIT", "4")
    p = bpool.BufferPool(max_bytes=1 << 22)
    live = [p.checkout(1024, np.float32) for _ in range(12)]
    assert len({v.ctypes.data for v in live}) == 12  # no aliasing, ever
    misses = p.stats()["misses"]
    extra = p.checkout(1024, np.float32)  # all busy: bounded probe, miss
    assert p.stats()["misses"] == misses + 1
    del live, extra
    hits = p.stats()["hits"]
    again = [p.checkout(1024, np.float32) for _ in range(12)]
    assert p.stats()["hits"] > hits  # freed slabs come back into service
    del again


# ---------------------------------------------------------------------------
# batched telemetry
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_submit_batched_counter_counts_members(impl, mk):
    before = tele.REGISTRY.counter("engine.submit.batched").value
    sub_before = tele.REGISTRY.counter("engine.submitted.allreduce").value
    e = mk()
    try:
        hs = e.submit_n("allreduce", [
            eng.SubmitRequest(f"c/{i}", np.ones((2,), np.float32),
                              average=False)
            for i in range(5)])
        for h in hs:
            e.synchronize(h)
    finally:
        e.shutdown()
    assert tele.REGISTRY.counter(
        "engine.submit.batched").value == before + 5
    assert tele.REGISTRY.counter(
        "engine.submitted.allreduce").value == sub_before + 5
