"""Structural pins on the COMPILED collective schedule (VERDICT r3 #8).

Multi-chip hardware is absent on this rig, so the scaling-efficiency
design claims (docs/benchmarks.md "Scaling efficiency") are checkable
only in their compiled form: these tests lower the real programs and
assert on the optimized HLO —

1. hierarchical allreduce lowers to reduce-scatter + all-gather over the
   ICI groups with the cross-tier reduction over the DCN groups (the
   reference's NCCL-RS / MPI-allreduce / NCCL-AG split,
   /root/reference/horovod/common/operations.cc:1194-1346);
2. a fused gradient-pytree allreduce emits at most one collective per
   dtype group (the reference's 64 MB fusion buffer contract,
   operations.cc:2035-2074);
3. growing the world does not change the per-chip allreduce payload
   (the constant-per-chip-volume property ring/tree allreduce scaling
   rests on), and the DCN-crossing payload of the hierarchical form
   shrinks by exactly the ICI group size.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from horovod_tpu.common.compat import shard_map

from horovod_tpu.parallel.hierarchical import hierarchical_allreduce

# Accept both HLO replica-group syntaxes: explicit {{0,1},{2,3}} and the
# iota form [2,2]<=[4] (+ optional transpose suffix).
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[\d,{} ]*\}\}|\[[\d,]+\]<=\[[\d,]+\][^,)\s]*)")


def _collectives(hlo: str, op: str):
    """[(groups_literal, result_shape_literal)] for every ``op`` line."""
    out = []
    for line in hlo.splitlines():
        ls = line.strip()
        # The result can be a bare shape or a tuple (XLA's combiner
        # merges same-group collectives into one variadic op); match the
        # op itself and its async -start form, not the -done wrapper.
        shape_m = re.search(rf"= (\([^)]*\)|\S+) {op}(?:-start)?\(", ls)
        if not shape_m:
            continue
        m = _GROUPS_RE.search(ls)
        out.append((m.group(1) if m else None, shape_m.group(1)))
    return out


def _group_sizes(groups: str):
    """Sizes of the replica groups in either HLO syntax."""
    if groups is None:
        return []
    if groups.startswith("{{"):
        return [len(g.split(",")) for g in re.findall(r"\{([\d, ]+)\}", groups)]
    m = re.match(r"\[(\d+),(\d+)\]<=", groups)
    assert m, groups
    ngroups, per = int(m.group(1)), int(m.group(2))
    return [per] * ngroups


def _mesh2d(outer, inner):
    devs = np.array(jax.devices()[: outer * inner]).reshape(outer, inner)
    return Mesh(devs, ("dcn", "ici"))


def _compile_hier(outer, inner, n=1024):
    mesh = _mesh2d(outer, inner)
    fn = shard_map(lambda x: hierarchical_allreduce(x, "ici", "dcn"),
                   mesh=mesh, in_specs=P(), out_specs=P(),
                   check_vma=False)
    return jax.jit(fn).lower(jnp.ones((n,), jnp.float32)).compile().as_text()


def test_hierarchical_allreduce_lowers_to_rs_dcn_ar_ag():
    hlo = _compile_hier(2, 4)
    rs = _collectives(hlo, "reduce-scatter")
    ag = _collectives(hlo, "all-gather")
    ar = _collectives(hlo, "all-reduce")
    assert len(rs) == 1 and len(ag) == 1 and len(ar) == 1, hlo[-3000:]
    # RS + AG ride the inner tier: 2 groups of 4 (the ICI rows).
    assert sorted(_group_sizes(rs[0][0])) == [4, 4], rs
    assert sorted(_group_sizes(ag[0][0])) == [4, 4], ag
    # The reduction crossing tiers pairs one chip per ICI position over
    # DCN: 4 groups of 2.
    assert sorted(_group_sizes(ar[0][0])) == [2, 2, 2, 2], ar


def test_hierarchical_dcn_payload_is_shard_sized():
    """The DCN-crossing all-reduce must carry 1/inner of the tensor —
    the hierarchical design's entire point (2N/L bytes over the slow
    tier, parallel/hierarchical.py cost model)."""
    n = 1024
    for outer, inner in [(2, 4), (4, 2)]:
        hlo = _compile_hier(outer, inner, n=n)
        (groups, shape), = _collectives(hlo, "all-reduce")
        m = re.match(r"f32\[(\d+)\]", shape)
        assert m, shape
        assert int(m.group(1)) == n // inner, (outer, inner, shape)


def test_flat_allreduce_per_chip_payload_invariant_in_world_size():
    """Doubling the world must not change what each chip reduces: the
    all-reduce operand stays the full gradient shape at any size (the
    scaling table's constant-per-chip-volume premise)."""
    n = 4096
    shapes = {}
    for world in (2, 4, 8):
        mesh = Mesh(np.array(jax.devices()[:world]), ("hvd",))
        fn = shard_map(lambda x: lax.psum(x, "hvd"), mesh=mesh,
                       in_specs=P(), out_specs=P(), check_vma=False)
        hlo = jax.jit(fn).lower(jnp.ones((n,), jnp.float32)).compile().as_text()
        ars = _collectives(hlo, "all-reduce")
        assert len(ars) == 1, hlo[-2000:]
        groups, shape = ars[0]
        assert sum(_group_sizes(groups)) == world
        shapes[world] = shape
    assert len(set(shapes.values())) == 1, shapes
    assert "f32[4096]" in shapes[2], shapes


def test_fused_grad_allreduce_one_collective_per_dtype(hvd):
    """allreduce_pytree over a mixed-dtype gradient tree compiles to at
    most one all-reduce per dtype group — and, with XLA's combiner, at
    least not one per LEAF (8 leaves here)."""
    import horovod_tpu.jax as hvd_jax

    tree = {
        "f32": [jnp.ones((3, 5)), jnp.ones((7,)), jnp.ones((2, 2, 2)),
                jnp.ones((11,)), jnp.ones((4,))],
        "bf16": [jnp.ones((6,), jnp.bfloat16), jnp.ones((3, 3), jnp.bfloat16),
                 jnp.ones((5,), jnp.bfloat16)],
    }

    @hvd_jax.jit(in_specs=(P(),), out_specs=P())
    def reduce_tree(t):
        return hvd_jax.allreduce_pytree(t, average=True)

    hlo = reduce_tree.lower(tree).compile().as_text()
    ars = _collectives(hlo, "all-reduce")
    # One fused buffer per dtype group at most; XLA's combiner may merge
    # the groups further into a single variadic all-reduce (observed on
    # CPU: one op carrying (f32[6], f32[22])) — never one per leaf.
    n_dtypes = 2
    assert 1 <= len(ars) <= n_dtypes, (len(ars), [a[1] for a in ars])
    # Every chip participates in each (world = one group of 8).
    for groups, _ in ars:
        assert sum(_group_sizes(groups)) == 8, groups


def test_flat_vs_hierarchical_same_result(hvd):
    """The two schedules are interchangeable numerically (same devices,
    same order — topology._build_two_tier's invariant)."""
    mesh = _mesh2d(2, 4)
    x = jnp.arange(24.0, dtype=jnp.float32)
    hier = jax.jit(shard_map(
        lambda v: hierarchical_allreduce(v, "ici", "dcn"), mesh=mesh,
        in_specs=P(), out_specs=P(), check_vma=False))(x)
    flat_mesh = Mesh(np.array(jax.devices()), ("hvd",))
    flat = jax.jit(shard_map(
        lambda v: lax.psum(v, "hvd"), mesh=flat_mesh,
        in_specs=P(), out_specs=P(), check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat))
