"""Serving-plane admission control + priority classes (ISSUE 20),
pinned for BOTH engines:

- priority-class resolution (spellings, codes, HVD_PRIORITY default)
  and the per-class budget env grammar;
- admission rejection is SYNCHRONOUS and per class: a class at its
  in-flight or bytes budget rejects new submits with a descriptive
  AdmissionRejected while other classes keep flowing;
- batched submits are all-or-nothing — admission never tears a batch;
- the deadline-aware fast-fail sheds a submit whose remaining deadline
  is under the observed p50 queue(+negotiate) latency, gated on
  SHED_MIN_SAMPLES so a cold engine never sheds;
- the cycle loop drains (priority, deadline-margin, name) ordered;
- quiesce during saturation reports shed-vs-drained separately;
- a cancel storm against a saturated queue leaves the ring/pool
  counters flat (no leaked slots, no ring pressure);
- /healthz grows the ``saturated`` arm + admission body, and the doctor
  classifies a tripped budget as an ``overload`` verdict naming the
  class and budget.
"""

import threading
import time

import numpy as np
import pytest

from horovod_tpu.core import engine as eng
from horovod_tpu.core import telemetry as tele
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.engine import AdmissionRejected
from horovod_tpu.core.native_engine import NativeEngine


class GatedExecutor:
    """Local data plane whose allreduce can be held open — the wedge
    that keeps the queue saturated while admission decisions land."""

    measure_staging = False
    last_stage_s = 0.0
    pool = None
    wire_policy = "none"
    last_wire_bytes = 0
    last_wire_compressed = 0

    def __init__(self, world=8):
        self.world = world
        self.gate = threading.Event()
        self.gate.set()
        self.calls = []  # flat sizes, in executor-call order

    def allreduce(self, flat, average):
        self.calls.append(flat.size)
        assert self.gate.wait(20.0), "executor gate never released"
        return flat if average else flat * self.world

    def allgather(self, t):
        return np.tile(t, (self.world,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        return t.copy()


def _mk_py(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("stall_warning_s", 0.2)
    kw.setdefault("timeline", tl.Timeline(None))
    return eng.Engine(executor=executor or GatedExecutor(), **kw)


def _mk_native(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("stall_warning_s", 0.2)
    kw.setdefault("timeline_path", "")
    return NativeEngine(executor=executor or GatedExecutor(), **kw)


ENGINES = [("python", _mk_py), ("native", _mk_native)]


def _wait(cond, timeout_s=10.0, msg="condition never held"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(msg)


def _counter(e, name):
    if hasattr(e, "_collect_stats"):
        e._collect_stats()  # native: fold the C++ atomics in
    return tele.REGISTRY.counter(name).value


# ---------------------------------------------------------------------------
# resolution + env grammar (pure)
# ---------------------------------------------------------------------------


def test_resolve_priority_spellings():
    assert eng.resolve_priority(None) == eng.PRIORITY_CODES["normal"]
    assert eng.resolve_priority("high") == 0
    assert eng.resolve_priority("NORMAL") == 1
    assert eng.resolve_priority("low") == 2
    assert eng.resolve_priority(0) == 0
    assert eng.resolve_priority(np.int64(2)) == 2
    with pytest.raises(eng.EngineError, match="unknown priority class"):
        eng.resolve_priority("urgent", name="t0")
    with pytest.raises(eng.EngineError, match="t0"):
        eng.resolve_priority(7, name="t0")


def test_priority_from_env(monkeypatch):
    monkeypatch.delenv("HVD_PRIORITY", raising=False)
    monkeypatch.delenv("HOROVOD_PRIORITY", raising=False)
    assert eng.priority_from_env() == eng.PRIORITY_CODES["normal"]
    monkeypatch.setenv("HVD_PRIORITY", "high")
    assert eng.priority_from_env() == 0
    monkeypatch.delenv("HVD_PRIORITY")
    monkeypatch.setenv("HOROVOD_PRIORITY", "low")
    assert eng.priority_from_env() == 2


def test_admission_from_env_per_class_overrides(monkeypatch):
    for v in ("HVD_ADMISSION_MAX_INFLIGHT", "HVD_ADMISSION_MAX_BYTES"):
        for c in ("", "_HIGH", "_NORMAL", "_LOW"):
            monkeypatch.delenv(v + c, raising=False)
    mi, mb = eng.admission_from_env()
    assert mi == [0, 0, 0] and mb == [0, 0, 0]  # 0 = unbounded
    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT", "16")
    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT_LOW", "2")
    monkeypatch.setenv("HVD_ADMISSION_MAX_BYTES_HIGH", "1048576")
    mi, mb = eng.admission_from_env()
    assert mi == [16, 16, 2]  # ordered like PRIORITY_CLASSES
    assert mb == [1048576, 0, 0]


# ---------------------------------------------------------------------------
# per-class budgets (both engines)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_env_default_priority_applies_to_submits(impl, mk, monkeypatch):
    """HVD_PRIORITY classifies submits that pass priority=None; the
    per-class in-flight accounting (admission_summary) proves which
    class the entry landed in."""
    monkeypatch.setenv("HVD_PRIORITY", "high")
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        h = e.allreduce_async("envdft", np.ones(4, np.float32), False)
        _wait(lambda: e.admission_summary()
              ["classes"]["high"]["inflight"] == 1,
              msg="high-class in-flight never reached 1")
        assert e.admission_summary()["classes"]["normal"]["inflight"] == 0
        ex.gate.set()
        e.synchronize(h)
    finally:
        ex.gate.set()
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_inflight_budget_rejects_only_that_class(impl, mk, monkeypatch):
    """A class at its in-flight budget rejects synchronously with the
    class + budget named; other classes keep flowing; the counter and
    the saturated/tripped summary tell the same story."""
    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT_LOW", "2")
    ex = GatedExecutor()
    e = mk(ex)
    try:
        before = _counter(e, "engine.admission.rejected")
        ex.gate.clear()
        hs = [e.allreduce_async(f"low.{k}", np.ones(4, np.float32),
                                False, priority="low") for k in range(2)]
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 2)
        with pytest.raises(AdmissionRejected) as ei:
            e.allreduce_async("low.over", np.ones(4, np.float32), False,
                              priority="low")
        msg = str(ei.value)
        assert "'low'" in msg and "HVD_ADMISSION_MAX_INFLIGHT" in msg
        assert _counter(e, "engine.admission.rejected") == before + 1
        summary = e.admission_summary()
        assert summary["saturated"] == ["low"]
        assert summary["tripped"] == {"cls": "low",
                                      "budget": "max_inflight"}
        # High class is not governed by the low budget.
        hh = e.allreduce_async("hi.ok", np.ones(4, np.float32), False,
                               priority="high")
        ex.gate.set()
        for h in hs + [hh]:
            e.synchronize(h)
        # Budget slots free on completion: the class admits again.
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 0)
        h2 = e.allreduce_async("low.again", np.ones(4, np.float32),
                               False, priority="low")
        e.synchronize(h2)
    finally:
        ex.gate.set()
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_bytes_budget_rejects(impl, mk, monkeypatch):
    monkeypatch.setenv("HVD_ADMISSION_MAX_BYTES_NORMAL", "1024")
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        h = e.allreduce_async("nb.small", np.ones(64, np.float32), False)
        _wait(lambda: e.admission_summary()
              ["classes"]["normal"]["inflight"] == 1)
        with pytest.raises(AdmissionRejected, match="bytes budget"):
            e.allreduce_async("nb.big", np.ones(512, np.float32), False)
        ex.gate.set()
        e.synchronize(h)
    finally:
        ex.gate.set()
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_batched_submit_is_all_or_nothing(impl, mk, monkeypatch):
    """A batch that would push its class over budget rejects WHOLE at
    the submit boundary: no member handle exists, nothing is admitted,
    and a batch that fits afterwards goes through — admission never
    tears a fused batch."""
    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT_LOW", "2")
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        reqs = [eng.SubmitRequest(f"batch.{k}", np.ones(4, np.float32),
                                  average=False, priority="low")
                for k in range(3)]
        with pytest.raises(AdmissionRejected, match="never tears"):
            e.submit_n("allreduce", reqs)
        assert e.admission_summary()["classes"]["low"]["inflight"] == 0
        ok = e.submit_n("allreduce", reqs[:2])
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 2)
        ex.gate.set()
        for h in ok:
            e.synchronize(h)
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# deadline-aware shed
# ---------------------------------------------------------------------------


def test_shed_python_gated_on_min_samples(monkeypatch):
    """The fast-fail sheds only once SHED_MIN_SAMPLES queue-phase
    observations exist; then a submit whose deadline is under the p50
    is shed synchronously, counted in engine.admission.shed."""
    tele.REGISTRY.reset()  # own the process-global phase histograms
    try:
        assert eng.queue_latency_estimate() is None  # cold: never sheds
        h = tele.REGISTRY.histogram("engine.phase.queue")
        for _ in range(eng.SHED_MIN_SAMPLES):
            h.observe(0.4)
        est = eng.queue_latency_estimate()
        assert est is not None and est > 0.05
        ex = GatedExecutor()
        e = _mk_py(ex)
        try:
            before = tele.REGISTRY.counter("engine.admission.shed").value
            with pytest.raises(AdmissionRejected,
                               match="engine.admission.shed"):
                e.allreduce_async("shed.me", np.ones(4, np.float32),
                                  False, deadline_ms=20)
            assert tele.REGISTRY.counter(
                "engine.admission.shed").value == before + 1
            # A deadline with margin above the estimate is admitted.
            h2 = e.allreduce_async("keep.me", np.ones(4, np.float32),
                                   False, deadline_ms=30000)
            e.synchronize(h2)
            # No deadline = never shed, regardless of the estimate.
            h3 = e.allreduce_async("nodl", np.ones(4, np.float32), False)
            e.synchronize(h3)
        finally:
            e.shutdown()
    finally:
        tele.REGISTRY.reset()  # drop the synthetic 400 ms samples


def test_shed_native_from_observed_queue_residency():
    """The C++ engine sheds from ITS OWN phase histogram: after >=8
    entries observed ~300 ms of queue residency, a 20 ms-deadline
    submit is shed with the same message vocabulary."""
    ex = GatedExecutor()
    e = _mk_native(ex)
    try:
        ex.gate.clear()
        hs = [e.allreduce_async(f"warm.{k}", np.ones(4, np.float32),
                                False) for k in range(10)]
        time.sleep(0.35)  # queue residency the histogram will observe
        ex.gate.set()
        for h in hs:
            e.synchronize(h)
        before = _counter(e, "engine.admission.shed")
        with pytest.raises(AdmissionRejected,
                           match="engine.admission.shed"):
            e.allreduce_async("shed.me", np.ones(4, np.float32), False,
                              deadline_ms=20)
        assert _counter(e, "engine.admission.shed") == before + 1
        h2 = e.allreduce_async("keep.me", np.ones(4, np.float32), False,
                               deadline_ms=30000)
        e.synchronize(h2)
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# drain order
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_drain_order_priority_margin_name(impl, mk):
    """A saturated cycle drains (priority, deadline-margin, name)
    ordered: high first, tighter deadlines first within a class, names
    last for determinism. Distinct dtypes keep same-class entries out
    of one fused batch so the executor call order is observable."""
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        h0 = e.allreduce_async("blk", np.ones(5, np.float32), False,
                               priority="low")
        _wait(lambda: len(ex.calls) == 1, msg="blocker never executed")
        order = [
            # (name, size, dtype, priority, deadline_ms)
            ("z2.low", 9, np.float64, "low", None),
            ("na.norm", 17, np.float32, "normal", 9000),
            ("hi", 11, np.float32, "high", None),
            ("nb.norm", 31, np.float64, "normal", 5000),
            ("z1.low", 7, np.float32, "low", None),
        ]
        hs = [e.allreduce_async(n, np.ones(sz, dt), False, priority=p,
                                deadline_ms=dl)
              for n, sz, dt, p, dl in order]
        ex.gate.set()
        for h in hs:
            e.synchronize(h)
        e.synchronize(h0)
        # call 0 = blocker; then: high(11), normal margin 5s (31),
        # normal margin 9s (17), low by name z1(7) then z2(9).
        assert ex.calls == [5, 11, 31, 17, 7, 9], ex.calls
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# quiesce vs saturation: shed-vs-drained reported separately
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_quiesce_saturated_reports_shed_separately(impl, mk):
    """Quiesce against a saturated queue: work retired WITHOUT
    completing inside the drain window (a cooperative cancel here) is
    reported as ``shed``, separate from the names that actually
    drained; completed waiters still deliver."""
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        hb = e.allreduce_async("q.blk", np.ones(4, np.float32), False,
                               priority="high")
        _wait(lambda: len(ex.calls) == 1, msg="blocker never executed")
        hh = e.allreduce_async("q.hi", np.ones(4, np.float32), False,
                               priority="high")
        hl = e.allreduce_async("q.low", np.ones(4, np.float32), False,
                               priority="low")
        hc = e.allreduce_async("q.cancel", np.ones(4, np.float32),
                               False, priority="low")

        def mid_drain():
            time.sleep(0.15)
            e.cancel(hc)  # retired without completing -> shed
            time.sleep(0.15)
            ex.gate.set()

        t = threading.Thread(target=mid_drain)
        t.start()
        report = e.quiesce(10.0, reason="saturated drain")
        t.join()
        assert report["still_pending"] == [], report
        assert report["shed"] == 1, report
        assert {"q.blk", "q.hi", "q.low"} <= set(report["drained"])
        for h in (hb, hh, hl):
            e.synchronize(h)
        with pytest.raises(eng.CancelledError):
            e.synchronize(hc)
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# cancel storm against a saturated queue: counters stay flat
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_cancel_storm_leaves_ring_pool_counters_flat(impl, mk,
                                                     monkeypatch):
    """Two identical storm rounds against a class at budget: every
    admission slot frees, the submit-ring pressure counters do not
    move, and pool residency reaches steady state after round one (a
    leak would grow it every round)."""
    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT_LOW", "3")
    ex = GatedExecutor()
    e = mk(ex)

    def storm_round(tag):
        ex.gate.clear()
        hb = e.allreduce_async(f"{tag}.blk", np.ones(64, np.float32),
                               False, priority="high")
        _wait(lambda: e.admission_summary()
              ["classes"]["high"]["inflight"] == 1)
        hs = [e.allreduce_async(f"{tag}.{k}", np.ones(32, np.float32),
                                False, priority="low")
              for k in range(3)]
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 3)
        with pytest.raises(AdmissionRejected):
            e.allreduce_async(f"{tag}.over", np.ones(32, np.float32),
                              False, priority="low")
        for _ in range(3):  # the storm: repeated + bogus cancels
            for h in hs:
                e.cancel(h)
        assert e.cancel(987654) is False
        ex.gate.set()
        for h in hs:
            with pytest.raises(eng.CancelledError):
                e.synchronize(h)
        e.synchronize(hb)
        _wait(lambda: all(
            c["inflight"] == 0
            for c in e.admission_summary()["classes"].values()),
            msg="admission slots never freed after the storm")

    try:
        storm_round("s1")
        ring0 = (_counter(e, "engine.ring.full"),
                 _counter(e, "engine.ring.spins"))
        resident1 = tele.REGISTRY.gauge(
            "engine.pool.bytes_resident").value
        storm_round("s2")
        ring1 = (_counter(e, "engine.ring.full"),
                 _counter(e, "engine.ring.spins"))
        resident2 = tele.REGISTRY.gauge(
            "engine.pool.bytes_resident").value
        assert ring1 == ring0, (ring0, ring1)
        assert resident2 == resident1, (resident1, resident2)
        assert e.admission_summary()["queue_depth"] == 0
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# /healthz + doctor surfaces
# ---------------------------------------------------------------------------


def test_healthz_saturated_arm_and_admission_body(monkeypatch):
    """A tripped class flips /healthz to ``saturated`` (non-200 via
    telemetry_http's not-ok/init rule) and the body carries the
    admission state; it returns to ok once the budget frees."""
    from horovod_tpu.core import sentinel

    monkeypatch.setenv("HVD_ADMISSION_MAX_INFLIGHT_LOW", "1")
    ex = GatedExecutor()
    e = _mk_py(ex)
    saved = eng._engine
    eng._engine = e  # health reads the singleton
    sentinel.note_draining(None)  # an earlier quiesce test leaves the marker
    try:
        ex.gate.clear()
        h = e.allreduce_async("hz.low", np.ones(4, np.float32), False,
                              priority="low")
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 1)
        body = sentinel.health()
        assert body["status"] == "saturated"
        assert body["admission"]["saturated"] == ["low"]
        assert body["admission"]["queue_depth"] >= 1
        assert body["admission"]["classes"]["low"]["max_inflight"] == 1
        ex.gate.set()
        e.synchronize(h)
        _wait(lambda: e.admission_summary()
              ["classes"]["low"]["inflight"] == 0)
        assert sentinel.health()["status"] != "saturated"
    finally:
        ex.gate.set()
        e.shutdown()
        eng._engine = saved


def test_doctor_overload_verdict_names_class_and_budget(monkeypatch):
    """A snapshot whose admission state reports a tripped budget
    classifies as an ``overload`` finding naming the class, budget and
    rank."""
    from horovod_tpu.core import doctor

    snap = {
        "v": 1, "rank": 2, "nproc": 4, "wall": time.time(),
        "generation": 0, "epoch": 0, "kind": "stall", "reason": "x",
        "entries": [], "draining": None, "kv_failovers": 0,
        "exec_median_us": None,
        "admission": eng.build_admission_summary(
            7, [0, 1, 5], [0, 64, 4096], [0, 0, 5], [0, 0, 0]),
    }
    v = doctor.classify([snap], nproc=4)
    over = [f for f in v["findings"] if f["kind"] == "overload"]
    assert len(over) == 1, v
    f = over[0]
    assert f["ranks"] == [2]
    assert "'low'" in f["detail"] and "max_inflight" in f["detail"]
    assert "queue depth 7" in f["detail"]
