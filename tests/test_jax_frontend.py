"""JAX frontend tests (reference: test/test_tensorflow.py — allreduce
average/compression/grads — and the DistributedOptimizer train-step tests in
test/test_keras.py:41-108)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hj
from horovod_tpu.jax import Compression


@pytest.fixture(autouse=True)
def _init(hvd):
    pass


def test_allreduce_fp16_compression():
    x = jnp.linspace(-1, 1, 16, dtype=jnp.float32)
    out = hj.allreduce(x, average=True, compression=Compression.fp16)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-3)


def test_allreduce_bf16_compression():
    x = jnp.linspace(-1, 1, 16, dtype=jnp.float32)
    out = hj.allreduce(x, average=False, compression=Compression.bf16)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * hj.size(), atol=0.1)


def test_sparse_allreduce():
    sparse = pytest.importorskip("jax.experimental.sparse")
    dense = jnp.zeros((6, 3)).at[1].set(2.0).at[4].set(-1.0)
    x = sparse.BCOO.fromdense(dense, nse=6)
    out = hj.allreduce(x, average=False)
    np.testing.assert_allclose(np.asarray(out.todense()), np.asarray(dense) * hj.size())
    out_avg = hj.allreduce(x, average=True)
    np.testing.assert_allclose(np.asarray(out_avg.todense()), np.asarray(dense), rtol=1e-6)
    out_dense = hj.allreduce(x, average=False, sparse_as_dense=True)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(dense) * hj.size())


def test_broadcast_parameters_and_optimizer_state():
    params = {"w": jnp.arange(4.0), "b": jnp.ones(())}
    out = hj.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.arange(4.0))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    state2 = hj.broadcast_optimizer_state(state, root_rank=0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        state, state2,
    )


def test_broadcast_object():
    obj = {"epoch": 7, "name": "resnet"}
    assert hj.broadcast_object(obj, root_rank=0) == obj


def _toy_data(n=64):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 2).astype(np.float32)
    Y = X @ np.array([3.0, -1.0], np.float32) + 0.7
    return jnp.asarray(X), jnp.asarray(Y)


def _loss_fn(p, x, y):
    pred = x @ p["w"] + p["b"]
    return jnp.mean((pred - y) ** 2)


def test_distributed_optimizer_spmd_matches_full_batch():
    """DP (per-rank shards + averaged grads) must equal full-batch SGD —
    the fundamental data-parallel correctness invariant."""
    X, Y = _toy_data()
    params0 = {"w": jnp.zeros(2), "b": jnp.zeros(())}
    opt = hj.DistributedOptimizer(optax.sgd(0.1))

    def step(p, s, x, y):
        g = jax.grad(_loss_fn)(p, x, y)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s

    sharded_step = hj.jit(
        step, in_specs=(P(), P(), P("hvd", None), P("hvd")), out_specs=(P(), P())
    )
    p, s = params0, opt.init(params0)
    for _ in range(50):
        p, s = sharded_step(p, s, X, Y)

    # Reference: plain optax on the full batch.
    ref_opt = optax.sgd(0.1)
    rp, rs = params0, ref_opt.init(params0)
    for _ in range(50):
        g = jax.grad(_loss_fn)(rp, X, Y)
        up, rs = ref_opt.update(g, rs, rp)
        rp = optax.apply_updates(rp, up)

    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(rp["w"]), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(p["b"]), np.asarray(rp["b"]), rtol=1e-4)


def test_distributed_optimizer_eager():
    X, Y = _toy_data()
    params = {"w": jnp.zeros(2), "b": jnp.zeros(())}
    opt = hj.DistributedOptimizer(optax.sgd(0.1))
    state = opt.init(params)
    for _ in range(100):
        g = jax.grad(_loss_fn)(params, X, Y)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
    assert float(_loss_fn(params, X, Y)) < 1e-3


def test_backward_passes_per_step_accumulates():
    params = {"w": jnp.ones(2)}
    opt = hj.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=2)
    state = opt.init(params)
    g = {"w": jnp.ones(2)}
    updates, state = opt.update(g, state, params)
    # First micro-step: no update applied yet.
    np.testing.assert_allclose(np.asarray(updates["w"]), np.zeros(2))
    updates, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.1 * np.ones(2), rtol=1e-6)


def test_grad_and_value_and_grad_wrappers():
    X, Y = _toy_data(16)
    params = {"w": jnp.zeros(2), "b": jnp.zeros(())}
    g1 = hj.grad(_loss_fn)(params, X, Y)
    v, g2 = hj.value_and_grad(_loss_fn)(params, X, Y)
    ref = jax.grad(_loss_fn)(params, X, Y)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(ref["w"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g2["w"]), np.asarray(ref["w"]), rtol=1e-5)
    assert float(v) == pytest.approx(float(_loss_fn(params, X, Y)))


def test_gradient_through_spmd_collective():
    """Autodiff through the in-step collective: d/dx sum(pmean(x)) == 1/size
    per element per rank, summed over ranks' outputs == 1 (reference
    gradient tests: test_tensorflow.py:321-346)."""
    n = hvd_size = hj.size()
    xs = jnp.arange(n * 2, dtype=jnp.float32).reshape(n, 2)

    def loss(x):
        r = hj.allreduce(x, average=True)
        return jnp.sum(r)

    f = hj.jit(
        lambda x: jax.grad(loss)(x), in_specs=P("hvd", None), out_specs=P("hvd", None)
    )
    g = f(xs)
    # pmean's VJP is psum(ct)/n (the reference registers allreduce's gradient
    # as allreduce — tensorflow/mpi_ops.py:94-105): every rank's unit
    # cotangent flows to every rank's x with weight 1/n, summed over n ranks.
    # hj.fetch: g is rank-sharded; in a multi-controller world plain
    # np.asarray cannot fetch non-addressable shards.
    np.testing.assert_allclose(hj.fetch(g), np.ones((n, 2)), rtol=1e-6)


def _mixed_tree(seed=0):
    """Pytree mixing dtypes/shapes, like a real model's params."""
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 5)
    return {
        "dense": {"kernel": jax.random.normal(ks[0], (8, 16), jnp.float32),
                  "bias": jax.random.normal(ks[1], (16,), jnp.float32)},
        "embed": jax.random.normal(ks[2], (32, 4), jnp.bfloat16),
        "scale": jax.random.normal(ks[3], (4,), jnp.float32),
        # Above the fuse() threshold: exercises the per-tensor passthrough
        # beside the packed buffers.
        "wide": jax.random.normal(ks[4], (512, 9), jnp.float32),
    }


@pytest.mark.parametrize("make_opt", [
    lambda: optax.sgd(0.05, momentum=0.9),
    lambda: optax.adam(1e-2),
    lambda: optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.1)),
], ids=["sgd_momentum", "adam", "global_clip_sgd"])
def test_fused_update_matches_unfused(make_opt):
    """hj.fuse() collapses per-parameter updates into per-dtype buffers
    without changing the math for elementwise transforms (and global-norm
    clipping, which is global either way). 5 steps, mixed f32/bf16 tree."""
    params_f = _mixed_tree()
    params_u = _mixed_tree()
    fused, plain = hj.fuse(make_opt()), make_opt()
    sf, su = fused.init(params_f), plain.init(params_u)
    for step in range(5):
        grads = jax.tree.map(
            lambda p: jnp.asarray(
                np.random.RandomState(step).normal(size=p.shape), p.dtype),
            params_u)
        uf, sf = fused.update(grads, sf, params_f)
        uu, su = plain.update(grads, su, params_u)
        params_f = optax.apply_updates(params_f, uf)
        params_u = optax.apply_updates(params_u, uu)
    for a, b in zip(jax.tree.leaves(params_f), jax.tree.leaves(params_u)):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=2e-6)


@pytest.mark.parametrize("fused", [False, True], ids=["plain", "fused"])
def test_state_storage_policy_on_unsharded_paths(fused):
    """state_dtype='bf16' on the fused/plain (master-less) paths
    (HBM diet round 2, satellite): the optimizer state is *stored* bf16
    between steps — every non-scalar float buffer — while the update
    math runs f32; updates come back at the PARAM width and the
    trajectory tracks the f32 oracle within bf16 storage rounding."""
    params_p = _mixed_tree()
    params_u = _mixed_tree()
    mk = lambda: optax.adam(1e-2)
    policy = (hj.fuse(mk(), state_dtype="bf16") if fused
              else hj.state_storage(mk(), "bf16"))
    plain = mk()
    sp, su = policy.init(params_p), plain.init(params_u)
    # Storage layout: non-scalar float state (m/v, packed or not) lives
    # in bf16; the count scalar stays exact.
    bufs = [l for l in jax.tree.leaves(sp)
            if hasattr(l, "dtype") and jnp.ndim(l) >= 1
            and jnp.issubdtype(l.dtype, jnp.floating)]
    assert bufs and all(b.dtype == jnp.bfloat16 for b in bufs), [
        b.dtype for b in bufs]
    for step in range(3):
        grads = jax.tree.map(
            lambda p: jnp.asarray(
                np.random.RandomState(step).normal(size=p.shape), p.dtype),
            params_u)
        up, sp = policy.update(grads, sp, params_p)
        uu, su = plain.update(grads, su, params_u)
        for a, b in zip(jax.tree.leaves(up), jax.tree.leaves(params_p)):
            assert a.dtype == b.dtype, "updates must arrive at param width"
        params_p = optax.apply_updates(params_p, up)
        params_u = optax.apply_updates(params_u, uu)
    for a, b in zip(jax.tree.leaves(params_p), jax.tree.leaves(params_u)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-2, atol=2e-2)


def test_state_storage_identity_when_off():
    """state_dtype=None/'f32' is the identity wrapper — same state
    dtypes, same trajectory object-for-object semantics."""
    opt = hj.state_storage(optax.adam(1e-2), None)
    params = _mixed_tree()
    s = opt.init(params)
    bufs = [l for l in jax.tree.leaves(s)
            if hasattr(l, "dtype") and jnp.ndim(l) >= 1]
    # No downcast: m/v mirror the param dtypes (f32 stays f32).
    assert any(b.dtype == jnp.float32 for b in bufs)
    assert hj.canonical_state_dtype("f32") is None
    assert hj.canonical_state_dtype("bf16") == jnp.bfloat16


def test_distributed_optimizer_fused_update_spmd(hvd):
    """fused_update=True inside the compiled SPMD step gives the same
    trajectory as the default path (the profile-driven fast path for
    bench.py; VERDICT r3 item 1)."""
    xs, ys = _toy_data()
    n = hj.size()

    def run(fused):
        opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                      fused_update=fused)
        p = {"w": jnp.ones((2,)), "b": jnp.zeros(())}
        s = opt.init(p)

        @hj.jit(in_specs=(P(), P(), P("hvd", None), P("hvd")),
                out_specs=(P(), P()))
        def step(p, s, x, y):
            g = jax.grad(_loss_fn)(p, x, y)
            u, s2 = opt.update(g, s, p)
            return optax.apply_updates(p, u), s2

        for _ in range(3):
            p, s = step(p, s, xs, ys)
        return p

    pf, pu = run(True), run(False)
    for a, b in zip(jax.tree.leaves(pf), jax.tree.leaves(pu)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
