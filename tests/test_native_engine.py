"""The C++ engine (libhvdcore) must show the same observable behavior as the
Python reference engine in test_engine.py — same fusion, error, duplicate-
name, shutdown and timeline semantics (reference behaviors:
operations.cc:265-268, 2035-2074, 1535-1581, 1833-1848; timeline.cc)."""

import json
import time

import numpy as np
import pytest

from horovod_tpu.core import engine as eng
from horovod_tpu.core.native_engine import NativeEngine


class RecordingExecutor:
    def __init__(self, world=8, delay=0.0):
        self.world = world
        self.delay = delay
        self.calls = []

    def allreduce(self, flat, average):
        if self.delay:
            time.sleep(self.delay)
        self.calls.append(("allreduce", flat.size, average))
        return flat if average else flat * self.world

    def allgather(self, t):
        self.calls.append(("allgather", t.size, None))
        return np.tile(t, (self.world,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        self.calls.append(("broadcast", t.size, root))
        return t + 100.0 if t.dtype.kind == "f" else t


def _mk(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("timeline_path", "")
    return NativeEngine(executor=executor or RecordingExecutor(), **kw)


def test_roundtrip_all_ops():
    e = _mk()
    try:
        h = e.allreduce_async("r", np.ones((4,), np.float32), average=False)
        np.testing.assert_allclose(e.synchronize(h), np.full((4,), 8.0))
        h = e.allgather_async("g", np.arange(6, np.int64).reshape(2, 3)
                              if False else
                              np.arange(6, dtype=np.int64).reshape(2, 3))
        out = e.synchronize(h)
        assert out.shape == (16, 3) and out.dtype == np.int64
        h = e.broadcast_async("b", np.zeros((3,), np.float64), 2)
        np.testing.assert_allclose(e.synchronize(h), np.full((3,), 100.0))
    finally:
        e.shutdown()


def test_dtype_roundtrip_exact():
    """64-bit payloads must round-trip bit-exactly through the C buffer."""
    e = _mk()
    try:
        x = np.array([1.5e300, -2.5e-300, 3.141592653589793], np.float64)
        h = e.broadcast_async("f64", x, 0)
        np.testing.assert_array_equal(e.synchronize(h), x + 100.0)
        xi = np.array([2**62, -(2**61), 7], np.int64)
        h = e.allreduce_async("i64", xi, average=True)
        np.testing.assert_array_equal(e.synchronize(h), xi)
    finally:
        e.shutdown()


def test_poll_then_synchronize():
    e = _mk()
    try:
        h = e.allreduce_async("t", np.ones((2,), np.float32), average=True)
        deadline = time.monotonic() + 2
        while not e.poll(h):
            assert time.monotonic() < deadline
            time.sleep(0.001)
        np.testing.assert_allclose(e.synchronize(h), np.ones((2,)))
    finally:
        e.shutdown()


def test_duplicate_name_rejected():
    ex = RecordingExecutor(delay=0.05)
    e = _mk(ex, cycle_time_s=0.001)
    try:
        h1 = e.allreduce_async("same", np.ones((2,), np.float32), False)
        with pytest.raises(eng.DuplicateNameError):
            e.allreduce_async("same", np.ones((2,), np.float32), False)
        e.synchronize(h1)
        h2 = e.allreduce_async("same", np.ones((2,), np.float32), False)
        e.synchronize(h2)
    finally:
        e.shutdown()


def test_fusion_batches_same_dtype():
    """Deterministic fusion pin (this assertion used to race the loop
    thread: with a zero-delay executor the cycle drains entries one by
    one exactly as fast as the test enqueues them, so whether ANY two
    landed in a cycle together was a coin flip under scheduler jitter).
    Gating the FIRST execution until every handle is submitted forces
    the remaining entries into one drained cycle — they MUST fuse."""
    import threading

    gate = threading.Event()

    class GatedExecutor(RecordingExecutor):
        def allreduce(self, flat, average):
            if not self.calls:
                gate.wait(timeout=10)
            return super().allreduce(flat, average)

    ex = GatedExecutor()
    e = _mk(ex, cycle_time_s=0.002)
    try:
        handles = [
            e.allreduce_async(f"t{i}", np.full((8,), float(i), np.float32),
                              False)
            for i in range(16)
        ]
        gate.set()
        for i, h in enumerate(handles):
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((8,), 8.0 * i))
        ar = [c for c in ex.calls if c[0] == "allreduce"]
        assert len(ar) < 16, f"no fusion: {len(ar)} calls"
        assert max(n for _, n, _ in ar) > 8, f"no fused batch: {ar}"
    finally:
        e.shutdown()


def test_fusion_respects_threshold():
    ex = RecordingExecutor()
    e = _mk(ex, cycle_time_s=0.05, fusion_threshold=8 * 4)
    try:
        time.sleep(0.06)
        handles = [
            e.allreduce_async(f"t{i}", np.ones((8,), np.float32), False)
            for i in range(4)
        ]
        for h in handles:
            e.synchronize(h)
        ar = [c for c in ex.calls if c[0] == "allreduce"]
        assert all(c[1] <= 8 for c in ar)
    finally:
        e.shutdown()


def test_mixed_dtypes_not_fused():
    ex = RecordingExecutor()
    e = _mk(ex, cycle_time_s=0.05)
    try:
        time.sleep(0.06)
        h1 = e.allreduce_async("f", np.ones((4,), np.float32), False)
        h2 = e.allreduce_async("i", np.ones((4,), np.int32), False)
        e.synchronize(h1)
        e.synchronize(h2)
        ar = [c for c in ex.calls if c[0] == "allreduce"]
        assert len(ar) == 2
    finally:
        e.shutdown()


def test_prescale_applied():
    ex = RecordingExecutor()
    e = _mk(ex)
    try:
        h = e.allreduce_async("p", np.ones((4,), np.float32), False,
                              prescale=0.5)
        np.testing.assert_allclose(e.synchronize(h), np.full((4,), 4.0))
    finally:
        e.shutdown()


def test_executor_error_surfaces():
    class Boom(RecordingExecutor):
        def allreduce(self, flat, average):
            raise RuntimeError("wire fell out")

    e = _mk(Boom())
    try:
        h = e.allreduce_async("t", np.ones((2,), np.float32), False)
        with pytest.raises(eng.EngineError, match="wire fell out"):
            e.synchronize(h)
        # The engine survives an executor error.
        h = e.broadcast_async("u", np.ones((2,), np.float32), 0)
        e.synchronize(h)
    finally:
        e.shutdown()


def test_unknown_handle():
    e = _mk()
    try:
        with pytest.raises(eng.EngineError):
            e.poll(12345)
        with pytest.raises(eng.EngineError):
            e.synchronize(12345)
    finally:
        e.shutdown()


def test_enqueue_after_shutdown_raises():
    e = _mk()
    e.shutdown()
    with pytest.raises(eng.ShutdownError):
        e.allreduce_async("t", np.ones((2,), np.float32), False)


def test_stall_warning_printed(capfd):
    class Slow(RecordingExecutor):
        def allreduce(self, flat, average):
            time.sleep(0.5)
            return flat

    e = NativeEngine(executor=Slow(), cycle_time_s=0.001,
                     stall_warning_s=0.05, timeline_path="")
    try:
        e.allreduce_async("stuck_tensor", np.ones((2,), np.float32), False)
        time.sleep(0.3)
        err = capfd.readouterr().err
        assert "stuck_tensor" in err and "WARNING" in err
    finally:
        e.shutdown()


def test_timeline_written(tmp_path):
    path = tmp_path / "native_timeline.json"
    e = _mk(timeline_path=str(path))
    h = e.allreduce_async("tensor_a", np.ones((4,), np.float32), False)
    e.synchronize(h)
    h = e.broadcast_async("tensor_b", np.ones((4,), np.float32), 0)
    e.synchronize(h)
    e.shutdown()
    events = json.loads(path.read_text())
    names = {ev.get("name") for ev in events}
    assert {"ALLREDUCE", "BROADCAST", "QUEUE"} <= names
    lanes = {ev["args"]["name"] for ev in events
             if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    assert {"tensor_a", "tensor_b"} <= lanes
    # Distributed tracing: the clock mapping rides every trace.
    assert any(ev.get("name") == "HVD_CLOCK" for ev in events)
