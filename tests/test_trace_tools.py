"""Tier-1 tests for the distributed-tracing surface (ISSUE 3): the
trace-analysis CLI over a checked-in synthetic two-rank capture (one
rank truncated mid-event — pins the truncation-tolerant reader), the
always-on flight recorder of BOTH engines, clock-anchor exchange, the
``now_us`` disabled-timeline fix, and ``stats --watch live``."""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

DATA = os.path.join(os.path.dirname(__file__), "data", "trace_tworank")


# ---------------------------------------------------------------------------
# Truncation-tolerant reader + merge/skew over the checked-in capture
# ---------------------------------------------------------------------------


def test_truncated_rank_file_still_loads():
    """rank1's file is cut mid-event (no closing bracket, a dangling
    half-written line) — the reader must recover every complete event."""
    from horovod_tpu.utils import trace

    events = trace.load_events(os.path.join(DATA, "timeline.rank1.json"))
    assert events, "truncated file yielded nothing"
    # The half-written trailing event is dropped; the last COMPLETE one
    # (rank 1's second self RANK_READY) survives.
    assert events[-1]["name"] == "RANK_READY"
    assert events[-1]["ts"] == 249800
    names = {ev["name"] for ev in events}
    assert {"QUEUE", "NEGOTIATE_ALLREDUCE", "RANK_READY"} <= names


def test_merge_aligns_ranks_on_common_base(tmp_path):
    """pid = rank, tid = tensor lane, and the two ranks' NEGOTIATE spans
    for the same tensor overlap once mapped through HVD_CLOCK."""
    from horovod_tpu.utils import trace

    out = str(tmp_path / "merged.json")
    info = trace.merge(DATA, out=out)
    assert info["files"] == 2 and info["ranks"] == [0, 1]
    merged = json.load(open(out))
    procs = {ev["pid"]: ev["args"]["name"] for ev in merged
             if ev.get("name") == "process_name"}
    assert procs == {0: "rank 0", 1: "rank 1"}
    lanes = {(ev["pid"], ev["tid"]): ev["args"]["name"] for ev in merged
             if ev.get("name") == "thread_name"}
    assert lanes[(0, 1)] == lanes[(1, 1)] == "grad/0"
    spans = {}
    stack = {}
    for ev in merged:
        if ev.get("name") != "NEGOTIATE_ALLREDUCE":
            continue
        key = ev["pid"]
        if ev["ph"] == "B":
            stack.setdefault(key, []).append(ev["ts"])
        elif ev["ph"] == "E" and stack.get(key):
            spans.setdefault(key, []).append((stack[key].pop(), ev["ts"]))
    (b0, e0) = sorted(spans[0])[0]
    (b1, e1) = sorted(spans[1])[0]
    assert b0 < e1 and b1 < e0, (spans[0], spans[1])  # overlap
    # The fixture's clocks: rank0 base 999501100, rank1's first
    # NEGOTIATE begins inside rank0's span on the common base.
    assert b0 < b1 < e0


def test_skew_blames_late_rank_with_exact_waits():
    from horovod_tpu.utils import trace

    d = trace.skew_data(DATA)
    assert d["ranks"] == [0, 1]
    assert d["instances"] == 2  # paired self-announcements per rank
    # Fixture arithmetic: rank1 late by 99500 us then 49600 us.
    assert d["wait_us"] == {0: 0, 1: 149100}
    assert d["late_count"][1] == 2
    assert d["worst"]["rank"] == 1 and d["worst"]["skew_us"] == 99500
    assert d["per_tensor"]["grad/0"]["worst_rank"] == 1
    # The clock metadata (and its error bound) is surfaced.
    assert d["clock"][1]["rtt_us"] == 900


def test_skew_cross_checks_telemetry_prom(tmp_path, capsys):
    from horovod_tpu.utils import trace

    prom = tmp_path / "tele.prom"
    prom.write_text(
        "# TYPE hvd_straggler_wait_microseconds counter\n"
        'hvd_straggler_wait_microseconds{process="0"} 120\n'
        'hvd_straggler_wait_microseconds{process="1"} 150000\n')
    assert trace.parse_straggler_prom(str(prom)) == {0: 120, 1: 150000}
    report = trace.skew_report(DATA, prom=str(prom))
    assert "process 1: imposed wait 0.149 s" in report
    assert "telemetry straggler report: 0.150 s" in report


def test_critical_path_and_stats_over_capture():
    from horovod_tpu.utils import trace

    d = trace.critical_path_data(DATA)
    # rank0 has 2 complete QUEUE instances, rank1 has 1 (the truncated
    # second instance has no QUEUE end and is dropped).
    assert d["instances"] == 3
    assert d["phase_us"]["NEGOTIATE"] > 0
    assert d["phase_us"]["COLLECTIVE"] == 7000 + 500  # the two allreduces
    assert abs(sum(d["shares"].values()) - 1.0) < 1e-9
    assert d["slowest"][0]["total_us"] >= d["slowest"][-1]["total_us"]

    s = trace.stats_data(DATA)
    assert set(s["ranks"]) == {0, 1}
    assert s["ranks"][0]["counts"]["RANK_READY"] == 4
    assert s["ranks"][1]["clock"]["rank"] == 1


def test_negotiate_cached_vs_full_attribution():
    """The `cached` arg the engines stamp on NEGOTIATE span ends (the
    response-cache fast path, ISSUE 4) is attributed by both
    critical-path and skew: the fixture has two full rounds (rank0's
    first, rank1's only complete one) and one cached round."""
    from horovod_tpu.utils import trace

    d = trace.critical_path_data(DATA)
    neg = d["negotiate"]
    assert neg["cached"]["count"] == 1
    assert neg["cached"]["us"] == 250500 - 200100
    assert neg["cached"]["median_us"] == 50400
    assert neg["full"]["count"] == 2
    # rank0: 102000-1100, rank1: 101500-100600.
    assert neg["full"]["us"] == 100900 + 900
    assert neg["full"]["median_us"] == 100900
    assert neg["unknown"]["count"] == 0
    report = trace.critical_path_report(DATA)
    assert "negotiate rounds (response cache)" in report
    assert "cached n=1" in report and "full n=2" in report

    sk = trace.skew_data(DATA)
    assert sk["negotiate_rounds"][0] == {"cached": 1, "full": 1}
    assert sk["negotiate_rounds"][1] == {"cached": 0, "full": 1}
    assert "[negotiate spans: 1 cached / 1 full]" in trace.skew_report(DATA)


def test_wire_route_attribution():
    """Collective spans split by wire route from the `wire`/`wire_dcn`
    args the engines stamp at span START: the fixture's first allreduce
    is full-width, the second rode the hierarchical per-tier route."""
    from horovod_tpu.utils import trace

    d = trace.critical_path_data(DATA)
    w = d["wire"]
    assert w["flat"]["count"] == 1 and w["flat"]["us"] == 7000
    assert w["two_tier"]["count"] == 1 and w["two_tier"]["us"] == 500
    assert w["quantized"]["count"] == 0
    report = trace.critical_path_report(DATA)
    assert "collective spans (wire route)" in report
    assert "two_tier n=1" in report and "flat n=1" in report


def test_trace_cli_subcommands(tmp_path, capsys):
    from horovod_tpu.utils import trace

    out = str(tmp_path / "m.json")
    assert trace.main(["merge", DATA, "-o", out]) == 0
    assert "2 rank file(s)" in capsys.readouterr().out
    assert json.load(open(out))

    assert trace.main(["skew", DATA]) == 0
    text = capsys.readouterr().out
    assert "process 1: imposed wait 0.149 s" in text
    assert "skew error bound" in text

    assert trace.main(["skew", DATA, "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["wait_us"]["1"] == 149100

    assert trace.main(["critical-path", DATA]) == 0
    assert "phase shares" in capsys.readouterr().out
    assert trace.main(["stats", DATA]) == 0
    assert "rank 0" in capsys.readouterr().out

    assert trace.main(["merge", str(tmp_path / "nonexistent")]) == 1
    # Re-analyzing merge's own output would silently double-rebase the
    # timestamps — refused with directions instead.
    assert trace.main(["skew", out]) == 1
    assert "MERGED trace" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# now_us satellite: the disabled timeline returns the real clock
# ---------------------------------------------------------------------------


def test_now_us_returns_real_clock_when_disabled(hvd):
    """A caller computing retro-span boundaries from now_us() must never
    receive 0 from a disabled timeline (a timeline enabled mid-run would
    then emit zero/negative timestamps). Both writers."""
    from horovod_tpu.core.timeline import Timeline

    t = Timeline(None)
    a = t.now_us()
    time.sleep(0.01)
    b = t.now_us()
    assert b > a >= 0
    t2 = Timeline(None)
    # Two disabled timelines tick the same clock family (monotonic).
    assert t2.now_us() >= 0

    from horovod_tpu.core.native_engine import NativeEngine

    e = NativeEngine(timeline_path="")
    n1 = int(e._lib.hvd_engine_timeline_now(e._ptr))
    time.sleep(0.01)
    n2 = int(e._lib.hvd_engine_timeline_now(e._ptr))
    assert n2 > n1 >= 0
    e.shutdown()


# ---------------------------------------------------------------------------
# Flight recorder: both engines, identical event names, no file needed
# ---------------------------------------------------------------------------


def _flight_ops(engine):
    engine.synchronize(
        engine.allreduce_async("f/a", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allgather_async("f/g", np.ones((2, 3), np.float32)))
    engine.synchronize(
        engine.broadcast_async("f/c", np.ones((5,), np.float32), 0))


def test_flight_recorder_parity_without_timeline_file(hvd):
    """The acceptance contract: the last-N events are recorded by BOTH
    engine implementations under identical event names, with no
    HVD_TIMELINE set."""
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    e_py = Engine(timeline=Timeline(None))
    _flight_ops(e_py)
    py_events = e_py.timeline.recent()
    e_py.shutdown()

    e_cpp = NativeEngine(timeline_path="")
    _flight_ops(e_cpp)
    cpp_events = e_cpp.recent_events()
    e_cpp.shutdown()

    py_names = {ev["name"] for ev in py_events}
    cpp_names = {ev["name"] for ev in cpp_events}
    assert py_names == cpp_names, py_names ^ cpp_names
    assert {"QUEUE", "WAIT_FOR_DATA", "ALLREDUCE", "ALLGATHER",
            "BROADCAST", "HVD_CLOCK"} <= py_names
    # Same (tensor, activity, phase) coverage for the span events.
    def shape(evs):
        return {(ev.get("tensor"), ev["name"], ev["ph"]) for ev in evs
                if ev["ph"] in ("B", "E")}
    assert shape(py_events) == shape(cpp_events)


def test_flight_dump_loadable_and_carries_telemetry(hvd, tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core import timeline as tl

    events = [{"name": "QUEUE", "ph": "B", "ts": 1, "tensor": "t"}]
    path = tl.dump_flight_recorder(events, "unit test", rank=3)
    assert path and os.path.dirname(path) == str(tmp_path)
    dump = json.load(open(path))
    assert dump["rank"] == 3 and dump["reason"] == "unit test"
    assert dump["events"] == events
    assert "telemetry" in dump and "straggler" in dump
    # The trace CLI accepts dump files wherever a trace file goes.
    from horovod_tpu.utils import trace

    assert trace.load_events(path) == events


def test_sigusr1_dumps_flight_recorder(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.timeline import Timeline

    e = Engine(timeline=Timeline(None))
    try:
        e.synchronize(
            e.allreduce_async("s/x", np.ones((2,), np.float32), False))
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [f for f in os.listdir(tmp_path)
                     if f.startswith("hvd_flight.rank")
                     and f.endswith(".json")]
            time.sleep(0.01)
        assert dumps, os.listdir(tmp_path)
        dump = json.load(open(tmp_path / dumps[0]))
        assert dump["reason"] == "SIGUSR1"
        assert any(ev["name"] == "ALLREDUCE" for ev in dump["events"])
    finally:
        e.shutdown()
    # Shutdown unregisters the dumper: the module global must not pin a
    # dead engine, and a later SIGUSR1 must not dump its stale ring.
    from horovod_tpu.core import timeline as tl

    assert tl._sigusr1_dump is None


def test_stall_warning_dumps_flight_recorder(hvd, tmp_path, monkeypatch):
    """A stalled tensor leaves a post-mortem (_check_stalls dump)."""
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.timeline import Timeline

    gate = threading.Event()

    class Plug:
        def allreduce(self, flat, average):
            gate.wait(10.0)
            return flat.copy()

    e = Engine(executor=Plug(), stall_warning_s=0.05,
               timeline=Timeline(None))
    try:
        h = e.allreduce_async("stuck", np.ones((2,), np.float32), False)
        deadline = time.monotonic() + 5.0
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [f for f in os.listdir(tmp_path)
                     if f.startswith("hvd_flight.rank")
                     and f.endswith(".json")]
            time.sleep(0.02)
        assert dumps, "no stall dump written"
        dump = json.load(open(tmp_path / dumps[0]))
        assert "stalled" in dump["reason"] and "stuck" in dump["reason"]
    finally:
        gate.set()
        e.synchronize(h)
        e.shutdown()


def test_native_stall_dump_written(hvd, tmp_path, monkeypatch):
    """Stall-dump parity: the C++ engine's python-side watchdog dumps
    when in-flight work stops progressing (the twin of the python
    engine's _check_stalls dump)."""
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core.native_engine import NativeEngine

    gate = threading.Event()

    class Plug:
        def allreduce(self, flat, average):
            gate.wait(15.0)
            return flat.copy()

    e = NativeEngine(executor=Plug(), stall_warning_s=0.2,
                     timeline_path="")
    try:
        h = e.allreduce_async("stuck", np.ones((2,), np.float32), False)
        deadline = time.monotonic() + 8.0
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [f for f in os.listdir(tmp_path)
                     if f.startswith("hvd_flight.rank")
                     and f.endswith(".json")]
            time.sleep(0.05)
        assert dumps, "no native stall dump written"
        dump = json.load(open(tmp_path / dumps[0]))
        assert "stalled" in dump["reason"], dump["reason"]
        assert any(ev["name"] == "QUEUE" for ev in dump["events"])
    finally:
        gate.set()
        e.synchronize(h)
        e.shutdown()


def test_skew_over_flight_dump_directory(tmp_path, monkeypatch):
    """The documented post-mortem recipe: a dir of hvd_flight.rank*.json
    dumps (no timeline files) is analyzable by the CLI — the newest
    dump per rank stands in for the rank's trace."""
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.utils import trace

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    for rank, (epoch, self_ts) in enumerate(
            [(1000000000, 1000), (1000200000, 101000)]):
        events = [
            {"name": "HVD_CLOCK", "ph": "M", "ts": 0,
             "args": {"rank": rank, "epoch_wall_us": epoch,
                      "offset_us": 500000 + rank * 200000}},
            {"name": "NEGOTIATE_ALLREDUCE", "ph": "B", "ts": self_ts - 100,
             "tensor": "g"},
            {"name": "RANK_READY", "ph": "i", "ts": self_ts, "tensor": "g",
             "args": {"process": rank}},
        ]
        tl.dump_flight_recorder(events, "test", rank=rank)
    d = trace.skew_data(str(tmp_path))
    # Common base: rank0 self at 999501000, rank1 at 999601000.
    assert d["instances"] == 1
    assert d["wait_us"] == {0: 0, 1: 100000}
    assert d["late_count"][1] == 1


# ---------------------------------------------------------------------------
# Clock-anchor exchange (unit, LocalKV)
# ---------------------------------------------------------------------------


def test_clock_anchor_exchange_over_localkv():
    """Both coordinators converge on rank 0's wall↔monotonic bridge with
    a finite measured KV round trip — the merge tool's common base."""
    from horovod_tpu.core.coordinator import Coordinator, LocalKV

    store = {}
    coords = {}
    errors = []

    def worker(pid):
        c = Coordinator(LocalKV(store), 2, pid, 0.005, 0, timeout_s=10.0)
        coords[pid] = c
        try:
            for _ in range(3):  # sync converges within a round or two
                c.negotiate([])
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    assert not errors
    c0, c1 = coords[0], coords[1]
    assert c0.clock_ready and c1.clock_ready
    assert c0.clock_rtt_us >= 0 and c1.clock_rtt_us >= 0
    # Same process ⇒ identical wall/monotonic clocks ⇒ the bridges agree
    # to well under a second; rank 1 adopted rank 0's exactly.
    assert c1.clock_offset_us == c0.clock_offset_us
    # close() queues the clock keys as residue for the next generation.
    c0.close()
    from horovod_tpu.core import coordinator as coord

    with coord._residue_lock:
        assert any(k.endswith("/clock/p0") for _, k in coord._residue)
        coord._residue[:] = [e for e in coord._residue
                             if e[0] != c0.ns]  # leave no cross-test junk
    c1.close()
    with coord._residue_lock:
        coord._residue[:] = [e for e in coord._residue if e[0] != c1.ns]


def test_timeline_clock_sync_reemits_metadata(tmp_path):
    from horovod_tpu.core.timeline import Timeline

    path = str(tmp_path / "tl.json")
    t = Timeline(path, rank=2)
    t.start("x", "QUEUE")
    t.clock_sync(123456, 789)
    t.end("x", "QUEUE")
    t.close()
    events = json.load(open(path))
    clocks = [ev for ev in events if ev.get("name") == "HVD_CLOCK"]
    assert len(clocks) == 2  # open-time + post-exchange
    last = clocks[-1]["args"]
    assert last == {"rank": 2, "epoch_wall_us": t.epoch_wall_us,
                    "offset_us": 123456, "rtt_us": 789}
    # The merge tool uses the LAST one.
    from horovod_tpu.utils.trace import RankTrace

    rt = RankTrace(path)
    assert rt.clock["offset_us"] == 123456 and rt.rank == 2


def test_timeline_legacy_file_paths_stay_file_mode(tmp_path):
    """An existing plain file (the reference allowed arbitrary trace
    filenames, e.g. HOROVOD_TIMELINE=/tmp/hvd.trace) must never be
    classified as a directory — makedirs on it would crash engine
    init."""
    from horovod_tpu.core import timeline as tl

    legacy = tmp_path / "hvd.trace"
    legacy.write_text("[\n")
    assert not tl.is_dir_mode(str(legacy))
    assert tl.resolve_timeline_path(str(legacy), rank=0) == str(legacy)
    # Nonexistent non-.json path: dir mode (the documented rule).
    assert tl.is_dir_mode(str(tmp_path / "traces"))


def test_sigusr1_chains_the_application_handler(monkeypatch, tmp_path):
    """The dump handler must be additive: an application handler
    installed before hvd (e.g. SLURM preemption checkpointing) still
    runs on SIGUSR1."""
    from horovod_tpu.core import timeline as tl

    dumped, chained = [], []
    monkeypatch.setattr(tl, "_sigusr1_dump", dumped.append)
    monkeypatch.setattr(tl, "_sigusr1_prev",
                        lambda signum, frame: chained.append(signum))
    tl._on_sigusr1(signal.SIGUSR1, None)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not dumped:
        time.sleep(0.01)
    assert dumped == ["SIGUSR1"]
    assert chained == [signal.SIGUSR1]


def test_timeline_dir_mode_resolves_per_rank(tmp_path, monkeypatch):
    from horovod_tpu.core import timeline as tl

    d = str(tmp_path / "traces")
    monkeypatch.setenv("HVD_TIMELINE", d)
    monkeypatch.setenv("HVD_PROCESS_ID", "5")
    # Process index comes from topology once initialized; the hvd
    # fixture may have run, so force the env path by asking explicitly.
    assert tl.resolve_timeline_path(d, rank=5) == \
        os.path.join(d, "timeline.rank5.json")
    assert os.path.isdir(d)
    # The single-file spelling is untouched.
    f = str(tmp_path / "single.json")
    assert tl.resolve_timeline_path(f, rank=5) == f


# ---------------------------------------------------------------------------
# stats --watch live (satellite)
# ---------------------------------------------------------------------------


def test_stats_live_watch_redraws_and_exits_cleanly(monkeypatch, capsys):
    from horovod_tpu.utils import stats

    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        if len(sleeps) >= 2:
            raise KeyboardInterrupt  # the user's Ctrl-C

    monkeypatch.setattr(stats.time, "sleep", fake_sleep)
    assert stats.main(["live", "--watch", "0.5"]) == 0
    out = capsys.readouterr().out
    # Redrawn once per sleep: at least two reports before the interrupt.
    assert out.count("\n\n") >= 1 or len(out.splitlines()) >= 2
    assert sleeps == [0.5, 0.5]
