"""TSan smoke worker: drive the native engine hard under ThreadSanitizer.

Spawned by tests/test_analysis.py (opt-in HVD_SLOW_TESTS tier) with
``LD_PRELOAD=<libtsan>`` and ``HVD_SANITIZE=thread`` so load_library
picks the instrumented ``libhvdcore.tsan.so``. The executor is pure
numpy — no jax backend initialization, no devices — which keeps the run
about the ENGINE's concurrency: multi-threaded submits, fusion batches,
donated buffers, waiter wakeups, stats reads, and shutdown-drain, all
racing the C++ loop/watchdog threads. Any "WARNING: ThreadSanitizer"
line in our output fails the smoke.
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class LocalExecutor:
    """Identity 'collective' data plane (world of one, no jax)."""

    measure_staging = False
    last_stage_s = 0.0
    pool = None
    wire_policy = "none"
    last_wire_bytes = 0
    last_wire_compressed = 0

    def allreduce(self, flat, average):
        self.last_wire_bytes = flat.nbytes
        return flat * 1.0

    def allgather(self, t):
        self.last_wire_bytes = t.nbytes
        return np.concatenate([t, t])

    def broadcast(self, t, root_rank):
        self.last_wire_bytes = t.nbytes
        return t * 1.0


def submitter(engine, tid, steps, errors):
    try:
        for i in range(steps):
            handles = [
                engine.allreduce_async(f"t{tid}.g{i}.{j}",
                                       np.full(513, float(j), np.float32),
                                       average=True)
                for j in range(4)
            ]
            donated = np.arange(256, dtype=np.float32)
            handles.append(engine.allreduce_async(
                f"t{tid}.d{i}", donated, average=False, donate=True))
            handles.append(engine.allgather_async(
                f"t{tid}.ag{i}", np.arange(16, dtype=np.int32)))
            handles.append(engine.broadcast_async(
                f"t{tid}.bc{i}", np.zeros(64, np.float32), 0))
            for h in handles:
                engine.synchronize(h)
    except Exception as exc:  # pragma: no cover - failure path
        errors.append(f"thread {tid}: {exc!r}")


def ring_hammer(engine, tid, steps, errors):
    """Batched-submit producer: CAS-publish into the MPSC submit ring
    from several threads at once, against a ring sized small enough that
    the ring-full locked fallback also gets exercised."""
    from horovod_tpu.core import engine as eng

    try:
        for i in range(steps):
            reqs = [eng.SubmitRequest(f"r{tid}.b{i}.{j}",
                                      np.full(97, float(j + 1), np.float32),
                                      average=False)
                    for j in range(6)]
            handles = engine.submit_n("allreduce", reqs)
            for h in handles:
                engine.synchronize(h)
    except Exception as exc:  # pragma: no cover - failure path
        errors.append(f"ring thread {tid}: {exc!r}")


def main():
    from horovod_tpu.core.native_engine import NativeEngine

    engine = NativeEngine(executor=LocalExecutor(), cycle_time_s=0.002,
                          stall_warning_s=0.0)
    errors: list = []
    threads = [threading.Thread(target=submitter,
                                args=(engine, t, 25, errors))
               for t in range(3)]
    for t in threads:
        t.start()
    # Concurrent readers: stats + params churn while submits fly.
    for _ in range(50):
        engine.current_params()
        engine.set_params(cycle_time_s=0.002)
    for t in threads:
        t.join()
    engine.shutdown()

    # Phase 2: the lock-free submit ring under multi-producer pressure.
    # An 8-slot ring with 4 producers × 6-request batches guarantees both
    # the CAS publish path and the ring-full locked fallback run, racing
    # the loop thread's fold-on-mu_-entry consumer.
    os.environ["HVD_SUBMIT_RING_SIZE"] = "8"
    ring_engine = NativeEngine(executor=LocalExecutor(), cycle_time_s=0.002,
                               stall_warning_s=0.0)
    ring_threads = [threading.Thread(target=ring_hammer,
                                     args=(ring_engine, t, 20, errors))
                    for t in range(4)]
    for t in ring_threads:
        t.start()
    for _ in range(50):
        ring_engine._collect_stats()
    for t in ring_threads:
        t.join()
    ring_engine.shutdown()

    if errors:
        print("\n".join(errors))
        return 1
    print("TSAN_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
