"""Full dtype × dimensionality sweep — the reference's value-test matrix.

The reference sweeps every MPIDataType across 1-3D tensors on every rank
(test_torch.py:56-119 test_horovod_allreduce over
[torch.IntTensor, ..., torch.cuda.HalfTensor] × dims [1,2,3];
test_tensorflow.py:56-119 likewise). Here the wire table is
core/native_engine.py::_DTYPES (the MPIDataType role,
common/mpi_message.h:26-37); this file pins that every entry — including
bf16 via ml_dtypes, bool, and the complex pair the reference never had —
round-trips every engine verb correctly, and that each frontend's dtype
surface does the same.
"""

import numpy as np
import pytest

from horovod_tpu.core import engine as eng
from horovod_tpu.core.native_engine import _DTYPES


def _world_size(hvd):
    return hvd.size()


def _fill(shape, dtype, value):
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return np.ones(shape, np.bool_)
    if dt.kind == "c":
        # A nonzero imaginary part, so corruption of either component
        # fails the exact-equality asserts below.
        return np.full(shape, value * (1 + 2j), dtype)
    return (np.ones(shape) * value).astype(dtype)


@pytest.mark.parametrize("dtype", _DTYPES, ids=str)
@pytest.mark.parametrize("dim", [1, 2, 3])
def test_engine_allreduce_every_wire_dtype(hvd, dtype, dim):
    e = eng.get_engine()
    shape = (4,) * dim
    x = _fill(shape, dtype, 1)
    out = e.synchronize(
        e.allreduce_async(f"mat/ar/{dtype}/{dim}", x, average=False))
    assert out.dtype == dtype and out.shape == shape
    n = _world_size(hvd)
    if np.dtype(dtype) == np.bool_:
        # Summing bools saturates at True (the reference reduces bools
        # with MPI_SUM on uint8 storage; saturation is the TPU analogue).
        assert bool(np.asarray(out).ravel()[0])
    else:
        # Exact equality in the ORIGINAL dtype: keeps both complex
        # components under test (a float64 cast would drop imag).
        np.testing.assert_array_equal(
            np.asarray(out), _fill(shape, dtype, n))


@pytest.mark.parametrize("dtype", _DTYPES, ids=str)
def test_engine_allgather_and_broadcast_every_wire_dtype(hvd, dtype):
    e = eng.get_engine()
    n = _world_size(hvd)
    x = _fill((2, 3), dtype, 1)
    g = e.synchronize(e.allgather_async(f"mat/ag/{dtype}", x))
    assert g.dtype == dtype and g.shape == (2 * n, 3)
    b = e.synchronize(e.broadcast_async(f"mat/bc/{dtype}", x, 0))
    assert b.dtype == dtype
    np.testing.assert_array_equal(np.asarray(b), np.asarray(x))


_TORCH_DTYPES = ["uint8", "int8", "int16", "int32", "int64",
                 "float16", "bfloat16", "float32", "float64"]


@pytest.mark.parametrize("name", _TORCH_DTYPES)
@pytest.mark.parametrize("dim", [1, 3])
def test_torch_allreduce_dtype_matrix(hvd, name, dim):
    """The reference's test_horovod_allreduce type sweep through the
    torch API (test_torch.py:56-86)."""
    torch = pytest.importorskip("torch")
    import horovod_tpu.torch as hvt

    hvt.init()
    dtype = getattr(torch, name)
    x = torch.ones((3,) * dim, dtype=dtype)
    out = hvt.allreduce(x, average=False, name=f"mat/t/{name}/{dim}")
    assert out.dtype == dtype and out.shape == x.shape
    n = _world_size(hvd)
    assert float(out.reshape(-1)[0]) == float(n)


_JAX_DTYPES = ["float32", "bfloat16", "float16", "int32", "uint32"]


@pytest.mark.parametrize("name", _JAX_DTYPES)
def test_jax_eager_allreduce_dtype_matrix(hvd, name):
    """Eager (compiled shard_map) path across the jax dtype surface."""
    import jax.numpy as jnp

    x = jnp.ones((4, 2), getattr(jnp, name))
    out = hvd.allreduce(x, average=False)
    assert out.dtype == x.dtype
    n = _world_size(hvd)
    np.testing.assert_array_equal(
        np.asarray(out).astype(np.float64), np.full((4, 2), float(n)))
