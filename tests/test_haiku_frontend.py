"""haiku frontend: a transformed model trains through the distributed
optimizer on the mesh."""

import numpy as np
import pytest

hk = pytest.importorskip("haiku")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu.haiku as hvd_hk  # noqa: E402


def test_haiku_training_loop(hvd):
    def net(x):
        return hk.Sequential([hk.Linear(16), jax.nn.relu, hk.Linear(1)])(x)

    model = hk.without_apply_rng(hk.transform(net))
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x @ rng.randn(4, 1)).astype(np.float32)

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:8]))
    params = hvd_hk.broadcast_parameters(params)
    opt = hvd_hk.DistributedOptimizer(optax.adam(1e-2))
    opt_state = opt.init(params)

    def loss_fn(p, xb, yb):
        return jnp.mean((model.apply(p, xb) - yb) ** 2)

    @hvd_hk.jit(in_specs=(P(), P(), P(hvd_hk.HVD_AXIS), P(hvd_hk.HVD_AXIS)),
                out_specs=(P(), P(), P()))
    def step(p, s, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, hvd_hk.allreduce(loss)

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2


def test_haiku_state_broadcast(hvd):
    state = {"bn": {"mean": jnp.ones((4,)), "var": jnp.zeros((4,))}}
    out = hvd_hk.broadcast_state(state)
    np.testing.assert_allclose(out["bn"]["mean"], np.ones(4))


def test_haiku_average_state(hvd):
    """average_state must compute the TRUE cross-chip mean of
    per-replica BN statistics: the training arrays claim replication
    while chips disagree (check_vma=False), so a host-side fetch would
    silently read one chip's values — construct exactly that divergent
    state and require the real average (plus integer dtype round-trip)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = hvd.mesh()

    def divergent(per_chip_value):
        shards = [jax.device_put(np.asarray(per_chip_value(i)), d)
                  for i, d in enumerate(mesh.devices.flat)]
        return jax.make_array_from_single_device_arrays(
            shards[0].shape, NamedSharding(mesh, P()), shards)

    n = hvd.size()
    state = {"bn": {
        "mean": divergent(lambda i: np.full((3,), float(i), np.float32)),
        "counter": divergent(lambda i: np.asarray([10 * i], np.int32)),
    }}
    out = hvd_hk.average_state(state)
    expect = (n - 1) / 2.0  # mean of 0..n-1
    np.testing.assert_allclose(np.asarray(out["bn"]["mean"]),
                               np.full(3, expect), rtol=1e-6)
    assert np.asarray(out["bn"]["counter"]).dtype == np.int32
    np.testing.assert_array_equal(np.asarray(out["bn"]["counter"]),
                                  [int(10 * expect)])
