"""Torch frontend tests (reference: test/test_torch.py — allreduce variants
:68-224, grads :351-403/:523-565/:700-733, DistributedOptimizer, state
broadcast :734-935)."""

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvt
from horovod_tpu.torch import Compression


@pytest.fixture(autouse=True)
def _init(hvd):
    hvt.init()


def test_allreduce_sum_and_average():
    x = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    out = hvt.allreduce(x, average=False)
    np.testing.assert_allclose(out.numpy(), x.numpy() * hvt.size())
    out = hvt.allreduce(x, average=True)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)
    # Input not modified (reference: mpi_ops.py allreduce docstring).
    np.testing.assert_allclose(x.numpy(), np.arange(12, dtype=np.float32).reshape(3, 4))


def test_allreduce_inplace():
    x = torch.ones(5)
    out = hvt.allreduce_(x, average=False)
    assert out is x
    np.testing.assert_allclose(x.numpy(), np.full((5,), float(hvt.size())))


def test_allreduce_inplace_donate():
    """PR 13 follow-up: the in-place variants take the donation path —
    the engine references the tensor's host buffer in place (read-only)
    and the reduced result is written back at synchronize, AFTER the
    engine dropped its reference. Same read-only/frozen-view contract
    as the out-of-place donate."""
    x = torch.arange(8, dtype=torch.float32) + 1.0
    out = hvt.allreduce_(x, average=False, donate=True)
    assert out is x
    np.testing.assert_allclose(
        x.numpy(), (np.arange(8, dtype=np.float32) + 1.0) * hvt.size())
    # The buffer is usable (writable) again after completion: a second
    # round through the same tensor must work.
    out = hvt.allreduce_(x, average=True)
    assert out is x


def test_allreduce_async_inplace_donate_poll():
    from horovod_tpu.torch import mpi_ops

    x = torch.full((6,), 2.0)
    h = mpi_ops.allreduce_async_(x, average=False, donate=True)
    out = mpi_ops.synchronize(h)
    assert out is x
    np.testing.assert_allclose(x.numpy(),
                               np.full((6,), 2.0 * hvt.size()))


def test_broadcast_inplace_donate():
    x = torch.arange(5, dtype=torch.float32)
    out = hvt.broadcast_(x, 0, donate=True)
    assert out is x
    np.testing.assert_allclose(x.numpy(), np.arange(5, dtype=np.float32))


def test_allreduce_async_poll_synchronize():
    x = torch.ones(4)
    h = hvt.allreduce_async(x, average=False)
    import time

    deadline = time.monotonic() + 5
    while not hvt.poll(h):
        assert time.monotonic() < deadline
        time.sleep(0.001)
    out = hvt.synchronize(h)
    np.testing.assert_allclose(out.numpy(), np.full((4,), float(hvt.size())))


def test_allreduce_fp16_compression():
    x = torch.linspace(-1, 1, 16)
    out = hvt.allreduce(x, average=True, compression=Compression.fp16)
    assert out.dtype == torch.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-3)


def test_allreduce_int8_engine_wire():
    """Quantized policy (ISSUE 12): block-scaled int8 in the engine's
    execution chunks; the torch surface accepts the class/name, and the
    per-tensor select() container routes by name."""
    x = torch.linspace(-2.0, 2.0, 600)
    out = hvt.allreduce(x, average=True, compression=Compression.int8)
    assert out.dtype == torch.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=2.0 / 127)
    # Name-based override: 'bn*' stays full-width, everything else int8.
    sel = Compression.select("int8", **{"bn*": "none"})
    exact = hvt.allreduce(x, average=True, name="bn.gamma",
                          compression=sel)
    np.testing.assert_allclose(exact.numpy(), x.numpy(), atol=1e-6)


def test_compression_unknown_name_fails_fast_naming_rank():
    """Satellite pin: a bad compressor fails at resolution with rank
    attribution, not as an attribute error mid-step."""
    with pytest.raises(ValueError, match="rank|pid"):
        Compression.resolve("int9")
    with pytest.raises(ValueError, match="rank|pid"):
        hvt.DistributedOptimizer(
            torch.optim.SGD([torch.nn.Parameter(torch.ones(3))], lr=0.1),
            compression="bogus")


def test_allreduce_bf16_tensor():
    x = torch.ones(8, dtype=torch.bfloat16)
    out = hvt.allreduce(x, average=False)
    assert out.dtype == torch.bfloat16
    np.testing.assert_allclose(out.float().numpy(), np.full((8,), float(hvt.size())))


def test_allreduce_grad():
    """Gradient of allreduce is allreduce (reference: test_torch.py:351-403)."""
    x = torch.ones(4, requires_grad=True)
    out = hvt.allreduce(x, average=False)
    out.sum().backward()
    # backward: allreduce(ones, average=False) == ones * size
    np.testing.assert_allclose(x.grad.numpy(), np.full((4,), float(hvt.size())))


def test_allreduce_grad_average_and_cotangent():
    """Reference grad oracle with non-uniform upstream cotangents
    (test_torch.py:351-403 multiplies by a random tensor before summing):
    the registered backward is itself an allreduce of the cotangent, so
    d/dx sum(allreduce(x) * c) = allreduce(c)."""
    c = torch.arange(1.0, 5.0)
    x = torch.ones(4, requires_grad=True)
    (hvt.allreduce(x, average=False) * c).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               c.numpy() * hvt.size(), rtol=1e-6)
    # average=True: backward averages the cotangent over ranks, so with
    # every rank contributing c the gradient is exactly c.
    x2 = torch.ones(4, requires_grad=True)
    (hvt.allreduce(x2, average=True) * c).sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), c.numpy(), rtol=1e-6)


def test_allgather_grad_cotangent_slices():
    """Backward of allgather slices the cotangent: each rank receives the
    gradient rows of ITS contribution (reference: test_torch.py:523-565,
    mpi_ops.py HorovodAllgather backward)."""
    n = hvt.size()
    x = torch.ones(2, 3, requires_grad=True)
    out = hvt.allgather(x)  # (2n, 3): rank r's rows at [2r, 2r+2)
    w = torch.arange(1.0, 2 * n * 3 + 1).reshape(2 * n, 3)
    (out * w).sum().backward()
    # Backward = allreduce(cotangent, SUM) then take THIS rank's rows:
    # every rank contributes w, so rank r's slice is w[2r:2r+2] * size
    # (rank-aware like the reference's multi-rank runs, test_torch.py:
    # 523-565 under mpirun).
    r = hvt.rank()
    np.testing.assert_allclose(x.grad.numpy(),
                               w[2 * r: 2 * r + 2].numpy() * n)


def test_broadcast_grad_average_path():
    c = torch.tensor([2.0, 0.5, 4.0])
    x = torch.ones(3, requires_grad=True)
    (hvt.broadcast(x, root_rank=0) * c).sum().backward()
    # Root receives allreduce(c) = c * size; non-root ranks get zeros
    # (reference: broadcast's registered gradient, mpi_ops.py:168-183).
    expect = (c.numpy() * hvt.size() if hvt.rank() == 0
              else np.zeros(3, np.float32))
    np.testing.assert_allclose(x.grad.numpy(), expect, rtol=1e-6)


def test_allgather():
    x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvt.allgather(x)
    assert out.shape == (2 * hvt.size(), 3)
    np.testing.assert_allclose(out.numpy(), np.tile(x.numpy(), (hvt.size(), 1)))


def test_allgather_grad():
    x = torch.ones(2, 3, requires_grad=True)
    out = hvt.allgather(x)
    out.sum().backward()
    # Each rank's slice receives summed cotangent = size.
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 3), float(hvt.size())))


def test_broadcast_and_inplace():
    x = torch.arange(4, dtype=torch.float32)
    out = hvt.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.numpy(), x.numpy())
    y = torch.arange(4, dtype=torch.float32)
    out = hvt.broadcast_(y, root_rank=3)
    assert out is y


def test_broadcast_root_out_of_range_raises():
    with pytest.raises(hvt.EngineError):
        hvt.broadcast(torch.ones(3), root_rank=hvt.size() + 5)


def test_broadcast_grad():
    x = torch.ones(3, requires_grad=True)
    out = hvt.broadcast(x, root_rank=0)
    out.sum().backward()
    # Root: grad = allreduce(ones, sum) = size; non-root ranks get zeros.
    expect = (np.full((3,), float(hvt.size())) if hvt.rank() == 0
              else np.zeros(3, np.float32))
    np.testing.assert_allclose(x.grad.numpy(), expect)


def _train(opt_factory, steps=60, seed=0):
    torch.manual_seed(seed)
    model = torch.nn.Sequential(torch.nn.Linear(2, 8), torch.nn.Tanh(),
                                torch.nn.Linear(8, 1))
    rng = np.random.RandomState(0)
    X = torch.tensor(rng.randn(64, 2), dtype=torch.float32)
    Y = (X @ torch.tensor([3.0, -1.0]) + 0.7).unsqueeze(1)
    opt = opt_factory(model)
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(X), Y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return model, losses


def test_distributed_optimizer_trains():
    def factory(model):
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        return hvt.DistributedOptimizer(
            opt, named_parameters=model.named_parameters()
        )

    model, losses = _train(factory)
    assert losses[-1] < losses[0] * 0.1, losses[-1]


def test_distributed_optimizer_matches_plain():
    """Average of identical per-chip grads == plain grads, so training must
    match the undistributed optimizer bit-for-bit-ish."""
    def dist_factory(model):
        return hvt.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
        )

    def plain_factory(model):
        return torch.optim.SGD(model.parameters(), lr=0.05)

    m1, _ = _train(dist_factory, steps=30, seed=42)
    m2, _ = _train(plain_factory, steps=30, seed=42)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(
            p1.detach().numpy(), p2.detach().numpy(), rtol=1e-4, atol=1e-5
        )


def test_distributed_optimizer_backward_passes_per_step():
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1)
    opt = hvt.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2,
    )
    X = torch.randn(8, 2)
    Y = torch.randn(8, 1)
    opt.zero_grad()
    torch.nn.functional.mse_loss(model(X), Y).backward()
    torch.nn.functional.mse_loss(model(X), Y).backward()
    opt.step()  # drains the accumulated (2-pass) gradient


def test_distributed_optimizer_keeps_class():
    model = torch.nn.Linear(2, 1)
    opt = hvt.DistributedOptimizer(
        torch.optim.Adam(model.parameters(), lr=1e-3),
        named_parameters=model.named_parameters(),
    )
    assert isinstance(opt, torch.optim.Adam)


def test_duplicate_named_parameters_rejected():
    model = torch.nn.Linear(2, 1)
    with pytest.raises(ValueError, match="not unique"):
        hvt.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("p", model.weight), ("p", model.bias)],
        )


def test_broadcast_parameters():
    model = torch.nn.Linear(4, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    hvt.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        np.testing.assert_allclose(v.numpy(), before[k].numpy())


def test_broadcast_optimizer_state():
    """Round-trip incl. scalar hyperparameters with type preservation
    (reference: test_torch.py:734-935)."""
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.25, momentum=0.9,
                          weight_decay=1e-4, nesterov=True)
    # Materialize state.
    loss = model(torch.randn(3, 4)).sum()
    loss.backward()
    opt.step()
    lr_before = opt.param_groups[0]["lr"]
    hvt.broadcast_optimizer_state(opt, root_rank=0)
    g = opt.param_groups[0]
    assert isinstance(g["lr"], float) and g["lr"] == lr_before
    assert isinstance(g["nesterov"], bool) and g["nesterov"] is True
    assert isinstance(g["momentum"], float) and g["momentum"] == 0.9
    # State buffers intact.
    for p in model.parameters():
        assert "momentum_buffer" in opt.state[p]


def test_broadcast_optimizer_state_lbfgs_rejected():
    model = torch.nn.Linear(2, 1)
    opt = torch.optim.LBFGS(model.parameters())
    with pytest.raises(ValueError):
        hvt.broadcast_optimizer_state(opt, root_rank=0)


def test_broadcast_parameters_bare_list_rejected():
    model = torch.nn.Linear(2, 1)
    with pytest.raises(ValueError, match="name, tensor"):
        hvt.broadcast_parameters(list(model.parameters()), root_rank=0)


def test_broadcast_parameters_named_parameters_generator():
    model = torch.nn.Linear(2, 1)
    hvt.broadcast_parameters(model.named_parameters(), root_rank=0)


def test_bf16_handoff_is_bit_exact_and_zero_copy():
    """torch bf16 -> ml_dtypes bf16 rides a bit-reinterpret, not an
    f32 round trip (r4; the old path cost two conversion copies per
    tensor on the engine's host leg). Inf and denormals must survive
    bit-exactly, and the outbound leg must be a VIEW of the torch
    storage."""
    import ml_dtypes
    from horovod_tpu.torch.mpi_ops import _np_of, _torch_of

    vals = torch.tensor([1.5, -0.0, 3.14159e-40, float("inf"), 1e-3],
                        dtype=torch.bfloat16)
    a = _np_of(vals)
    assert a.dtype == ml_dtypes.bfloat16
    assert a.view(np.int16).tolist() == vals.view(torch.int16).tolist()
    back = _torch_of(a, vals)
    assert back.dtype == torch.bfloat16
    assert back.view(torch.int16).tolist() == vals.view(torch.int16).tolist()

    t = torch.ones(4, dtype=torch.bfloat16)
    n = _np_of(t)
    t[0] = 2.0  # visible through the view => zero-copy
    assert float(np.asarray(n.astype(np.float32))[0]) == 2.0
