"""Graceful-preemption chaos worker — run under the launcher:

    python -m horovod_tpu.run -np 2 --cpu -- python preempt_worker.py

Phase 1 (the eviction): every rank arms the deterministic
``preempt.signal`` faultline site (same spec, lockstep batch count), so
the whole world "receives SIGTERM" at the same batch boundary — the
trainer must drain the step, write the crash-atomic emergency
checkpoint, quiesce the engine, pass the drain barrier, journal a
``preempted`` note, and exit 0 (the ``PREEMPTED rank=...`` marker).

Phase 2 (the relaunch — a second launcher run with no faults): resumes
from the newest checkpoint and finishes the remaining epochs; per-epoch
losses land in ``$HVD_PREEMPT_TEST_DIR/losses.rank<N>.jsonl`` across
BOTH phases so the pytest driver can assert the curve is continuous
(no restart-from-scratch jump)."""

import json
import os
import sys
import time

RANK = int(os.environ.get("HVD_PROCESS_ID", "0"))
OUT = os.environ["HVD_PREEMPT_TEST_DIR"]
EPOCHS = int(os.environ.get("HVD_TEST_EPOCHS", "6"))

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hk  # noqa: E402
from horovod_tpu.core import elastic  # noqa: E402

hvd.init()
print(f"WORLD rank={hvd.process_index()} np={hvd.num_processes()} "
      f"size={hvd.size()}", flush=True)

import flax.linen as nn  # noqa: E402
import optax  # noqa: E402


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(4)(h)


rng = np.random.default_rng(0)
N, BS = 256, 4
x = rng.normal(size=(N, 8)).astype(np.float32)
w_true = rng.normal(size=(8, 4)).astype(np.float32)
y = (x @ w_true).argmax(axis=1).astype(np.int32)


class Log(hk.callbacks.Callback):
    def on_epoch_end(self, epoch, logs=None):
        rec = {"rank": RANK, "epoch": epoch,
               "loss": float(logs.get("loss", -1.0)),
               "size": hvd.size(), "wall": round(time.time(), 3)}
        with open(os.path.join(OUT, f"losses.rank{RANK}.jsonl"),
                  "a") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(f"EPOCH rank={RANK} epoch={epoch} "
              f"loss={rec['loss']:.4f}", flush=True)


trainer = hk.Trainer(MLP(), optax.sgd(0.02, momentum=0.9), rng=0)
x_sample = x[:BS * hvd.local_size()]
initial_epoch = elastic.maybe_restore(trainer, x_sample)
if initial_epoch:
    print(f"RESUMED rank={RANK} at epoch {initial_epoch}", flush=True)

trainer.fit(x, y, batch_size=BS, epochs=EPOCHS, shuffle=False,
            initial_epoch=initial_epoch, callbacks=[Log()])

print(f"PREEMPT_TEST DONE rank={RANK} epochs={EPOCHS}", flush=True)
sys.stdout.flush()
# Same exit discipline as the other world workers: interpreter teardown
# barriers can hang if a peer left first.
os._exit(0)
