"""Multi-controller integration tests: 2 jax.distributed processes × 4
virtual chips. The reference runs its suite under `mpirun -np N`
(SURVEY.md §4); these spawn real separate controller processes the same
way."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(scenario: str, nproc: int = 2, timeout: int = 240):
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(i), str(nproc),
             scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"proc {i} failed (rc={p.returncode}):\n{out[-3000:]}"
        assert f"SCENARIO {scenario} PASSED" in out, out[-3000:]
    return outs


def test_two_process_collectives():
    _run_world("collectives")


def test_launcher_spawns_world():
    """python -m horovod_tpu.run -np 2 --cpu wires a 2-process world
    (the mpirun role — reference: docs/running.md)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(repo, "tests", "launcher_worker.py")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--", sys.executable, worker],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert proc.stdout.count("LAUNCHER TEST PASSED") == 2, proc.stdout


def test_launcher_propagates_failure():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--", sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])


def test_two_process_consistency_check_detects_mismatch():
    outs = _run_world("mismatch")
    for out in outs:
        assert "mismatch detected OK" in out
