"""Multi-controller integration tests: 2 jax.distributed processes × 4
virtual chips. The reference runs its suite under `mpirun -np N`
(SURVEY.md §4); these spawn real separate controller processes the same
way."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(scenario: str, nproc: int = 2, timeout: int = 240,
               extra_env: dict = None, expect_dead: tuple = ()):
    """Spawn an nproc-controller world. ``expect_dead`` names process ids
    allowed (expected) to die without printing PASSED — e.g. a SIGKILL
    victim in failure-injection scenarios."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(i), str(nproc),
             scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if i in expect_dead:
            continue
        assert p.returncode == 0, \
            f"proc {i} failed (rc={p.returncode}):\n{out[-3000:]}"
        assert f"SCENARIO {scenario} PASSED" in out, out[-3000:]
    return outs


def test_two_process_collectives():
    _run_world("collectives")


def test_launcher_spawns_world():
    """python -m horovod_tpu.run -np 2 --cpu wires a 2-process world
    (the mpirun role — reference: docs/running.md)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(repo, "tests", "launcher_worker.py")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--", sys.executable, worker],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert proc.stdout.count("LAUNCHER TEST PASSED") == 2, proc.stdout


def test_launcher_propagates_failure():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--", sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])


def test_two_process_consistency_check_detects_mismatch():
    outs = _run_world("mismatch")
    for out in outs:
        assert "mismatch detected OK" in out


def test_two_process_engine_without_negotiation():
    """HVD_NEGOTIATION=0: fallback multi-controller engine path keeps
    fusion force-disabled and name-ordered execution."""
    _run_world("collectives_nonegotiation",
               extra_env={"HVD_NEGOTIATION": "0"})


ENGINES = ["native", "python"]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_negotiated_fusion(engine):
    """Fusion stays ON across controller processes: batch composition is
    agreed through KV negotiation rounds and results are identical
    everywhere (reference: rank-0 fused responses,
    operations.cc:2035-2074)."""
    outs = _run_world("engine_fusion", extra_env={"HVD_ENGINE": engine})
    results = [line for out in outs for line in out.splitlines()
               if line.startswith("RESULT ")]
    assert len(results) == 2 and results[0] == results[1], results


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_mismatch_errors_on_every_rank(engine):
    """dtype/shape/root/op mismatches surface the same coordinator-style
    error on EVERY process (reference: test_torch.py:265-349)."""
    outs = _run_world("engine_mismatch", extra_env={"HVD_ENGINE": engine})
    for out in outs:
        for needle in ("Mismatched data types OK",
                       "Mismatched tensor shapes OK",
                       "Mismatched root ranks OK",
                       "Mismatched collective operations OK"):
            assert needle in out, out[-3000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_priority_mismatch_fails_fast(engine):
    """A world that disagrees on a tensor's priority class fails fast
    BY NAME on every process (priority joins the negotiation
    fingerprint), and agreeing mixed-class traffic still reduces
    correctly (ISSUE 20 serving plane)."""
    outs = _run_world("engine_priority", extra_env={"HVD_ENGINE": engine})
    for out in outs:
        assert "priority mismatch OK" in out, out[-3000:]
        assert "agreed classes reduce OK" in out, out[-3000:]


def test_two_process_serving_overload_acceptance():
    """The ISSUE 20 acceptance gate, durable: the mixed-priority load
    harness on the 2-process tier with injected exec stalls + KV delays
    on rank 0 and a tiny low-class in-flight budget. The harness itself
    asserts (--assert-acceptance): high-class p99 <= its deadline knob,
    admission rejections on the low class only (and present), zero torn
    fused batches, zero poisonings — every non-shed completion
    digest-verified against the exact expected reduction."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--faults", "0:engine.exec:stall:3:0.1,kv.get:delay:5:0.02",
         "--", sys.executable,
         os.path.join(repo, "examples", "serving_load_harness.py"),
         "--requests", "60", "--max-inflight-low", "2",
         "--deadline-high-ms", "8000", "--assert-acceptance"],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo)
    assert proc.returncode == 0, \
        proc.stdout[-3000:] + proc.stderr[-2000:]
    # One JSON report per rank; the acceptance math ran in-harness, but
    # pin the headline numbers here too so a silent no-op can't pass.
    import json as _json

    reports = [_json.loads(line.split("] ", 1)[-1])
               for line in proc.stdout.splitlines()
               if line.lstrip("[01] ").startswith("{")]
    assert len(reports) == 2, proc.stdout[-3000:]
    for r in reports:
        assert r["counters"]["engine.admission.rejected"] > 0
        assert r["digest_failures"] == 0 and r["torn_batches"] == 0


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_stall_names_missing_process(engine):
    """The stall warning names the process that has not submitted
    (reference: CheckForStalledTensors, operations.cc:1535-1581)."""
    outs = _run_world(
        "engine_stall",
        extra_env={"HVD_ENGINE": engine, "HVD_STALL_CHECK_TIME": "1"})
    assert any("late" in out and "missing from process(es): 1" in out
               for out in outs), outs[0][-3000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_straggler_report_blames_delayed_rank(engine):
    """Per-rank wait attribution (core/telemetry.py StragglerTracker, fed
    from the negotiation round tables): with process 1 artificially
    delayed, the straggler report names it with the largest cumulative
    imposed wait — on every process (ISSUE 2 acceptance)."""
    outs = _run_world("engine_straggler",
                      extra_env={"HVD_ENGINE": engine})
    assert sum("STRAGGLER" in out for out in outs) == 2, outs[0][-3000:]


@pytest.mark.parametrize("engine", ["cpp", "python"])
def test_two_process_negotiation_rankready_marks(engine):
    """NEGOTIATE_* spans carry per-process RANK_READY instants naming who
    announced when — the late process is visible in the trace itself
    (reference: timeline.cc:106-130; VERDICT r4 missing #4)."""
    outs = _run_world(
        "engine_rankready",
        extra_env={"HVD_ENGINE": engine})
    assert any("rankready marks" in out for out in outs), outs[0][-3000:]


def test_two_process_torch_api_errors():
    """Mismatches surfaced through the torch API as exceptions on every
    rank — the reference's error-path tests drive the torch surface, not
    the raw engine (test_torch.py:265-349)."""
    outs = _run_world("torch_errors")
    for out in outs:
        for needle in ("torch Mismatched data types OK",
                       "torch Mismatched tensor shapes OK",
                       "torch Mismatched root ranks OK"):
            assert needle in out, out[-3000:]


def test_two_process_hierarchical_allreduce():
    """HVD_HIERARCHICAL_ALLREDUCE on a 2-process world: the (dcn, ici)
    mesh is built from process grouping and eager/compiled/engine
    allreduces all ride the hierarchical composition (reference:
    operations.cc:1194-1346)."""
    _run_world("hierarchical",
               extra_env={"HVD_HIERARCHICAL_ALLREDUCE": "1"})


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_trace_merged_and_skew(engine, tmp_path):
    """Distributed tracing (ISSUE 3 acceptance): a 2-process world with
    HVD_TIMELINE=<dir> produces per-rank traces that merge onto a
    common clock base — overlapping NEGOTIATE spans — and `trace skew`
    blames the artificially delayed rank within 20% of the telemetry
    straggler report (assertions live in multiproc_worker.py)."""
    tdir = str(tmp_path / "tl")
    outs = _run_world("engine_trace_merged",
                      extra_env={"HVD_ENGINE": engine,
                                 "HVD_TIMELINE": tdir})
    assert any("TRACE_MERGED" in out for out in outs), outs[0][-3000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_flight_dump_on_negotiation_timeout(engine):
    """Killing a rank mid-negotiation yields a loadable flight-recorder
    dump from the survivor — same event names from both engines — whose
    reason AND straggler snapshot name the dead/delayed process (ISSUE 3
    acceptance + satellite: the C++-engine straggler path end-to-end)."""
    outs = _run_world(
        "engine_flight_timeout",
        extra_env={"HVD_ENGINE": engine, "HVD_NEGOTIATION_TIMEOUT": "6"},
        expect_dead=(1,), timeout=300)
    assert any("FLIGHT dump names process 1" in out for out in outs), \
        outs[0][-3000:]


def test_launcher_collects_and_merges_timeline(tmp_path):
    """python -m horovod_tpu.run --timeline DIR: children write
    per-rank traces, the launcher auto-merges at exit."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    tdir = str(tmp_path / "tl")
    worker = os.path.join(repo, "tests", "launcher_worker.py")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--timeline", tdir, "--", sys.executable, worker],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "[launcher] merged timeline" in proc.stderr, proc.stderr[-1000:]
    merged = os.path.join(tdir, "timeline.merged.json")
    assert os.path.exists(merged), os.listdir(tdir)
    import json as _json

    _json.load(open(merged))  # Perfetto-loadable (complete JSON)
    # Per-rank files exist for both processes.
    assert {f for f in os.listdir(tdir) if f.startswith("timeline.rank")} \
        == {"timeline.rank0.json", "timeline.rank1.json"}


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_negotiation_cache_steady_state(engine):
    """ISSUE 4 acceptance: with a stable tensor set, steady-state
    negotiated cycles take the response-cache bitvector fast path
    (hit counter >> miss counter, zero steady-state misses — asserted in
    multiproc_worker.py), a changed set falls back to a full round, and
    reduction outputs are BITWISE identical cache-on vs
    HVD_CACHE_CAPACITY=0 — both engines."""
    on = _run_world("engine_cache", extra_env={"HVD_ENGINE": engine})
    off = _run_world("engine_cache",
                     extra_env={"HVD_ENGINE": engine,
                                "HVD_CACHE_CAPACITY": "0"})

    def digests(outs):
        return sorted(line for out in outs for line in out.splitlines()
                      if line.startswith("RESULT "))

    d_on, d_off = digests(on), digests(off)
    assert len(d_on) == 2 and len(set(d_on)) == 1, d_on  # agree across ranks
    assert d_on == d_off, (d_on, d_off)  # bitwise: cache-on == cache-off
    assert sum("CACHE" in out for out in on) == 2, on[0][-2000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_cache_eviction_forces_full_rounds(engine):
    """HVD_CACHE_CAPACITY=2 under a 4-tensor steady set: LRU evictions
    advance the epoch in lockstep, evicted tensors miss and force
    full-table rounds, results stay correct (ISSUE 4 satellite)."""
    outs = _run_world("engine_cache_evict",
                      extra_env={"HVD_ENGINE": engine,
                                 "HVD_CACHE_CAPACITY": "2"})
    assert sum("EVICT OK" in out for out in outs) == 2, outs[0][-2000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_numerics_chaos_attributes_poisoner(engine, tmp_path):
    """Numerics observatory chaos (ISSUE 8 acceptance), BOTH engines:
    process 1 submits NaN-poisoned gradients -> the `nonfinite` verdict
    names process 1 on every survivor; a deliberately desynced parameter
    bucket -> the consistency digest's `diverged` report names the
    float32 bucket and BOTH processes (a 2-controller disagreement is a
    structural 4-vs-4 digest tie — no vote can single one out, and the
    report says so) identically on every process; a flight dump lands
    per verdict per rank. Counter names are pinned inside the worker, so
    the native and python runs cannot drift apart."""
    fdir = tmp_path / f"flight_{engine}"
    fdir.mkdir()
    outs = _run_world("numerics_chaos",
                      extra_env={"HVD_ENGINE": engine,
                                 "HVD_NUMERICS": "warn",
                                 "HVD_FLIGHT_DIR": str(fdir),
                                 "HVD_FLIGHT_MIN_INTERVAL": "0"})
    for out in outs:
        assert "NONFINITE names process 1" in out, out[-3000:]
        assert ("DIVERGED tie names both processes, bucket float32"
                in out), out[-3000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_peer_shutdown_propagates(engine):
    """A peer stopping its engine fails outstanding collectives with
    ShutdownError instead of hanging (reference: SHUT_DOWN_ERROR,
    operations.cc:1833-1848)."""
    outs = _run_world("engine_peer_shutdown",
                      extra_env={"HVD_ENGINE": engine})
    assert any("peer shutdown surfaced" in out for out in outs)


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_fleet_rollup_and_sigkill_stale(engine, tmp_path):
    """The fleet observability plane end-to-end on both engines: every
    rank publishes latency snapshots to the KV plane, rank 0 merges them
    into world rollups (identical instrument vocabularies across ranks,
    world p99 reflecting rank 1's injected skew), and a SIGKILLed rank
    goes STALE after the lease without wedging rank 0's rollup."""
    outs = _run_world(
        "fleet",
        extra_env={"HVD_ENGINE": engine,
                   "HVD_FLEET_DIR": str(tmp_path),
                   # Only explicit beats: the STALE verdict must not race
                   # a background publish between barrier and SIGKILL.
                   "HVD_FLEET_INTERVAL_S": "60",
                   "HVD_FLEET_LEASE_S": "1.0"},
        expect_dead=(1,))
    assert any("world p99" in out for out in outs)
    assert any("STALE after lease" in out for out in outs)


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_doctor_blames_withheld_submit(engine, tmp_path):
    """Hang-doctor acceptance (ISSUE 18), BOTH engines: process 1's
    submit of 'held' is withheld through the faultline; the stalled
    process's verdict (riding its stall dump, within one stall-warning
    interval) and the blamed process's on-demand ``hvd.diagnose()`` must
    BOTH be ``missing_submitter`` naming the identical tensor and
    missing rank (assertions live in multiproc_worker.py)."""
    fdir = tmp_path / "fleet"
    fdir.mkdir()
    flight = tmp_path / "flight"
    flight.mkdir()
    outs = _run_world(
        "doctor_withheld",
        extra_env={"HVD_ENGINE": engine,
                   "HVD_STALL_CHECK_TIME": "1",
                   "HVD_FLEET_DIR": str(fdir),
                   # Only explicit doctor publishes matter here; keep
                   # the latency publisher quiet.
                   "HVD_FLEET_INTERVAL_S": "60",
                   "HVD_FLIGHT_DIR": str(flight),
                   "HVD_FLIGHT_MIN_INTERVAL": "0"})
    assert sum("DOCTOR blames rank 1 tensor 'held'" in out
               for out in outs) == 2, outs[0][-3000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_doctor_dead_peer_verdict(engine, tmp_path):
    """A SIGKILLed peer classifies as ``dead_peer`` (the elastic death
    note outranks missing_submitter) and the diagnoser stays prompt with
    a corpse in the world — BOTH engines (ISSUE 18 satellite)."""
    fdir = tmp_path / "fleet"
    fdir.mkdir()
    flight = tmp_path / "flight"
    flight.mkdir()
    edir = tmp_path / "elastic"
    edir.mkdir()
    outs = _run_world(
        "doctor_dead_peer",
        extra_env={"HVD_ENGINE": engine,
                   "HVD_STALL_CHECK_TIME": "1",
                   "HVD_NEGOTIATION_TIMEOUT": "6",
                   "HVD_ELASTIC": "1",
                   "HVD_ELASTIC_LEASE_S": "2",
                   "HVD_ELASTIC_GRACE_S": "120",
                   "HVD_ELASTIC_DIR": str(edir),
                   "HVD_FLEET_DIR": str(fdir),
                   "HVD_FLEET_INTERVAL_S": "60",
                   "HVD_FLIGHT_DIR": str(flight),
                   "HVD_FLIGHT_MIN_INTERVAL": "0"},
        expect_dead=(1,), timeout=300)
    assert any("DOCTOR verdict dead_peer names rank 1" in out
               for out in outs), outs[0][-3000:]


# ---------------------------------------------------------------------------
# np=4 tier (VERDICT r2 item 5): negotiation with 3+ peers, failure
# injection, parameter propagation, and a >2-process two-tier mesh.
# 2 virtual chips per process keep the 4-process world at 8 devices.
# ---------------------------------------------------------------------------

_NP4 = {"HVD_TEST_LOCAL_DEVICES": "2"}


def test_four_process_host_split():
    """2 simulated hosts × 2 controllers each: local_rank/local_num_
    processes/cross_rank/cross_size derive from the shared-host split
    (reference: the MPI shared-memory + cross communicator split,
    operations.cc:1668-1705)."""
    _run_world("host_split", nproc=4, extra_env=_NP4)


def test_four_process_collectives():
    _run_world("collectives", nproc=4, extra_env=_NP4)


@pytest.mark.parametrize("engine", ENGINES)
def test_four_process_negotiated_fusion(engine):
    """Fusion composition agreed across FOUR controllers; results bitwise
    identical everywhere (negotiation beyond the 2-peer case was
    previously only unit-tested against a fake KV)."""
    outs = _run_world("engine_fusion", nproc=4,
                      extra_env={**_NP4, "HVD_ENGINE": engine})
    results = [line for out in outs for line in out.splitlines()
               if line.startswith("RESULT ")]
    assert len(results) == 4 and len(set(results)) == 1, results


def test_four_process_two_tier_hierarchical():
    """(dcn=4, ici=2) two-tier mesh from process grouping; eager,
    compiled and engine allreduces ride the hierarchical composition
    (reference: operations.cc:1194-1346)."""
    _run_world("hierarchical", nproc=4,
               extra_env={**_NP4, "HVD_HIERARCHICAL_ALLREDUCE": "1"})


@pytest.mark.parametrize("engine", ENGINES)
def test_four_process_sigkill_peer_times_out_not_hangs(engine):
    """SIGKILL one peer mid-round (no tombstone): survivors surface an
    attributed negotiation timeout instead of hanging for the full 600 s
    default or mistaking it for a clean shutdown."""
    outs = _run_world(
        "engine_peer_sigkill", nproc=4,
        extra_env={**_NP4, "HVD_ENGINE": engine,
                   "HVD_NEGOTIATION_TIMEOUT": "6"},
        expect_dead=(3,), timeout=300)
    assert sum("sigkill surfaced as timeout naming process 3" in out
               for out in outs) == 3, outs[0][-2000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_four_process_negotiation_fuzz(engine):
    """40 mixed collectives per process, shuffled order, random
    think-time: negotiation must converge on identical batches from
    genuinely divergent partial tables (the reference's any-order
    guarantee, ConstructMPIResponse)."""
    outs = _run_world("engine_fuzz", nproc=4, timeout=300,
                      extra_env={**_NP4, "HVD_ENGINE": engine})
    assert sum("fuzz 40 ops OK" in out for out in outs) == 4


def test_four_process_negotiation_fuzz_aggregate():
    """The fuzz scenario under the gather-tree round shape
    (HVD_NEGOTIATION_AGGREGATE=1): 40 shuffled mixed collectives per
    process must still converge on identical batches when every peer
    reads only p0's digest. (All failure-injection scenarios — fuzz,
    mismatch, SIGKILL, re-init — were validated under aggregate mode in
    r4; this pins the broadest one in CI.)"""
    outs = _run_world("engine_fuzz", nproc=4, timeout=300,
                      extra_env={**_NP4,
                                 "HVD_NEGOTIATION_AGGREGATE": "1"})
    assert sum("fuzz 40 ops OK" in out for out in outs) == 4


@pytest.mark.parametrize("engine", ENGINES)
def test_two_process_engine_reinit_generations(engine):
    """Three collective shutdown/re-init cycles: each generation
    negotiates in a fresh namespace and reclaims the previous one's
    leftover keys (previously only unit-tested against a fake KV)."""
    outs = _run_world("engine_reinit", nproc=2,
                      extra_env={"HVD_ENGINE": engine})
    assert sum("three engine generations OK" in out for out in outs) == 2


@pytest.mark.parametrize("nproc", [2, 4, 8])
def test_negotiation_round_latency_vs_world_size(nproc):
    """The control plane's cost curve (VERDICT r3 #4; reference analogue:
    the rank-0 MPI_Gatherv tick, operations.cc:2117-2131). Each round is
    one KV set + (P-1) blocking reads per process; this measures per-op
    sequential latency, burst amortization, and the coordinator's own
    per-round wall time at P=2/4/8 — the measured table lives in
    docs/running.md. The np=8 bound is deliberately generous (the pinned
    failure mode is super-linear blowup — timeouts, compounding backoff
    — not CI jitter)."""
    env = {"HVD_TEST_LOCAL_DEVICES": "1"} if nproc == 8 else (
        _NP4 if nproc == 4 else {})
    outs = _run_world("negotiation_latency", nproc=nproc, timeout=420,
                      extra_env=env)
    import json as _json

    recs = []
    for out in outs:
        line = [ln for ln in out.splitlines() if "NEG_LATENCY" in ln][-1]
        recs.append(_json.loads(line.split("NEG_LATENCY ", 1)[1]))
    assert len(recs) == nproc
    for r in recs:
        # Burst submission amortizes rounds over K tensors; sequential
        # pays >= one round per op. Equality would mean the engine
        # serialized the burst into per-op rounds.
        assert r["burst_ms"] < r["seq_ms"], r
        assert r["rounds"] and r["per_round_ms"] is not None, r
    for r in recs:
        # Retry storms are the load-independent pathology signature:
        # a round is one blocking get per peer, so gets >> (P-1)*rounds
        # means peers keep missing the poll slice. Measured ratios are
        # 1.0-1.05 at P=2/4/8 (docs/running.md).
        assert r["kv_gets"] < 2 * (nproc - 1) * r["rounds"] + 10, r
    if nproc == 8:
        for r in recs:
            # The absolute bound the verdict asked for, with headroom
            # for a loaded CI host (measured 0.12-0.56 s/round at P=8
            # depending on concurrent suite load): the pinned pathology
            # — compounding timeouts at the 0.5 s poll slice — sits at
            # many seconds per round.
            assert r["per_round_ms"] < 1500.0, r


def test_negotiation_aggregate_gather_tree_np8():
    """HVD_NEGOTIATION_AGGREGATE=1 (the reference's gather-tree shape):
    correctness at P=8 plus the load signature — non-root processes
    read ~one key per round instead of P-1 (total KV load O(P) instead
    of O(P^2))."""
    outs = _run_world("negotiation_latency", nproc=8, timeout=420,
                      extra_env={"HVD_TEST_LOCAL_DEVICES": "1",
                                 "HVD_NEGOTIATION_AGGREGATE": "1"})
    import json as _json

    recs = []
    for out in outs:
        line = [ln for ln in out.splitlines() if "NEG_LATENCY" in ln][-1]
        recs.append(_json.loads(line.split("NEG_LATENCY ", 1)[1]))
    assert len(recs) == 8
    # One record is p0 (gets ~= (P-1)*rounds); the rest must sit near
    # one get per round (poll-slice retries allowed; the symmetric
    # protocol's ratio here is ~7x rounds).
    ratios = sorted(r["kv_gets"] / max(r["rounds"], 1) for r in recs)
    assert ratios[-1] > 3.0, ratios   # the root's gather
    for ratio in ratios[:-1]:
        assert ratio < 2.0, ratios    # digest readers
    for r in recs:
        assert r["burst_ms"] < r["seq_ms"], r


def test_eight_process_collectives():
    """The widest world one host can stage: 8 controllers x 1 chip.
    Negotiation readiness/cleanup and the compiled collectives hold at
    P=8 (reference: the mpirun tier ran the same suite at any -np)."""
    _run_world("collectives", nproc=8, timeout=420,
               extra_env={"HVD_TEST_LOCAL_DEVICES": "1"})


@pytest.mark.parametrize("engine", ENGINES)
def test_four_process_idle_backoff_does_not_compound(engine):
    """First op after an all-quiet stretch completes within ~one idle
    backoff cap, not nproc × cap: peer backoffs run concurrently and a
    local enqueue wakes the local loop (VERDICT r2 weak #6 — previously
    untested at np>2)."""
    # cap=4 puts the worker's pass bound (cap + 3s + 2x a measured
    # per-run baseline op, best of two attempts — multiproc_worker.py
    # "engine_idle_backoff") far under the compounding signature
    # ((nproc-1)*cap=12s) while tracking CI host load.
    outs = _run_world("engine_idle_backoff", nproc=4, timeout=300,
                      extra_env={**_NP4, "HVD_ENGINE": engine,
                                 "HVD_NEGOTIATION_IDLE_MAX": "4.0"})
    assert sum("IDLE_LATENCY" in out for out in outs) == 4, outs[0][-2000:]


@pytest.mark.parametrize("engine", ENGINES)
def test_four_process_autotune_param_propagation(engine):
    """Process 0's engine parameters reach all 3 peers through round
    params (reference: ParameterManager::SyncParams broadcast,
    parameter_manager.cc:63-77,203-236)."""
    outs = _run_world("autotune_propagation", nproc=4,
                      extra_env={**_NP4, "HVD_ENGINE": engine})
    assert sum("params propagated" in out for out in outs) == 4


# ---------------------------------------------------------------------------
# The reference's "same suite, N processes" tier (SURVEY §4;
# /root/reference/test/common.py:24-56): the single-process frontend test
# FILES run unmodified inside a 2-controller world via the launcher.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("suite", ["test_jax_frontend.py",
                                   "test_torch_frontend.py",
                                   "test_keras_frontend.py",
                                   "test_tensorflow_frontend.py"])
def test_frontend_suite_under_launcher_np2(suite):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         "--", sys.executable, "-m", "pytest",
         os.path.join(repo, "tests", suite), "-q", "--no-header", "-p",
         "no:cacheprovider"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-1000:]
    # Every process ran the whole file green.
    assert proc.stdout.count(" passed") >= 2, proc.stdout[-2000:]
