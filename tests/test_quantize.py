"""Quantized collectives (ISSUE 12 — EQuARX-style block-scaled int8 on
the compiled hot path and in the engine chunks): quantizer math, the
compiled shard_update pipeline (HLO-pinned at the StableHLO level per
the PR 7 CPU-legalization caveat), the non-divisible padding contract,
error-feedback convergence against the f32 oracle, cross-engine
bit-identical digests with the wire-byte counters, and the negotiation
mixed-policy fail-fast."""

import hashlib
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hj
from horovod_tpu.jax import quantize as Q
from horovod_tpu.jax.compression import Compression
from horovod_tpu.ops.collectives import HVD_AXIS


@pytest.fixture(autouse=True)
def _init(hvd):
    pass


class SmallInt8(Compression.int8):
    """Test-sized scale blocks: world*block padding stays tiny."""

    block = 8


class SmallInt8EF(SmallInt8):
    error_feedback = True


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    for pol in (Compression.int8, SmallInt8, Compression.fp8):
        n = pol.block * 5
        x = jnp.asarray(rng.randn(n).astype(np.float32) * 3.0)
        payload, scales = Q.quantize(x, pol)
        assert payload.shape == (n,) and scales.shape == (n // pol.block,)
        y = np.asarray(Q.dequantize(payload, scales, pol))
        # Worst-case per-element error: half a quantization step of the
        # block's scale (uniform int8 grid); fp8's grid is relative —
        # half an e4m3 ulp (2^-4) of the VALUE, with a subnormal floor.
        if pol.round_to_int:
            bound = np.repeat(np.asarray(scales), pol.block) * 0.5
        else:
            bound = (np.abs(np.asarray(x)) * 2.0 ** -4
                     + np.repeat(np.asarray(scales), pol.block) * 2.0 ** -9)
        assert np.all(np.abs(y - np.asarray(x)) <= bound * 1.0001)
    # Zero blocks: scale 1.0, payload zeros, exact round trip — the
    # padding-neutrality the scatter contract relies on.
    z = jnp.zeros((SmallInt8.block * 2,), jnp.float32)
    payload, scales = Q.quantize(z, SmallInt8)
    np.testing.assert_array_equal(np.asarray(payload), 0)
    np.testing.assert_array_equal(np.asarray(scales), 1.0)


def test_np_twin_matches_jnp():
    """The engines' host-side quantizer must agree with the compiled
    path's math (same rounding: ties to even)."""
    rng = np.random.RandomState(1)
    x = rng.randn(Compression.int8.block * 3).astype(np.float32)
    pj, sj = Q.quantize(jnp.asarray(x), Compression.int8)
    pn, sn, npad = Q.np_quantize(x, Compression.int8)
    assert npad == x.size
    np.testing.assert_array_equal(np.asarray(pj), pn)
    np.testing.assert_array_equal(np.asarray(sj), sn)


def test_eager_quantized_allreduce(hvd):
    """Eager semantics: every local chip contributes this controller's
    value, so the quantized sum is world * dequant(quant(x)) — and the
    non-divisible tail pads with reduction-neutral zero blocks."""
    world = hvd.size()
    x = jnp.asarray(np.random.RandomState(2).randn(33).astype(np.float32))
    out = hj.allreduce(x, average=False, compression=SmallInt8)
    xp = np.zeros((Q.padded_len(33, SmallInt8.block),), np.float32)
    xp[:33] = np.asarray(x)
    expect = world * np.asarray(
        Q.dequantize(*Q.quantize(jnp.asarray(xp), SmallInt8), SmallInt8))
    np.testing.assert_allclose(np.asarray(out), expect[:33], rtol=1e-6)
    avg = hj.allreduce(x, average=True, compression=SmallInt8)
    np.testing.assert_allclose(np.asarray(avg), expect[:33] / world,
                               rtol=1e-6)


def _tree():
    """Flat size 10+3+20 = 33 — NOT divisible by 8 (the padding-contract
    precedent tree): the quantized policy pads to world*block."""
    return {
        "w": jnp.arange(10.0),
        "b": jnp.full((3,), 0.5),
        "k": jnp.linspace(-1.0, 1.0, 20).reshape(4, 5),
    }


def _spmd_step(opt, state):
    ospec = hj.sharded_state_specs(state)

    @hj.jit(in_specs=(P(), ospec, P(HVD_AXIS)), out_specs=(P(), ospec))
    def step(p, s, gstack):
        g = jax.tree_util.tree_map(lambda l: l[0], gstack)
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    return step


def _stack(tree, world, distinct=True):
    """Rank-stacked gradients: row r is rank r's gradient."""

    def one(i, l):
        base = np.asarray(l, np.float32) * 0.01 + 0.05
        rows = np.stack([base * (1 + (0.13 * r if distinct else 0.0))
                         for r in range(world)])
        return jnp.asarray(rows)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [one(i, l) for i, l in enumerate(leaves)])


def test_nondivisible_tree_quantized_roundtrip_vs_oracle(hvd):
    """33-element tree over 8 ranks with DISTINCT per-rank gradients:
    the compiled quantize → int8 all-to-all → dequantize-accumulate →
    update → requantize → int8 all-gather pipeline must match the
    quantizer-math oracle computed leaf-by-leaf on the host (pad to
    world*block → per-rank quantize → sum dequants → average → SGD →
    blockwise-quantized delta), and round-trip the tree's shapes."""
    world = hvd.size()
    params = _tree()
    opt = hj.shard_update(optax.sgd(0.1), compression=SmallInt8)
    state = opt.init(params)
    gstack = _stack(params, world)
    new_p, _ = _spmd_step(opt, state)(params, state, gstack)

    # Host oracle over the packed f32 buffer (one dtype group here).
    mult = world * SmallInt8.block
    flat = np.concatenate([np.asarray(l, np.float32).ravel()
                           for l in jax.tree_util.tree_leaves(params)])
    npad = Q.padded_len(flat.size, mult)
    gflat = np.zeros((world, npad), np.float32)
    for r in range(world):
        row = np.concatenate(
            [np.asarray(l, np.float32)[r].ravel()
             for l in jax.tree_util.tree_leaves(gstack)])
        gflat[r, :row.size] = row
    total = np.zeros((npad,), np.float32)
    for r in range(world):
        total += np.asarray(Q.dequantize(
            *Q.quantize(jnp.asarray(gflat[r]), SmallInt8), SmallInt8))
    mean = total / world
    delta = np.asarray(Q.dequantize(
        *Q.quantize(jnp.asarray(-0.1 * mean), SmallInt8), SmallInt8))
    expect = np.zeros((npad,), np.float32)
    expect[:flat.size] = flat
    expect += delta
    got = np.concatenate([np.asarray(l, np.float32).ravel()
                          for l in jax.tree_util.tree_leaves(new_p)])
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(params)):
        assert a.shape == b.shape and a.dtype == b.dtype
    np.testing.assert_allclose(got, expect[:flat.size], atol=1e-5)


def test_eager_spmd_trajectory_parity(hvd):
    """With identical per-rank gradients the eager quantized path (full
    buffers, no collectives needed) and the compiled pipeline take the
    same trajectory — blockwise quantization of the full buffer equals
    the concatenation of the per-shard quantizations."""
    world = hvd.size()
    params = _tree()
    for comp in (SmallInt8, SmallInt8EF):
        opt = hj.shard_update(optax.sgd(0.1), compression=comp)
        se = opt.init(params)
        ss = opt.init(params)
        step = _spmd_step(opt, ss)
        pe, ps = params, params
        gstack = _stack(params, world, distinct=False)
        g = jax.tree_util.tree_map(lambda l: l[0], gstack)
        for _ in range(3):
            ue, se = opt.update(g, se, pe)
            pe = optax.apply_updates(pe, ue)
            ps, ss = step(ps, ss, gstack)
        for a, b in zip(jax.tree_util.tree_leaves(pe),
                        jax.tree_util.tree_leaves(ps)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


def test_hlo_pins_int8_wire(hvd):
    """Program-level (StableHLO) pin, per the PR 7 caveat (XLA:CPU's
    compiled text legalizes collectives — the pin is the program): under
    the int8 policy the payload-sized cross-rank collectives (the
    all_to_all reduce-scatter phase and the tiled all_gather) run at i8,
    and NO payload-sized float collective survives — only the small f32
    scale exchanges (n/block elements)."""
    params = _tree()
    opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  sharded_update=True,
                                  compression=SmallInt8)
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state)

    @hj.jit(in_specs=(P(), ospec, P()), out_specs=(P(), ospec))
    def step(p, s, g):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    txt = step.lower(params, state, params).as_text()
    sigs = re.findall(
        r'stablehlo\.(all_to_all|all_gather|reduce_scatter)"'
        r'.*?:\s*\(tensor<([^>]*)>\)',
        txt, re.S)
    assert sigs, "expected collectives in the 8-device program"

    def elems_dtype(sig):
        parts = sig.split("x")
        dims = [int(d) for d in parts[:-1]] or [1]
        n = 1
        for d in dims:
            n *= d
        return n, parts[-1]

    npad = Q.padded_len(33, 8 * SmallInt8.block)  # world * block
    payload_i8 = [s for op, s in sigs if elems_dtype(s)[1] == "i8"]
    assert payload_i8, txt[:2000]
    assert {op for op, s in sigs if elems_dtype(s)[1] == "i8"} >= {
        "all_to_all", "all_gather"}
    for op, s in sigs:
        n, dt = elems_dtype(s)
        if dt != "i8":
            # Scales only: n/block f32 values per exchange, never the
            # payload-sized buffer.
            assert n <= npad // SmallInt8.block, (op, s)


def test_error_feedback_convergence_and_noef_drift(hvd):
    """The convergence guardrail: SGD under int8-with-residual tracks
    the f32 oracle; without the residual, coordinates whose gradients
    are small against the block amax are crushed to zero payload every
    step and the trajectory stalls (the documented no-residual drift —
    docs/troubleshooting.md 'int8 quantization convergence')."""
    # One dominant coordinate per block pins the scale; the small
    # gradients elsewhere are ~1/40 of a quantization step.
    n = 64
    target = np.zeros((n,), np.float32)
    w0 = np.ones((n,), np.float32)
    w0[:: SmallInt8.block] = 100.0  # block-scale drivers

    def grads(w):
        return {"w": jnp.asarray(0.002 * (w - target), jnp.float32)}

    def run(comp, steps=60):
        params = {"w": jnp.asarray(w0)}
        opt = hj.shard_update(optax.sgd(1.0), compression=comp)
        state = opt.init(params)
        for _ in range(steps):
            u, state = opt.update(grads(np.asarray(params["w"])), state,
                                  params)
            params = optax.apply_updates(params, u)
        return np.asarray(params["w"], np.float32)

    def oracle(steps=60):
        w = w0.copy()
        for _ in range(steps):
            w = w - 1.0 * 0.002 * (w - target)
        return w

    w_ef = run(SmallInt8EF)
    w_noef = run(SmallInt8)
    w_f32 = oracle()
    small = np.ones(n, bool)
    small[:: SmallInt8.block] = False
    err_ef = np.max(np.abs(w_ef[small] - w_f32[small]))
    err_noef = np.max(np.abs(w_noef[small] - w_f32[small]))
    # EF tracks the oracle within a couple of quantization steps of the
    # FINAL scale; no-EF loses essentially the whole descent on the
    # small coordinates.
    assert err_ef < 0.05, (err_ef, err_noef)
    assert err_noef > 10 * err_ef, (err_ef, err_noef)


def test_engine_digest_parity_and_wire_counters(hvd, monkeypatch):
    """Acceptance pin: same input through the python and C++ engines
    under HVD_COMPRESSION=int8 reduces to BIT-IDENTICAL bytes (the
    shared data plane quantizes per chunk), both feed the same
    engine.wire_bytes{,.compressed} counters, and the shipped bytes are
    >= 3.9x below full width (f32 -> int8 payload + f32 scales)."""
    from horovod_tpu.core import engine as eng
    from horovod_tpu.core import telemetry as tele
    from horovod_tpu.core.native_engine import NativeEngine

    monkeypatch.setenv("HVD_COMPRESSION", "int8")
    data = np.random.RandomState(3).randn(1 << 18).astype(np.float32)

    digests, wires = [], []
    for cls in (eng.Engine, NativeEngine):
        before = tele.REGISTRY.flat_counters()
        e = cls()
        try:
            out = e.synchronize(
                e.allreduce_async("q/parity", data, average=False))
        finally:
            e.shutdown()
        after = tele.REGISTRY.flat_counters()
        digests.append(hashlib.sha256(out.tobytes()).hexdigest())
        wires.append((
            after.get("engine.wire_bytes", 0)
            - before.get("engine.wire_bytes", 0),
            after.get("engine.wire_bytes.compressed", 0)
            - before.get("engine.wire_bytes.compressed", 0)))
    assert digests[0] == digests[1], digests
    assert wires[0] == wires[1], wires
    wire, compressed = wires[0]
    assert compressed == wire > 0
    assert data.nbytes / wire >= 3.9, (data.nbytes, wire)


def test_engine_env_policy_fail_fast(hvd, monkeypatch):
    from horovod_tpu.core import engine as eng

    monkeypatch.setenv("HVD_COMPRESSION", "int9")
    with pytest.raises(eng.EngineError, match="int9"):
        eng.Engine()


def test_negotiation_mixed_policy_fails_by_name():
    """Mixed wire policies across processes fail fast at negotiation,
    naming the tensor and both policies (the HVD_CACHE_CAPACITY
    precedent: a misconfiguration, not a hang)."""
    from horovod_tpu.core import coordinator as coord

    def meta(compression):
        return coord.RequestMeta(name="grad/0", op="allreduce",
                                 dtype="float32", itemsize=4, shape=(8,),
                                 compression=compression)

    groups = coord.decide({0: [meta("int8")], 1: [meta("none")]},
                          [meta("int8")], 1 << 20)
    errs = [g for g in groups if g.error]
    assert errs, groups
    assert "wire compression policies" in errs[0].error
    assert "grad/0" in errs[0].error and "int8" in errs[0].error


def test_fusion_groups_split_by_policy():
    """Fused batches must be policy-uniform: the fusion key (both
    engines and the coordinator's _fuse_names) includes the wire
    policy."""
    from horovod_tpu.core import coordinator as coord

    metas = [coord.RequestMeta(name=f"g/{i}", op="allreduce",
                               dtype="float32", itemsize=4, shape=(8,),
                               nbytes=32,
                               compression="int8" if i % 2 else "none")
             for i in range(4)]
    groups = coord._fuse_names(metas, 1 << 20)
    for g in groups:
        pols = {m.compression for m in metas if m.name in g}
        assert len(pols) == 1, groups


def test_xplane_dtype_split_attributes_payload_and_scales():
    """Telemetry satellite: the xplane --hbm per-dtype accounting splits
    the int8 payload from the f32 scales (s8 vs f32 columns) — the
    compiled-path equivalent of the engine.wire_bytes counters."""
    from horovod_tpu.utils import xplane

    name = ("%fusion.1 = s8[4096]{0} fusion(s8[4096]{0} %a), "
            "f32[8]{0} %scales")
    by = xplane._hbm_shape_bytes_by_dtype(name)
    assert by["s8"] == 2 * 4096 and by["f32"] == 32


def test_quantized_policy_ships_nonfloat_full_width(hvd):
    """Integer payloads have no quantized form: a quantized policy must
    ship them full width (exact), not trip the quantized compressor's
    deliberate NotImplementedError."""
    x = jnp.arange(8, dtype=jnp.int32)
    out = hj.allreduce(x, average=False, compression=Compression.int8)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(8) * hvd.size())
    tree = {"f": jnp.ones((600,), jnp.float32), "i": x}
    red = hj.allreduce_pytree(tree, average=False,
                              compression=Compression.int8)
    np.testing.assert_array_equal(np.asarray(red["i"]),
                                  np.arange(8) * hvd.size())


def test_select_none_pins_engine_wire():
    """select() members are explicit: a 'none' entry pins the engine
    wire to full width even under an HVD_COMPRESSION default, while the
    implicit Compression.none keeps deferring to the env knob."""
    sel = Compression.select("int8", **{"bn*": "none"})
    assert sel.for_tensor("bn.gamma").engine_wire == "none"
    assert sel.for_tensor("conv.w").engine_wire == "int8"
    assert Compression.none.engine_wire is None


def test_allgather_broadcast_exact_under_wire_policy(hvd, monkeypatch):
    """Only allreduce has a quantized reduction: with HVD_COMPRESSION
    set, allgather/broadcast stay full width and bit-exact on BOTH
    engines (and negotiate as 'none', matching the python twin)."""
    from horovod_tpu.core import engine as eng
    from horovod_tpu.core.native_engine import NativeEngine

    monkeypatch.setenv("HVD_COMPRESSION", "int8")
    data = np.linspace(-1.0, 1.0, 100).astype(np.float32)
    for cls in (eng.Engine, NativeEngine):
        e = cls()
        try:
            g = e.synchronize(e.allgather_async("ag/x", data))
            b = e.synchronize(e.broadcast_async("bc/x", data, 0))
        finally:
            e.shutdown()
        assert g.shape == (hvd.size() * 100,)
        np.testing.assert_array_equal(b, data)


def test_shard_update_rejects_per_tensor_policy(hvd):
    with pytest.raises(ValueError, match="per-tensor"):
        hj.shard_update(optax.sgd(0.1),
                        compression=Compression.select("int8"))


def test_world_size_one_eager_elides_quantize(hvd, monkeypatch):
    """Eager degenerate branch: at world size 1 the quantized policy's
    update equals the uncompressed one BITWISE (no quantize round trip)
    and the error-feedback residuals pass through untouched."""
    from horovod_tpu.jax import sharded as _sh

    monkeypatch.setattr(_sh, "_world", lambda: 1)
    params = _tree()
    g = jax.tree_util.tree_map(lambda l: l * 0.01 + 0.05, params)
    outs = {}
    for nm, comp in (("none", Compression.none), ("int8", SmallInt8EF)):
        opt = hj.shard_update(optax.sgd(0.1), compression=comp)
        state = opt.init(params)
        u, s2 = opt.update(g, state, params)
        outs[nm] = u
        if nm == "int8":
            for k in s2["qres"]["g"]:
                np.testing.assert_array_equal(
                    np.asarray(s2["qres"]["g"][k]), 0.0)
    for a, b in zip(jax.tree_util.tree_leaves(outs["none"]),
                    jax.tree_util.tree_leaves(outs["int8"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_state_dtype_composes_with_int8(hvd):
    """The composed layout (quantized + bf16 residents + f32 master
    shards): state is {"qres", "base"={"master","inner"}}, the helpers
    unwrap it, and a compiled step runs."""
    params = hj.cast_resident_params(_tree(), "bf16")
    opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  sharded_update=True, state_dtype="bf16",
                                  compression=SmallInt8EF)
    state = opt.init(params)
    assert set(state) == {"qres", "base"}
    assert hj.has_master_shards(state)
    rebuilt = hj.resident_from_masters(state, params)
    for a, b in zip(jax.tree_util.tree_leaves(rebuilt),
                    jax.tree_util.tree_leaves(params)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32))
    gstack = _stack(params, hj.size())
    new_p, new_s = _spmd_step(opt, state)(params, state, gstack)
    assert set(new_s) == {"qres", "base"}
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(params)):
        assert a.dtype == b.dtype
        assert not np.array_equal(np.asarray(a, np.float32),
                                  np.asarray(b, np.float32))
