"""TF frontend parity tests (the role of the reference's
test/test_tensorflow.py: value tests, gradient tests, optimizer
integration — reference: test_tensorflow.py:56-119,321-346,591-624)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402


@pytest.fixture(autouse=True)
def _init(hvd):
    yield


def test_allreduce_sum_and_average():
    x = tf.constant([1.0, 2.0, 3.0])
    s = hvd_tf.allreduce(x, average=False)
    np.testing.assert_allclose(s.numpy(), np.array([8.0, 16.0, 24.0]))
    a = hvd_tf.allreduce(x, average=True)
    np.testing.assert_allclose(a.numpy(), x.numpy(), rtol=1e-6)


def test_allreduce_int():
    x = tf.constant([2, 4], tf.int32)
    s = hvd_tf.allreduce(x, average=False)
    np.testing.assert_array_equal(s.numpy(), [16, 32])


def test_allreduce_fp16_compression():
    x = tf.constant([1.0, 2.0], tf.float32)
    out = hvd_tf.allreduce(x, average=True,
                           compression=hvd_tf.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-2)


def test_allreduce_int8_engine_wire():
    """Quantized policy (ISSUE 12): block-scaled int8 applied in the
    ENGINE's execution chunks — the TF surface accepts the name/class
    and the reduced value tracks the input within one quantization
    step."""
    x = tf.constant(np.linspace(-2.0, 2.0, 600), tf.float32)
    out = hvd_tf.allreduce(x, average=True,
                           compression=hvd_tf.Compression.int8)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=2.0 / 127)


def test_compression_unknown_name_fails_fast_naming_rank():
    """Satellite pin: a bad compressor fails at resolution with rank
    attribution, not as an attribute error mid-step."""
    with pytest.raises(ValueError, match="rank|pid"):
        hvd_tf.Compression.resolve("int9")
    with pytest.raises(ValueError, match="rank|pid"):
        hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), compression="bogus")


def test_allgather():
    x = tf.constant([[1.0, 2.0]])
    g = hvd_tf.allgather(x)
    assert g.shape == (8, 2)
    np.testing.assert_allclose(g.numpy(), np.tile([[1.0, 2.0]], (8, 1)))


def test_broadcast():
    x = tf.constant([5.0, 6.0])
    b = hvd_tf.broadcast(x, root_rank=0)
    np.testing.assert_allclose(b.numpy(), x.numpy())
    with pytest.raises(ValueError):
        hvd_tf.broadcast(x, root_rank=99)


def test_allreduce_gradient():
    """Reference: gradient of allreduce is allreduce
    (test_tensorflow.py:321-346)."""
    x = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        y = hvd_tf.allreduce(x, average=False)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, x)
    # The registered gradient REPLACES the chain rule (reference:
    # mpi_ops.py:94-105): upstream dy=1 is itself allreduced(SUM) over the
    # 8 ranks -> 8 per element.
    np.testing.assert_allclose(g.numpy(), np.full(2, 8.0))


def test_allgather_gradient():
    x = tf.Variable([[1.0, 2.0]])
    with tf.GradientTape() as tape:
        y = hvd_tf.allgather(x)
        loss = tf.reduce_sum(y * 2.0)
    g = tape.gradient(loss, x)
    assert g.shape == (1, 2)
    # Every gathered copy contributes 2; summed over 8 ranks -> 16.
    np.testing.assert_allclose(g.numpy(), np.full((1, 2), 16.0))


def test_broadcast_gradient_root():
    x = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        y = hvd_tf.broadcast(x, root_rank=0)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, x)
    # The root's controller receives the allreduced grad; every other
    # controller zeros (reference: mpi_ops.py:168-183 — under the
    # launcher's -np 2 world this file also runs as a non-root process).
    expect = 8.0 if hvd_tf.rank() == 0 else 0.0
    np.testing.assert_allclose(g.numpy(), np.full(2, expect))


def test_allreduce_gradient_average_and_cotangent():
    """Non-uniform upstream cotangents, both reduction modes (reference
    multiplies by a random tensor before reducing,
    test_tensorflow.py:321-346)."""
    c = tf.constant([3.0, 5.0])
    x = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.allreduce(x, average=False) * c)
    g = tape.gradient(loss, x)
    np.testing.assert_allclose(g.numpy(), c.numpy() * 8)
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.allreduce(x, average=True) * c)
    g = tape.gradient(loss, x)
    # average mode: backward averages the cotangent over ranks -> exactly c.
    np.testing.assert_allclose(g.numpy(), c.numpy(), rtol=1e-6)


def test_allgather_gradient_cotangent_slices():
    """Backward of allgather reduces the cotangent then slices this
    rank's rows (reference: mpi_ops.py:127-148)."""
    x = tf.Variable([[1.0, 2.0]])
    w = tf.reshape(tf.range(1.0, 17.0), (8, 2))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.allgather(x) * w)
    g = tape.gradient(loss, x)
    # Every rank contributes w; this controller's slice is its first
    # chip's row (host-side API semantics: the controller acts as its
    # first chip — rank 0 single-process, rank 4 for the launcher's
    # second process).
    r = hvd_tf.rank()
    np.testing.assert_allclose(g.numpy(), w[r:r + 1].numpy() * 8)


def test_allgather_gradient_unequal_first_dims():
    """Ranks may contribute DIFFERENT first dims; the backward must split
    the reduced cotangent by the true per-rank sizes, not an equal split
    (reference: mpi_ops.py:127-148). Under the launcher's -np 2 world the
    two controllers genuinely contribute 1 vs 2 rows; single-process all
    chips agree (the path still runs end to end)."""
    r = hvd_tf.rank()
    rows = 1 if r == 0 else 2
    x = tf.Variable(np.full((rows, 2), float(r + 1), np.float32))
    with tf.GradientTape() as tape:
        y = hvd_tf.allgather(x)
        # Cotangent = the global row index, so a mis-sliced backward is
        # numerically visible, not just shape-wrong.
        w = tf.reshape(tf.range(tf.shape(y)[0], dtype=tf.float32), [-1, 1])
        loss = tf.reduce_sum(y * w)
    g = tape.gradient(loss, x)
    assert g.shape == (rows, 2)
    dims = hvd_tf.allgather(tf.constant([rows], tf.int32)).numpy()
    offset = int(dims[:r].sum())
    expect = 8.0 * np.arange(offset, offset + rows, dtype=np.float32)
    np.testing.assert_allclose(g.numpy(), np.tile(expect[:, None], (1, 2)))


def test_allgather_scalar_input():
    """A rank-0 input rides the >=1-d wire as one gathered row apiece
    (r4 advisor finding: the scalar path skipped atleast_1d and declared
    a scalar static shape)."""
    x = tf.Variable(3.0)
    with tf.GradientTape() as tape:
        y = hvd_tf.allgather(x)
        loss = tf.reduce_sum(y)
    assert y.shape.rank == 1
    np.testing.assert_allclose(y.numpy(), np.full(int(y.shape[0]), 3.0))
    g = tape.gradient(loss, x)
    assert g.shape.rank == 0
    np.testing.assert_allclose(g.numpy(), 8.0)


def test_sparse_allreduce_indexed_slices():
    """Reference sparse path: IndexedSlices -> allgather
    (tensorflow/__init__.py:48-94)."""
    v = tf.IndexedSlices(values=tf.constant([[1.0, 1.0]]),
                         indices=tf.constant([3]),
                         dense_shape=tf.constant([10, 2]))
    out = hvd_tf.allreduce(v, average=True)
    assert isinstance(out, tf.IndexedSlices)
    assert out.values.shape == (8, 2)
    np.testing.assert_allclose(out.values.numpy(),
                               np.full((8, 2), 1.0 / 8))
    assert set(out.indices.numpy()) == {3}


def test_distributed_gradient_tape_trains():
    w = tf.Variable([[0.5], [0.5]])
    x = tf.constant(np.random.RandomState(0).randn(16, 2), tf.float32)
    y = x @ np.array([[1.0], [-1.0]], np.float32)
    losses = []
    opt = tf.keras.optimizers.SGD(0.1)
    for _ in range(20):
        with hvd_tf.DistributedGradientTape() as tape:
            loss = tf.reduce_mean((x @ w - y) ** 2)
        g = tape.gradient(loss, [w])
        opt.apply_gradients(zip(g, [w]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_distributed_optimizer_trains():
    # Seeded PER-RANK initializers: under the launcher's -np 2 world this
    # test runs in two processes, and an unseeded init made the 25-step
    # loss-halving assertion nondeterministic (flaked once in the r3
    # full-suite run). Seeding by rank keeps the controllers genuinely
    # divergent (so broadcast_variables below still has real work — the
    # coverage the comment under it claims) while making the
    # post-broadcast trajectory exactly rank 0's, every run.
    seed = 7 + 2 * hvd_tf.rank()
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(
            4, activation="relu", input_shape=(3,),
            kernel_initializer=tf.keras.initializers.GlorotUniform(seed)),
         tf.keras.layers.Dense(
             1,
             kernel_initializer=tf.keras.initializers.GlorotUniform(seed + 1))])
    opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
    # Controllers initialize with different random weights; start agreed
    # (the reference's canonical startup, horovod/tensorflow/__init__.py
    # BroadcastGlobalVariablesHook) — without this, averaged gradients in
    # the launcher's -np 2 world descend a mixture of two models.
    hvd_tf.broadcast_variables(model.trainable_variables, root_rank=0)
    x = tf.constant(np.random.RandomState(1).randn(32, 3), tf.float32)
    y = tf.reduce_sum(x, axis=1, keepdims=True)
    losses = []
    for _ in range(25):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean((model(x) - y) ** 2)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5


def test_broadcast_variables_and_callback():
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0]])
    hvd_tf.broadcast_variables([v1, v2], root_rank=0)
    np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])

    model = tf.keras.Sequential([tf.keras.layers.Dense(2, input_shape=(2,))])
    model.compile(optimizer="sgd", loss="mse")
    x = np.zeros((8, 2), np.float32)
    y = np.zeros((8, 2), np.float32)
    model.fit(x, y, epochs=1, batch_size=4, verbose=0,
              callbacks=[hvd_tf.BroadcastGlobalVariablesCallback(0)])


def test_tf_function_graph_mode():
    """Collectives must work inside tf.function graphs (the reference's
    graph-mode op registration — tensorflow/mpi_ops.cc)."""

    @tf.function
    def step(x):
        return hvd_tf.allreduce(x, average=False)

    out = step(tf.constant([1.0, 1.0]))
    np.testing.assert_allclose(out.numpy(), [8.0, 8.0])


def _assert_weights_agree_across_ranks(model):
    """Allgather the flattened kernel; every chip's copy must match."""
    w = tf.reshape(model.layers[-1].kernel, [1, -1])
    rows = hvd_tf.allgather(w).numpy()
    np.testing.assert_allclose(rows, np.tile(rows[:1], (rows.shape[0], 1)),
                               rtol=1e-6)


def test_load_model_restores_wrapped_optimizer(tmp_path):
    """Round trip: save a model compiled with DistributedOptimizer, load
    via hvd.load_model, and the optimizer must still allreduce — with
    its slot state intact. A plain keras load silently restores an
    unwrapped optimizer (reference: horovod/keras/__init__.py:118-148,
    _keras/__init__.py:93-109)."""
    r = hvd_tf.rank()
    model = tf.keras.Sequential([tf.keras.Input((3,)),
                                 tf.keras.layers.Dense(2)])
    model.compile(
        optimizer=hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.05, momentum=0.9)),
        loss="mse")
    hvd_tf.broadcast_variables(model.trainable_variables, root_rank=0)
    # Rank-dependent data: only a reducing optimizer keeps ranks agreed.
    rng = np.random.RandomState(3 + r)
    x = rng.randn(8, 3).astype(np.float32)
    y = rng.randn(8, 2).astype(np.float32)
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)
    _assert_weights_agree_across_ranks(model)

    path = str(tmp_path / "model.keras")
    model.save(path)
    slot_state = [np.array(v) for v in model.optimizer.variables]

    loaded = hvd_tf.load_model(path)
    assert getattr(type(loaded.optimizer), "_hvd_wrapped", False)
    assert type(loaded.optimizer).__name__ == "SGD"  # save/load symmetric
    for a, b in zip(loaded.optimizer.variables, slot_state):
        np.testing.assert_allclose(np.array(a), b)
    # A further step on rank-DIVERGENT data must stay agreed: the loaded
    # optimizer still reduces.
    loaded.fit(x, y, epochs=1, batch_size=8, verbose=0)
    _assert_weights_agree_across_ranks(loaded)


def test_load_model_wraps_plain_saved_optimizer(tmp_path):
    """A model saved with an UNWRAPPED optimizer loads wrapped — the
    reference's load_model wraps whatever deserializes."""
    model = tf.keras.Sequential([tf.keras.Input((3,)),
                                 tf.keras.layers.Dense(2)])
    model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
    path = str(tmp_path / "plain.keras")
    model.save(path)
    loaded = hvd_tf.load_model(path)
    assert getattr(type(loaded.optimizer), "_hvd_wrapped", False)
    assert type(loaded.optimizer).__name__ == "Adam"


def test_user_supplied_names_are_stable_keys():
    """A user-supplied name is the engine matching key, carried in its
    own namespace so a numeric name can never collide with an unnamed
    op's auto counter; results stay correct across repeated use."""
    x = tf.constant([1.0, 2.0])
    for _ in range(2):  # same name reused sequentially = the per-step
        out = hvd_tf.allreduce(x, average=False, name="0")
        np.testing.assert_allclose(out.numpy(), [8.0, 16.0])
    g = hvd_tf.allgather(tf.constant([[3.0]]), name="rows")
    assert g.shape[0] == 8
    b = hvd_tf.broadcast(tf.constant([7.0]), root_rank=0, name="b0")
    np.testing.assert_allclose(b.numpy(), [7.0])


def test_bridge_names_scoped_per_graph():
    """Sequence counters are scoped to the graph under construction, so
    a RE-trace rebuilds the same engine names instead of marching a
    process-global counter past the peers' (r4 advisor finding)."""
    from horovod_tpu.tensorflow import mpi_ops as ops

    g1, g2 = tf.Graph(), tf.Graph()
    with g1.as_default():
        first = ops._group_names("allreduce", ["w", "b"])
        second = ops._group_names("allreduce", ["w"])
    with g2.as_default():  # a retrace = a fresh graph
        retraced = ops._group_names("allreduce", ["w", "b"])
    assert first == ["tf.allreduceg0.w", "tf.allreduceg0.b"]
    assert second == ["tf.allreduceg1.w"]  # later group, same graph
    assert retraced == first  # fresh graph restarts the sequence


def test_tf_function_asymmetric_retrace_keeps_collectives_paired():
    """Only SOME processes retrace (a different batch shape, e.g. a
    partial final batch with drop_remainder=False); the gradient
    allreduce inside must still pair across processes. A process-global
    name counter permanently desynced here (r4 advisor); graph-scoped
    counters rebuild identical names. Under the launcher's -np 2 world
    the second controller genuinely retraces while the first does not."""

    @tf.function
    def step(batch):
        g = tf.reduce_sum(batch, axis=0)  # weight-shaped: [2]
        return hvd_tf.allreduce(g, average=False)

    r = hvd_tf.rank()
    out1 = step(tf.ones([4, 2]))
    # Expected = sum of every chip's contribution, computed via the
    # (independently tested) eager allgather.
    rows = 4 if r == 0 else 2
    mine = np.full((1, 2), float(rows), np.float32)
    expect = hvd_tf.allgather(tf.constant(mine)).numpy().sum(axis=0)
    # Non-zero controllers see a second SHAPE -> only they retrace.
    out2 = step(tf.ones([rows, 2]))
    np.testing.assert_allclose(out2.numpy(), expect)
    assert out1.shape == out2.shape


def test_v1_session_skeleton_runs_unmodified(hvd):
    """The reference example's session-era training skeleton — v1 graph,
    placeholder feed, tf.compat.v1.train optimizer wrapped by
    DistributedOptimizer, BroadcastGlobalVariablesHook inside
    MonitoredTrainingSession — ports without edits (reference:
    examples/tensorflow_mnist.py:113-156; VERDICT r2 missing #4)."""
    import numpy as np
    import horovod_tpu.tensorflow as hvd_tf

    tf1 = tf.compat.v1
    graph = tf.Graph()
    with graph.as_default():
        image = tf1.placeholder(tf.float32, [None, 16], name="image")
        label = tf1.placeholder(tf.float32, [None], name="label")
        w = tf1.get_variable("w", [16, 1],
                             initializer=tf1.random_normal_initializer(seed=1))
        b = tf1.get_variable("b", [1], initializer=tf1.zeros_initializer())
        pred = tf.squeeze(tf.matmul(image, w), axis=1) + b
        loss = tf.reduce_mean(tf.square(pred - label))

        opt = tf1.train.GradientDescentOptimizer(0.002 * hvd_tf.size())
        opt = hvd_tf.DistributedOptimizer(opt)
        global_step = tf1.train.get_or_create_global_step()
        train_op = opt.minimize(loss, global_step=global_step)

        hooks = [hvd_tf.BroadcastGlobalVariablesHook(0),
                 tf1.train.StopAtStepHook(last_step=5)]
        rng = np.random.RandomState(0)
        x = rng.randn(8, 16).astype(np.float32)
        y = (x.sum(axis=1) * 0.1).astype(np.float32)
        losses = []
        with tf1.train.MonitoredTrainingSession(hooks=hooks) as sess:
            while not sess.should_stop():
                _, lv = sess.run([train_op, loss],
                                 feed_dict={image: x, label: y})
                losses.append(lv)
    assert len(losses) == 5
    assert losses[-1] < losses[0]  # it actually trains


def test_broadcast_global_variables_v1_collection(hvd):
    """broadcast_global_variables(0) works whenever the v1 collection is
    populated (VERDICT r2 weak #4); pure-eager TF2 still gets the guided
    NotImplementedError."""
    import horovod_tpu.tensorflow as hvd_tf

    graph = tf.Graph()
    with graph.as_default():
        v = tf.compat.v1.get_variable(
            "bgv_v", [4], initializer=tf.compat.v1.ones_initializer())
        op = hvd_tf.broadcast_global_variables(0)
        with tf.compat.v1.Session() as sess:
            sess.run(tf.compat.v1.global_variables_initializer())
            sess.run(op)
            out = sess.run(v)
    np.testing.assert_allclose(out, np.ones(4))

    with pytest.raises(NotImplementedError):
        hvd_tf.broadcast_global_variables(0)  # eager: no collection


def test_optimizer_mixed_sparse_dense_gradients():
    """An embedding (IndexedSlices gradient) + dense layer step: both
    gradient kinds must ride the SAME grouped py_function — separate
    sparse nodes would re-create the sequential-executor cross-rank
    wedge the grouping fixes (r4; see mpi_ops._bridge_group). Under the
    launcher's -np 2 world this also exercises the cross-controller
    negotiation of the mixed group."""
    emb = tf.Variable(
        tf.keras.initializers.GlorotUniform(11)((6, 4)), name="emb")
    w = tf.Variable(tf.keras.initializers.GlorotUniform(12)((4, 1)),
                    name="w")
    hvd_tf.broadcast_variables([emb, w], root_rank=0)
    ids = tf.constant([0, 2, 2, 5])
    y = tf.constant([[1.0], [0.0], [0.0], [2.0]])
    untouched_row = np.asarray(emb)[1].copy()  # never looked up below

    opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    losses = []
    for _ in range(15):
        with tf.GradientTape() as tape:
            h = tf.nn.embedding_lookup(emb, ids)
            loss = tf.reduce_mean((h @ w - y) ** 2)
        grads = tape.gradient(loss, [emb, w])
        assert isinstance(grads[0], tf.IndexedSlices)
        opt.apply_gradients(zip(grads, [emb, w]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, losses
    # Row 1 of the embedding is never looked up: sparse updates must not
    # have touched it on any rank (compared against its PRE-training
    # value — a wrong scatter index or dense-averaging bug would).
    np.testing.assert_allclose(np.asarray(emb)[1], untouched_row)


def test_distributed_gradient_tape_sparse():
    """The tape path reduces IndexedSlices through the same grouped
    bridge (reference sparse semantics: allgather of values+indices,
    horovod/tensorflow/__init__.py:48-94)."""
    emb = tf.Variable(tf.ones((4, 2)), name="emb2")
    ids = tf.constant([1, 3])
    with hvd_tf.DistributedGradientTape() as tape:
        loss = tf.reduce_sum(tf.nn.embedding_lookup(emb, ids))
    g = tape.gradient(loss, [emb])[0]
    assert isinstance(g, tf.IndexedSlices)
    # 8 ranks each contribute ones at rows {1,3}; average mode divides
    # values by size -> gathered values are all 1/8... averaged to 1.0
    # equivalents when scattered. Check the dense equivalent.
    dense = tf.math.unsorted_segment_sum(
        g.values, g.indices, num_segments=4)
    np.testing.assert_allclose(np.asarray(dense)[1], np.ones(2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dense)[0], np.zeros(2))
