"""Worker script for multi-process distributed tests: 2 controller
processes × 4 virtual CPU chips = an 8-chip world. The TPU analogue of the
reference's `mpirun -np N` test tier (SURVEY.md §4)."""

import os
import sys


def main():
    port = sys.argv[1]
    pid = int(sys.argv[2])
    nproc = int(sys.argv[3])
    scenario = sys.argv[4]

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)

    import numpy as np
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    assert hvd.size() == 4 * nproc, hvd.size()
    assert hvd.num_processes() == nproc
    assert hvd.cross_rank() == pid
    assert hvd.local_size() == 4

    if scenario == "collectives":
        # allreduce: each process's chips contribute its value.
        mine = float(pid + 1)
        out = np.asarray(hvd.allreduce(jnp.full((3,), mine), average=False))
        expect = 4 * sum(range(1, nproc + 1))
        np.testing.assert_allclose(out, np.full((3,), expect))

        # broadcast from a chip owned by process 1.
        val = jnp.full((2,), float(pid) + 10.0)
        out = np.asarray(hvd.broadcast(val, root_rank=4))  # proc 1's chip
        np.testing.assert_allclose(out, np.full((2,), 11.0))

        # allgather with DIFFERENT first dims per process (the
        # size-exchange + pad + strip path).
        rows = pid + 1
        g = np.asarray(hvd.allgather(
            jnp.full((rows, 2), float(pid))))
        # Each of the 4 local chips contributes this controller's tensor.
        expect_rows = sum(4 * (p + 1) for p in range(nproc))
        assert g.shape == (expect_rows, 2), g.shape

        # broadcast_object (pickle path).
        import horovod_tpu.jax as hvd_jax

        obj = hvd_jax.broadcast_object(
            {"epoch": 7, "who": "proc0"} if pid == 0 else None, root_rank=0)
        assert obj == {"epoch": 7, "who": "proc0"}

        # Engine path: async allreduce with fusion force-disabled.
        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        assert e.fusion_threshold == 0, e.fusion_threshold
        hs = [e.allreduce_async(f"t{i}", np.ones((4,), np.float32), False)
              for i in range(3)]
        for h in hs:
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((4,), 4.0 * nproc))
    elif scenario == "mismatch":
        os.environ["HVD_CONSISTENCY_CHECKS"] = "1"
        from horovod_tpu.common.topology import HorovodInternalError

        # Matching op first: must pass.
        hvd.allreduce(jnp.ones((2,)), average=False)
        # Then a shape mismatch: every process must raise.
        shape = (2,) if pid == 0 else (3,)
        try:
            hvd.allreduce(jnp.ones(shape), average=False)
        except HorovodInternalError:
            print(f"proc {pid}: mismatch detected OK", flush=True)
        else:
            raise SystemExit("consistency check did not fire")
    else:
        raise SystemExit(f"unknown scenario {scenario}")

    print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)


if __name__ == "__main__":
    main()
