"""Worker script for multi-process distributed tests: 2 controller
processes × 4 virtual CPU chips = an 8-chip world. The TPU analogue of the
reference's `mpirun -np N` test tier (SURVEY.md §4)."""

import os
import sys


def main():
    port = sys.argv[1]
    pid = int(sys.argv[2])
    nproc = int(sys.argv[3])
    scenario = sys.argv[4]

    local_devices = int(os.environ.get("HVD_TEST_LOCAL_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={local_devices}")
    if scenario.startswith("engine"):
        # Timeline must be configured before hvd.init() (the engine is
        # created there in multi-controller worlds). Tests may pass
        # their own HVD_TIMELINE (dir mode — the distributed-tracing
        # scenarios); default to the legacy per-pid file otherwise.
        os.environ.setdefault(
            "HVD_TIMELINE", f"/tmp/hvd_timeline_{scenario}_{pid}.json")
    if scenario == "host_split":
        # Two controllers per SIMULATED host (np=4 -> hosts A,A,B,B) —
        # must be set before hvd.init() reads it.
        os.environ["HVD_HOSTNAME"] = f"simhost{pid // 2}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)

    import numpy as np
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    assert hvd.size() == local_devices * nproc, hvd.size()
    assert hvd.num_processes() == nproc
    assert hvd.local_size() == local_devices
    if not os.environ.get("HVD_HOSTNAME"):
        # All spawned processes genuinely share this machine: the
        # shared-host split must see ONE host with nproc controllers
        # (reference: operations.cc:1668-1705).
        assert hvd.cross_rank() == 0, hvd.cross_rank()
        assert hvd.cross_size() == 1
        assert hvd.local_rank() == pid, hvd.local_rank()
        assert hvd.local_num_processes() == nproc

    if scenario == "host_split":
        # 2 controllers per simulated host: local_rank splits co-hosted
        # controllers, cross_* enumerates hosts (VERDICT r4 missing #3;
        # reference: operations.cc:1668-1705).
        n_hosts = (nproc + 1) // 2
        assert hvd.cross_size() == n_hosts, hvd.cross_size()
        assert hvd.cross_rank() == pid // 2, hvd.cross_rank()
        assert hvd.local_rank() == pid % 2, hvd.local_rank()
        assert hvd.local_num_processes() == 2
        # Per-host resource ownership: exactly ONE owner (local_rank 0)
        # per host — the cache-dir/log-ownership recipe.
        own = 1.0 if hvd.local_rank() == 0 else 0.0
        total = np.asarray(hvd.allreduce(jnp.full((1,), own),
                                         average=False))
        np.testing.assert_allclose(total, [local_devices * n_hosts])

    elif scenario == "collectives":
        # allreduce: each process's chips contribute its value.
        mine = float(pid + 1)
        out = np.asarray(hvd.allreduce(jnp.full((3,), mine), average=False))
        expect = local_devices * sum(range(1, nproc + 1))
        np.testing.assert_allclose(out, np.full((3,), expect))

        # broadcast from a chip owned by process 1.
        val = jnp.full((2,), float(pid) + 10.0)
        out = np.asarray(hvd.broadcast(
            val, root_rank=local_devices))  # proc 1's first chip
        np.testing.assert_allclose(out, np.full((2,), 11.0))

        # allgather with DIFFERENT first dims per process (the
        # size-exchange + pad + strip path).
        rows = pid + 1
        g = np.asarray(hvd.allgather(
            jnp.full((rows, 2), float(pid))))
        # Each of the 4 local chips contributes this controller's tensor.
        expect_rows = sum(local_devices * (p + 1) for p in range(nproc))
        assert g.shape == (expect_rows, 2), g.shape

        # broadcast_object (pickle path).
        import horovod_tpu.jax as hvd_jax

        obj = hvd_jax.broadcast_object(
            {"epoch": 7, "who": "proc0"} if pid == 0 else None, root_rank=0)
        assert obj == {"epoch": 7, "who": "proc0"}

        # Engine path: with negotiation (the default in multi-controller
        # worlds) fusion stays ENABLED; batch composition is agreed
        # through KV rounds (core/coordinator.py).
        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        assert e.fusion_threshold > 0, e.fusion_threshold
        hs = [e.allreduce_async(f"t{i}", np.ones((4,), np.float32), False)
              for i in range(3)]
        for h in hs:
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((4,), float(local_devices * nproc)))
    elif scenario == "collectives_nonegotiation":
        # HVD_NEGOTIATION=0 (set by the test): the fallback multi-
        # controller engine path must force fusion OFF and still agree.
        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        assert e.fusion_threshold == 0, e.fusion_threshold
        hs = [e.allreduce_async(f"t{i}", np.ones((4,), np.float32), False)
              for i in range(3)]
        for h in hs:
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((4,), float(local_devices * nproc)))
    elif scenario == "engine_fusion":
        # Negotiated fusion across controllers (reference: the rank-0
        # coordinator's fused responses, operations.cc:2035-2074): both
        # processes enqueue the same names with different values; results
        # must be identical everywhere and the engine must actually fuse.
        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        assert e.fusion_threshold > 0
        vals = [float(10 * i + pid + 1) for i in range(4)]
        hs = [e.allreduce_async(f"grad/{i}", np.full((8,), v, np.float32),
                                False)
              for i, v in enumerate(vals)]
        hg = e.allgather_async("gath", np.full((pid + 1, 2), float(pid),
                                               np.float32))
        hb = e.broadcast_async("bcast", np.full((3,), float(pid) + 5.0,
                                                np.float32), 0)
        outs = [e.synchronize(h) for h in hs]
        for i, out in enumerate(outs):
            # Each process's chips contribute its value once each.
            expect = local_devices * sum(10 * i + p + 1 for p in range(nproc))
            np.testing.assert_array_equal(out, np.full((8,), expect))
        g = e.synchronize(hg)
        assert g.shape == (
            sum(local_devices * (p + 1) for p in range(nproc)), 2)
        np.testing.assert_array_equal(e.synchronize(hb),
                                      np.full((3,), 5.0))
        # Bitwise agreement across processes (the test compares lines).
        print("RESULT " + ",".join(str(float(o[0])) for o in outs),
              flush=True)
        # The timeline must show fusion actually happened.
        import json

        eng.shutdown_engine()
        evs = json.load(open(os.environ["HVD_TIMELINE"]))
        assert any(ev.get("name") == "MEMCPY_IN_FUSION_BUFFER"
                   for ev in evs), "no fused batch in timeline"
        assert any(str(ev.get("name", "")).startswith("NEGOTIATE_")
                   for ev in evs), "no negotiation phases in timeline"
    elif scenario == "engine_mismatch":
        # Cross-process dtype/shape/root mismatches must surface the SAME
        # coordinator-style error on EVERY process (reference:
        # test_torch.py:265-349, operations.cc:315-517), and the engine
        # must stay usable afterwards.
        from horovod_tpu.core import engine as eng
        from horovod_tpu.core.engine import EngineError

        e = eng.get_engine()

        def expect_error(h, needle):
            try:
                e.synchronize(h)
            except EngineError as err:
                assert needle in str(err), (needle, str(err))
                print(f"proc {pid}: {needle} OK", flush=True)
            else:
                raise SystemExit(f"no error surfaced for {needle}")

        dt = np.float32 if pid == 0 else np.float64
        expect_error(e.allreduce_async("dt", np.ones((4,), dt), False),
                     "Mismatched data types")
        shape = (4,) if pid == 0 else (2, 2)
        expect_error(e.allreduce_async("shp", np.ones(shape, np.float32),
                                       False),
                     "Mismatched tensor shapes")
        expect_error(e.broadcast_async("rt", np.ones((2,), np.float32),
                                       root_rank=pid),
                     "Mismatched root ranks")
        op_h = (e.allreduce_async("op", np.ones((2,), np.float32), False)
                if pid == 0 else
                e.allgather_async("op", np.ones((2,), np.float32)))
        expect_error(op_h, "Mismatched collective operations")
        # Engine must still work after entry-level errors.
        h = e.allreduce_async("after", np.ones((4,), np.float32), False)
        np.testing.assert_allclose(e.synchronize(h),
                                   np.full((4,), float(local_devices * nproc)))
    elif scenario == "engine_priority":
        # Serving-plane coherence across controllers: (a) a world that
        # disagrees on a tensor's priority class fails fast BY NAME on
        # every process (priority is part of the negotiation
        # fingerprint — the HVD_COMPRESSION precedent), (b) the engine
        # stays usable, and (c) an agreeing mixed-class workload
        # completes with correct results (fused batches are composed
        # priority-uniform by the shared _fuse_names key).
        from horovod_tpu.core import engine as eng
        from horovod_tpu.core.engine import EngineError

        e = eng.get_engine()
        h = e.allreduce_async("prio.skew", np.ones((4,), np.float32),
                              False,
                              priority="high" if pid == 0 else "low")
        try:
            e.synchronize(h)
        except EngineError as err:
            assert "priority classes" in str(err), str(err)
            assert "prio.skew" in str(err), str(err)
            print(f"proc {pid}: priority mismatch OK", flush=True)
        else:
            raise SystemExit("no error surfaced for mixed priorities")
        expect = float(local_devices * sum(range(1, nproc + 1)))
        handles = {}
        for cls in ("low", "normal", "high"):
            handles[cls] = e.allreduce_async(
                f"prio.{cls}", np.full((8,), float(pid + 1), np.float32),
                False, priority=cls)
        for cls, h in handles.items():
            np.testing.assert_allclose(e.synchronize(h),
                                       np.full((8,), expect))
        print(f"proc {pid}: agreed classes reduce OK", flush=True)
    elif scenario == "engine_stall":
        # Missing-rank stall attribution (reference: CheckForStalledTensors
        # names missing ranks, operations.cc:1535-1581): process 1 delays
        # submitting 'late'; process 0's warning must name process 1.
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        if pid == 0:
            h = e.allreduce_async("late", np.ones((2,), np.float32), False)
        else:
            time.sleep(4.0)
            h = e.allreduce_async("late", np.ones((2,), np.float32), False)
        np.testing.assert_allclose(e.synchronize(h),
                                   np.full((2,), float(local_devices * nproc)))
    elif scenario == "engine_rankready":
        # RANK_READY instants inside NEGOTIATE_* spans (reference:
        # timeline.cc:106-130): process 1 submits 'staggered' ~2 s late;
        # process 0's trace must carry a per-process readiness mark for
        # each, with p1's visibly later — the trace names who was late.
        import json as _json
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        if pid != 0:
            time.sleep(2.0)
        h = e.allreduce_async("staggered", np.ones((2,), np.float32),
                              False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))
        # Steady-state re-submission of the SAME name (the per-step
        # gradient pattern): the next instance must get fresh marks too
        # (r5 review: the cpp engine's seen-set must live per instance,
        # not per name).
        h2 = e.allreduce_async("staggered", np.ones((2,), np.float32),
                               False)
        e.synchronize(h2)
        hvd.shutdown()  # close the timeline file
        if pid == 0:
            path = os.environ["HVD_TIMELINE"]
            with open(path) as fh:
                events = [ev for ev in _json.load(fh) if ev]
            marks = [ev for ev in events
                     if ev.get("name") == "RANK_READY" and ev.get("ph") == "i"]
            first = {}
            for ev in marks:
                first.setdefault(ev["args"]["process"], ev["ts"])
            assert set(first) == set(range(nproc)), (marks, events[-20:])
            gap_s = (first[1] - first[0]) / 1e6
            assert gap_s > 1.0, f"p1 mark only {gap_s}s after p0: {marks}"
            # Both instances marked: >= 2 marks per process.
            per_proc = [sum(ev["args"]["process"] == p for ev in marks)
                        for p in range(nproc)]
            assert all(n >= 2 for n in per_proc), (per_proc, marks)
            # The mark lands on the tensor's own lane, inside its
            # negotiation window.
            lanes = {ev["pid"]: ev["args"]["name"] for ev in events
                     if ev.get("ph") == "M"
                     and ev.get("name") == "process_name"}
            assert all(lanes[ev["pid"]] == "staggered" for ev in marks)
            print(f"proc {pid}: rankready marks "
                  f"{sorted(first.items())} counts={per_proc}", flush=True)
    elif scenario == "engine_straggler":
        # Straggler attribution (ISSUE 2 acceptance): process 1 delays
        # every submission ~1 s; the telemetry straggler report — fed
        # from the negotiation round tables (the RANK_READY data) — must
        # blame process 1 with the largest cumulative imposed wait, on
        # EVERY process (each coordinator ticks rounds while idle, so
        # both sides observe p0's early announcements).
        import json as _json
        import time

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core import telemetry as tele

        e = eng.get_engine()
        for i in range(3):
            if pid == 1:
                time.sleep(1.0)
            h = e.allreduce_async(f"sg/{i}", np.ones((2,), np.float32),
                                  False)
            np.testing.assert_allclose(
                e.synchronize(h),
                np.full((2,), float(local_devices * nproc)))
        snap = tele.STRAGGLERS.snapshot()
        assert snap["tensors"] >= 3, snap
        waits = snap["wait_us"]
        assert set(waits) == set(range(nproc)), waits
        worst_pid, worst_us = tele.STRAGGLERS.worst()
        assert worst_pid == 1, (worst_pid, waits)
        # 3 submissions x ~1 s delay each; generous floor for CI jitter.
        assert worst_us > 1.5e6, waits
        assert waits[0] < worst_us / 4, waits
        # The aggregated class blames the same process, and the stall/
        # report surfaces name it.
        assert snap["by_class"]["sg/#"][1] == worst_us, snap
        assert any("process 1" in ln
                   for ln in tele.STRAGGLERS.report_lines())
        # hvd.telemetry() folds the same data in.
        assert hvd.telemetry()["straggler"]["wait_us"][1] == worst_us
        print(f"proc {pid}: STRAGGLER " + _json.dumps(
            {str(p): us for p, us in sorted(waits.items())}), flush=True)
    elif scenario == "engine_trace_merged":
        # Distributed-tracing acceptance (ISSUE 3): HVD_TIMELINE=<dir>
        # (set by the test) yields per-rank traces with aligned clocks;
        # the merged Perfetto trace shows both ranks' NEGOTIATE spans
        # for one tensor OVERLAPPING on the common base, and `trace
        # skew` blames the artificially delayed rank with a wait within
        # 20% of the telemetry straggler report's figure.
        import json as _json
        import time

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core import telemetry as tele

        tdir = os.environ["HVD_TIMELINE"]
        e = eng.get_engine()
        for i in range(3):
            if pid == 1:
                time.sleep(1.0)
            h = e.allreduce_async(f"sg/{i}", np.ones((2,), np.float32),
                                  False)
            np.testing.assert_allclose(
                e.synchronize(h),
                np.full((2,), float(local_devices * nproc)))
        tele_worst = tele.STRAGGLERS.worst()
        # Collective engine shutdown closes every rank's trace file;
        # the eager barrier below proves peers are done before merging.
        eng.shutdown_engine()
        hvd.allreduce(jnp.ones((1,)), average=False)
        if pid == 0:
            from horovod_tpu.utils import trace as trace_mod

            info = trace_mod.merge(tdir)
            assert info["files"] == nproc, info
            merged = _json.load(open(info["path"]))
            lanes = {(ev["pid"], ev["tid"]): ev["args"]["name"]
                     for ev in merged if ev.get("name") == "thread_name"}
            spans, open_b = {}, {}
            for ev in merged:
                if not str(ev.get("name", "")).startswith("NEGOTIATE_"):
                    continue
                key = (ev["pid"], lanes[(ev["pid"], ev["tid"])])
                if ev["ph"] == "B":
                    open_b.setdefault(key, []).append(ev["ts"])
                elif ev["ph"] == "E" and open_b.get(key):
                    spans.setdefault(key, []).append(
                        (open_b[key].pop(), ev["ts"]))
            # Same tensor, both ranks, overlapping on the common base
            # (clock-offset error is bounded by the recorded KV rtt —
            # far under the ~1 s negotiate window here).
            (b0, e0) = sorted(spans[(0, "sg/0")])[0]
            (b1, e1) = sorted(spans[(1, "sg/0")])[0]
            assert b0 < e1 and b1 < e0, (spans[(0, "sg/0")],
                                         spans[(1, "sg/0")])
            clocks = {ev["pid"]: ev["args"] for ev in merged
                      if ev.get("name") == "HVD_CLOCK"}
            assert set(clocks) == set(range(nproc)), clocks
            assert clocks[1].get("rtt_us", -1) >= 0, clocks
            sk = trace_mod.skew_data(tdir)
            assert max(sk["wait_us"], key=sk["wait_us"].get) == 1, sk
            tp, tus = tele_worst
            assert tp == 1, tele_worst
            trace_wait = sk["wait_us"][1]
            assert abs(trace_wait - tus) <= 0.2 * tus, (trace_wait, tus)
            print(f"proc {pid}: TRACE_MERGED trace_wait={trace_wait} "
                  f"tele_wait={tus}", flush=True)
    elif scenario == "engine_flight_timeout":
        # Flight-recorder post-mortem (ISSUE 3 acceptance): process 1
        # seeds the straggler report (delayed warm op) then dies
        # silently mid-negotiation; process 0's forced
        # NegotiationTimeout dumps the flight recorder — which must be
        # loadable, carry the recent NEGOTIATE events, and name the SAME
        # process as the straggler report. Exercised for BOTH engines by
        # the test parametrization.
        import glob as _glob
        import json as _json
        import shutil
        import signal as _signal
        import time

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core.engine import EngineError, ShutdownError

        fdir = f"/tmp/hvd_flight_{port}"
        if pid == 0:
            shutil.rmtree(fdir, ignore_errors=True)
            os.makedirs(fdir, exist_ok=True)
        os.environ["HVD_FLIGHT_DIR"] = fdir
        e = eng.get_engine()
        if pid == 1:
            time.sleep(1.0)
        h = e.allreduce_async("warm", np.ones((2,), np.float32), False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))
        if pid == 1:
            os.kill(os.getpid(), _signal.SIGKILL)
        h = e.allreduce_async("orphan", np.ones((2,), np.float32), False)
        try:
            e.synchronize(h)
        except ShutdownError:
            raise SystemExit("SIGKILL must not look like a clean shutdown")
        except EngineError as err:
            assert "timed out" in str(err) and "process 1" in str(err), \
                str(err)
        else:
            raise SystemExit("dead peer did not surface")
        deadline = time.monotonic() + 15.0
        dump = None
        while time.monotonic() < deadline and dump is None:
            # Newest-first, retry on a racing retention prune: dumps
            # are unique-per-write now and the cap keeps only the
            # newest K, so a globbed path may vanish before open().
            for cand in sorted(_glob.glob(
                    os.path.join(fdir, "hvd_flight.rank0.*.json")),
                    reverse=True):
                try:
                    dump = _json.load(open(cand))
                    break
                except (OSError, ValueError):
                    continue
            if dump is None:
                time.sleep(0.1)
        assert dump is not None, f"no loadable flight dump in {fdir}"
        assert "process 1" in dump["reason"], dump["reason"]
        names = {ev.get("name") for ev in dump["events"]}
        assert "NEGOTIATE_ALLREDUCE" in names and "QUEUE" in names, names
        waits = dump["straggler"]["wait_us"]
        assert max(waits, key=lambda k: waits[k]) == "1", waits
        print(f"proc {pid}: FLIGHT dump names process 1", flush=True)
        # Same exit rule as engine_peer_sigkill: the coordination
        # service's shutdown barrier can never pass with a SIGKILLed
        # member — skip interpreter teardown after the PASS line.
        print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)
        os._exit(0)
    elif scenario == "engine_cache":
        # Negotiation response cache (ISSUE 4): a STABLE tensor set
        # re-submitted every step — the per-step-gradient pattern — must
        # collapse steady-state rounds to the bitvector fast path (hit
        # counter >> miss counter, zero steady-state misses), a changed
        # tensor set must fall back to a full-table round and stay
        # correct, and reductions must be BITWISE identical to a
        # cache-off world (the test diffs RESULT digests across runs
        # with HVD_CACHE_CAPACITY unset vs =0).
        import hashlib
        import json as _json

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core import telemetry as tele

        cache_off = os.environ.get("HVD_CACHE_CAPACITY") == "0"
        e = eng.get_engine()
        digest = hashlib.sha1()

        def step(names, step_no):
            hs = [e.allreduce_async(
                n, np.full((32,), float((i + 1) * (pid + 1) + step_no),
                           np.float32) * 0.3, True)
                  for i, n in enumerate(names)]
            for h in hs:
                digest.update(e.synchronize(h).tobytes())

        names = [f"grad/{i}" for i in range(4)]
        # Warmup absorbs startup skew (a pending entry re-counts a miss
        # every round until the whole world announces it — only the
        # steady-state deltas below are load-independent).
        for s in range(3):
            step(names, s)
        c1 = tele.REGISTRY.flat_counters()
        steady_steps = 8
        for s in range(3, 3 + steady_steps):
            step(names, s)
        c2 = tele.REGISTRY.flat_counters()
        if not cache_off:
            hits = (c2.get("engine.negotiation.cache_hits", 0)
                    - c1.get("engine.negotiation.cache_hits", 0))
            misses = (c2.get("engine.negotiation.cache_misses", 0)
                      - c1.get("engine.negotiation.cache_misses", 0))
            assert hits >= len(names) * steady_steps, (hits, misses)
            assert misses == 0, (hits, misses)  # steady state: all hit
            c = getattr(e, "_coordinator", None)
            assert c is not None and c.stats["fast_rounds"] > 0, c.stats
            flat = tele.REGISTRY.flat()
            assert flat.get("engine.negotiation.cache_bytes_saved", 0) > 0
        # Changed tensor set: the new name misses -> full round; correct.
        h = e.allreduce_async("late/extra",
                              np.full((8,), float(pid + 2), np.float32),
                              False)
        out = e.synchronize(h)
        expect = local_devices * sum(p + 2 for p in range(nproc))
        np.testing.assert_allclose(out, np.full((8,), float(expect)))
        digest.update(out.tobytes())
        c3 = tele.REGISTRY.flat_counters()
        if not cache_off:
            assert c3.get("engine.negotiation.cache_misses", 0) > \
                c2.get("engine.negotiation.cache_misses", 0), c3
            assert "engine.negotiation.cache_invalidations" not in c3, c3
        else:
            # HVD_CACHE_CAPACITY=0: the cache must be fully inert.
            assert "engine.negotiation.cache_hits" not in c3, c3
        print("RESULT " + digest.hexdigest(), flush=True)
        print(f"proc {pid}: CACHE " + _json.dumps(
            {"hits": int(c3.get("engine.negotiation.cache_hits", 0)),
             "misses": int(c3.get("engine.negotiation.cache_misses", 0))}),
            flush=True)
    elif scenario == "engine_cache_evict":
        # Eviction-driven fallback (ISSUE 4 adversarial satellite): a
        # capacity-2 cache (HVD_CACHE_CAPACITY=2, set by the test) can
        # never hold the 4-tensor steady set — every round some entry
        # missed or was just evicted, rounds stay FULL, evictions bump
        # the invalidations counter in lockstep, and every reduction
        # stays correct.
        from horovod_tpu.core import engine as eng
        from horovod_tpu.core import telemetry as tele

        e = eng.get_engine()
        for _ in range(6):
            hs = [e.allreduce_async(
                f"ev/{i}", np.full((8,), float(i + 1 + pid), np.float32),
                False) for i in range(4)]
            for i, h in enumerate(hs):
                expect = local_devices * sum(i + 1 + p
                                             for p in range(nproc))
                np.testing.assert_allclose(
                    e.synchronize(h), np.full((8,), float(expect)))
        counters = tele.REGISTRY.flat_counters()
        assert counters.get("engine.negotiation.cache_invalidations",
                            0) > 0, counters
        assert counters.get("engine.negotiation.cache_misses", 0) > 0
        c = getattr(e, "_coordinator", None)
        assert c is not None and c.cache is not None
        assert len(c.cache) <= 2, len(c.cache)
        print(f"proc {pid}: EVICT OK", flush=True)
    elif scenario == "engine_peer_shutdown":
        # Cooperative shutdown propagation (reference: shutdown flag in the
        # request list → SHUT_DOWN_ERROR for stragglers,
        # operations.cc:2008-2011, 1833-1848).
        import time

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core.engine import ShutdownError

        e = eng.get_engine()
        if pid == 1:
            time.sleep(1.0)
            hvd.shutdown()
        else:
            h = e.allreduce_async("orphan", np.ones((2,), np.float32),
                                  False)
            try:
                e.synchronize(h)
            except ShutdownError as err:
                print(f"proc {pid}: peer shutdown surfaced: {err}",
                      flush=True)
            else:
                raise SystemExit("peer shutdown did not surface")
    elif scenario == "hierarchical":
        # HVD_HIERARCHICAL_ALLREDUCE=1 (set by the test): N processes x M
        # chips form the (dcn=N, ici=M) two-tier mesh from process
        # grouping; eager, compiled and engine allreduces all route
        # reduce-scatter(ICI) -> psum(DCN) -> all-gather(ICI)
        # (reference: operations.cc:1194-1346, env gate :1760-1778).
        from jax.sharding import NamedSharding, PartitionSpec as P

        import horovod_tpu.jax as hvd_jax
        from horovod_tpu.common import topology
        from horovod_tpu.ops import collectives as C

        tt = topology.two_tier()
        assert tt is not None and tt.devices.shape == (
            nproc, local_devices), tt
        assert C._hier_allreduce_active()

        mine = float(pid + 1)
        # Each process's M chips contribute its value once each.
        expect = local_devices * sum(range(1, nproc + 1))
        out = np.asarray(hvd.allreduce(jnp.full((7,), mine), average=False))
        np.testing.assert_allclose(out, np.full((7,), float(expect)))

        @hvd_jax.jit(in_specs=(P(hvd_jax.HVD_AXIS),), out_specs=P())
        def compiled(x):
            return C.allreduce(x[0], average=False)

        mesh = hvd.mesh()
        shards = [jax.device_put(jnp.full((1, 3), mine), d)
                  for d in jax.local_devices()]
        x = jax.make_array_from_single_device_arrays(
            (hvd.size(), 3), NamedSharding(mesh, P(hvd_jax.HVD_AXIS)),
            shards)
        np.testing.assert_allclose(np.asarray(compiled(x)),
                                   np.full((3,), float(expect)))

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        h = e.allreduce_async("ht", np.full((5,), mine, np.float32), False)
        np.testing.assert_allclose(e.synchronize(h),
                                   np.full((5,), float(expect)))
    elif scenario == "engine_peer_sigkill":
        # A peer dying WITHOUT a tombstone (SIGKILL mid-round) must not
        # hang the survivors: negotiation times out naming the dead
        # process (HVD_NEGOTIATION_TIMEOUT is shortened by the test;
        # reference behavior: an MPI peer death aborts the job — here the
        # survivors get a clean, attributed error instead).
        import signal
        import time

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core.engine import EngineError, ShutdownError

        e = eng.get_engine()
        # Clear any stale done-flags from an earlier run that reused this
        # port BEFORE the warm round: flags are only created after the
        # warm-round barrier, so deletion strictly precedes creation.
        for p in range(nproc):
            try:
                os.unlink(f"/tmp/hvd_sigkill_done_{port}_{p}")
            except OSError:
                pass
        if pid == nproc - 1:
            # Join one round so everyone's coordinator is live, then die
            # silently before the next.
            h = e.allreduce_async("warm", np.ones((2,), np.float32), False)
            e.synchronize(h)
            os.kill(os.getpid(), signal.SIGKILL)
        h = e.allreduce_async("warm", np.ones((2,), np.float32), False)
        e.synchronize(h)
        time.sleep(1.0)  # let the victim die
        h = e.allreduce_async("orphan", np.ones((2,), np.float32), False)
        try:
            e.synchronize(h)
        except ShutdownError:
            raise SystemExit(
                "SIGKILL must not look like a clean shutdown")
        except EngineError as err:
            msg = str(err)
            assert "timed out" in msg and str(nproc - 1) in msg, msg
            print(f"proc {pid}: sigkill surfaced as timeout naming "
                  f"process {nproc - 1}", flush=True)
        else:
            raise SystemExit("dead peer did not surface")
        # The engine surfaced the failure — that is this scenario's
        # contract. Skip the interpreter's atexit teardown: the JAX
        # coordination service's shutdown barrier can never pass with a
        # SIGKILLed member and would turn this PASS into a fatal abort.
        # Process 0 HOSTS the coordination service, so it must outlive
        # the other survivors (file flags; the KV itself dies with p0).
        print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)
        flag = f"/tmp/hvd_sigkill_done_{port}"
        if pid != 0:
            open(f"{flag}_{pid}", "w").close()
            os._exit(0)
        deadline = time.monotonic() + 30.0
        survivors = [p for p in range(1, nproc - 1)]
        while time.monotonic() < deadline:
            if all(os.path.exists(f"{flag}_{p}") for p in survivors):
                break
            time.sleep(0.1)
        os._exit(0)
    elif scenario == "autotune_propagation":
        # Process 0's engine parameters (the autotuner's output) must
        # reach every peer through the negotiation round params
        # (reference: rank 0 tunes and broadcasts a Params struct,
        # parameter_manager.cc:63-77,203-236).
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        if pid == 0:
            e.set_params(cycle_time_s=0.0123, fusion_threshold=777216)
        # Tick a few rounds so params ride to everyone.
        for i in range(3):
            h = e.allreduce_async(f"tick{i}", np.ones((2,), np.float32),
                                  False)
            np.testing.assert_allclose(
                e.synchronize(h),
                np.full((2,), float(local_devices * nproc)))
            time.sleep(0.05)
        # Params ride EVERY negotiation round, and rounds tick even when
        # idle (peers block on our round message otherwise) — so just
        # poll; no further collectives needed.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            cyc, fus = e.current_params()
            if abs(cyc - 0.0123) < 1e-9 and fus == 777216:
                break
            time.sleep(0.1)
        cyc, fus = e.current_params()
        assert abs(cyc - 0.0123) < 1e-9 and fus == 777216, (cyc, fus)
        print(f"proc {pid}: params propagated", flush=True)
    elif scenario == "engine_fuzz":
        # The reference's negotiation guarantee: any tensors, enqueued in
        # any order at any time, complete with identical batch
        # composition everywhere (SURVEY hard part (c); reference:
        # ConstructMPIResponse handles arbitrary arrival interleavings).
        # Each process submits the SAME 40 ops but in its own shuffled
        # order with random think-time between enqueues — so negotiation
        # rounds see genuinely divergent partial tables — then everything
        # must still complete with the right values.
        import random
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        rnd = random.Random(1234 + pid)  # per-process order + timing
        ops = []
        for i in range(40):
            kind = ("allreduce", "broadcast", "allgather")[i % 3]
            ops.append((kind, i))
        rnd.shuffle(ops)
        handles = {}
        # Per-process-DIVERGENT payloads: identical inputs would let a
        # no-op broadcast or misordered allgather pass undetected.
        mine = lambda i: float(i + 1 + pid * 100)  # noqa: E731
        for kind, i in ops:
            val = np.full((8,), mine(i), np.float32)
            if kind == "allreduce":
                handles[i] = e.allreduce_async(f"fz/{i}", val, False)
            elif kind == "broadcast":
                handles[i] = e.broadcast_async(f"fz/{i}", val, 0)
            else:
                handles[i] = e.allgather_async(f"fz/{i}", val)
            if rnd.random() < 0.5:
                time.sleep(rnd.random() * 0.05)
        for kind, i in sorted(ops, key=lambda t: t[1]):
            out = e.synchronize(handles[i])
            if kind == "allreduce":
                expect = local_devices * sum(
                    i + 1 + p * 100 for p in range(nproc))
                np.testing.assert_array_equal(
                    out, np.full((8,), float(expect)))
            elif kind == "broadcast":
                # Root is process 0's first chip: everyone must receive
                # process 0's value, not their own.
                np.testing.assert_array_equal(
                    out, np.full((8,), float(i + 1)))
            else:
                # Rank-ordered concat: controller p's value occupies the
                # slots of its local_devices chips.
                expect = np.repeat(
                    [i + 1 + p * 100 for p in range(nproc)],
                    local_devices * 8).astype(np.float32)
                np.testing.assert_array_equal(out.ravel(), expect)
        print(f"proc {pid}: fuzz 40 ops OK", flush=True)
    elif scenario == "engine_reinit":
        # Collective engine shutdown + re-init across the WORLD: the new
        # incarnation negotiates in a fresh KV namespace (generation
        # counter) and must neither consume the previous generation's
        # tombstones/final-round keys nor leak them (reference contract:
        # MPI_Init/Finalize pairing; here coordinator.py's generation +
        # residue-reclaim design, unit-tested in test_coordinator.py but
        # never before exercised with real peer processes).
        from horovod_tpu.core import engine as eng

        for gen in range(3):
            e = eng.get_engine()
            hs = [e.allreduce_async(f"g{gen}/t{i}",
                                    np.full((4,), float(gen + i + 1),
                                            np.float32), False)
                  for i in range(3)]
            for i, h in enumerate(hs):
                np.testing.assert_allclose(
                    e.synchronize(h),
                    np.full((4,), float((gen + i + 1)
                                        * local_devices * nproc)))
            # Engine lifecycle is COLLECTIVE (every process shuts down
            # the same number of times) — same as MPI_Finalize.
            eng.shutdown_engine()
        print(f"proc {pid}: three engine generations OK", flush=True)
    elif scenario == "engine_idle_backoff":
        # After an all-quiet stretch every process's negotiation loop has
        # backed off to HVD_NEGOTIATION_IDLE_MAX. Peers back off
        # CONCURRENTLY and a local enqueue wakes the local loop, so the
        # first op after the stretch must land within ~one backoff cap —
        # NOT nproc × cap compounding (reference analogue: the MPI
        # coordinator ticks every rank each cycle regardless of idleness,
        # operations.cc:2117 — it has no backoff to compound).
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        # Warm the negotiated path: coordinator built, round 0 consumed.
        np.testing.assert_allclose(
            e.synchronize(e.allreduce_async("warm", np.ones((2,), np.float32),
                                            False)),
            np.full((2,), float(local_devices * nproc)))
        # Per-run baseline: a second active op measures what THIS host
        # currently charges for one non-idle round trip, so the pass
        # bound tracks CI load instead of assuming an absolute cost
        # (ADVICE r3: absolute dt < cap+3 could flake under heavy
        # concurrent subprocess worlds).
        t0 = time.monotonic()
        e.synchronize(e.allreduce_async("baseline",
                                        np.ones((2,), np.float32), False))
        baseline = time.monotonic() - t0
        cap = float(os.environ.get("HVD_NEGOTIATION_IDLE_MAX", "1.0"))
        # The failure mode being pinned (serial compounding of peer
        # backoffs) costs >= (nproc-1)*cap = 12s at this cap; the bound
        # scales with measured host load but is CLAMPED below the
        # compounding signature so a slow baseline can never mask the
        # regression this test exists to catch. The floor keeps the
        # bound positive for small worlds/caps (nproc=2, cap=1 would
        # otherwise make it 0 and auto-fail — r4 advisor); NOTE at such
        # tiny worlds the floor sits ABOVE the compounding cost, so the
        # scenario only detects compounding for nproc*cap large enough
        # that (nproc-1)*cap - 1 > cap + 1 (the np=4/cap=4 config run
        # by test_multiprocess.py qualifies).
        bound = max(cap + 1.0,
                    min(cap + 3.0 + 2 * baseline, (nproc - 1) * cap - 1.0))
        # Two unconditional attempts (collectives must stay collective —
        # a data-dependent retry on one process would deadlock the
        # world); pass if EITHER lands under the bound. A one-off load
        # spike flakes one attempt; compounding misses both.
        dts = []
        for attempt in range(2):
            time.sleep(max(3.0, 2 * cap))  # idle long enough to max backoff
            t0 = time.monotonic()
            out = e.synchronize(
                e.allreduce_async(f"after_idle{attempt}",
                                  np.ones((2,), np.float32), False))
            dts.append(time.monotonic() - t0)
            np.testing.assert_allclose(
                out, np.full((2,), float(local_devices * nproc)))
        dt = min(dts)
        assert dt < bound, (f"first op after idle took {dts} twice "
                            f"(bound {bound:.2f}s, baseline {baseline:.2f}s)")
        print(f"proc {pid}: IDLE_LATENCY {dt:.3f}", flush=True)
    elif scenario == "negotiation_latency":
        # Control-plane cost vs world size (VERDICT r3 #4): per-op
        # latency of the negotiated path, sequential (1 op : >=1 round)
        # and burst (K ops land in few rounds — the amortization the
        # engine cycle + fusion exist for), plus the coordinator's own
        # round stats. docs/running.md carries the measured curve.
        import json as _json
        import time

        from horovod_tpu.core import engine as eng

        e = eng.get_engine()
        np.testing.assert_allclose(
            e.synchronize(e.allreduce_async("warm", np.ones((2,), np.float32),
                                            False)),
            np.full((2,), float(local_devices * nproc)))
        m = 20
        t0 = time.monotonic()
        for i in range(m):
            e.synchronize(e.allreduce_async(f"lat{i}",
                                            np.ones((64,), np.float32),
                                            False))
        seq_ms = (time.monotonic() - t0) / m * 1e3
        k = 32
        t0 = time.monotonic()
        hs = [e.allreduce_async(f"burst{i}", np.ones((64,), np.float32),
                                False) for i in range(k)]
        for h in hs:
            e.synchronize(h)
        burst_ms = (time.monotonic() - t0) / k * 1e3
        stats = dict(getattr(e, "_coordinator").stats) \
            if getattr(e, "_coordinator", None) is not None else {}
        per_round_ms = (stats["round_s"] / stats["rounds"] * 1e3
                        if stats.get("rounds") else None)
        print(f"proc {pid}: NEG_LATENCY " + _json.dumps(
            {"nproc": nproc, "seq_ms": round(seq_ms, 2),
             "burst_ms": round(burst_ms, 2),
             "rounds": stats.get("rounds"),
             "kv_gets": stats.get("kv_gets"),
             "per_round_ms": (round(per_round_ms, 2)
                              if per_round_ms else None)}), flush=True)
    elif scenario == "torch_errors":
        # Reference error-path tests drive mismatches through the TORCH
        # API and assert the coordinator error surfaces as an exception on
        # every rank (test_torch.py:265-349).
        import torch

        import horovod_tpu.torch as hvt

        hvt.init()

        def expect(fn, needle):
            try:
                fn()
            except hvt.EngineError as err:
                assert needle in str(err), (needle, str(err))
                print(f"proc {pid}: torch {needle} OK", flush=True)
            else:
                raise SystemExit(f"torch API surfaced no error for {needle}")

        dt = torch.float32 if pid == 0 else torch.float64
        expect(lambda: hvt.allreduce(torch.ones(4, dtype=dt),
                                     average=False, name="dt"),
               "Mismatched data types")
        shape = (4,) if pid == 0 else (2, 2)
        expect(lambda: hvt.allreduce(torch.ones(shape), average=False,
                                     name="shp"),
               "Mismatched tensor shapes")
        expect(lambda: hvt.broadcast(torch.ones(2), root_rank=pid,
                                     name="rt"),
               "Mismatched root ranks")
        # And the API still works afterwards.
        out = hvt.allreduce(torch.ones(3), average=False, name="after")
        np.testing.assert_allclose(out.numpy(),
                                   np.full((3,), float(local_devices * nproc)))
    elif scenario == "numerics_chaos":
        # Numerics observatory (ISSUE 8 acceptance), both engines via the
        # test parametrization: (1) process 1 submits NaN-poisoned
        # gradients through the engine — the `nonfinite` verdict on
        # EVERY survivor names that process (the submit-side counts are
        # allgathered at detection); (2) the cross-rank consistency
        # digest catches an artificially-desynced parameter bucket with
        # an attributed report naming the process AND the bucket,
        # identically on every process. Flight dumps land for both.
        import glob as _glob
        import json as _json

        from horovod_tpu.core import engine as eng
        from horovod_tpu.core import numerics as numx

        assert os.environ.get("HVD_NUMERICS") == "warn"
        fdir = os.environ["HVD_FLIGHT_DIR"]  # test-made, empty, shared
        e = eng.get_engine()
        t = np.ones((4,), np.float32)
        if pid == 1:
            t[1] = np.nan
        h = e.allreduce_async("poison/grad", t, average=False)
        res = e.synchronize(h)  # warn: observe, don't raise
        assert np.isnan(res).any()  # the NaN survived the reduction
        rep = numx.report()
        v = rep["verdicts"]["nonfinite"]
        assert v["tensor"] == "poison/grad", v
        assert v["processes"] == [1], v
        assert v["local_nonfinite_at_submit"] == (1 if pid == 1 else 0)
        print(f"proc {pid}: NONFINITE names process 1", flush=True)
        # Counter-name parity across engines: the hooks ARE shared code;
        # pin the exact family so the native/python runs can't diverge.
        flat = rep["metrics"]
        for name in ("numerics.engine.nonfinite_results",
                     "numerics.nonfinite.events"):
            assert flat.get(name, 0) >= 1, (name, sorted(flat))
        if pid == 1:
            assert flat.get("numerics.engine.nonfinite_submits", 0) >= 1
        # The engine still works after the verdict (warn is observe-only).
        h = e.allreduce_async("after", np.ones((2,), np.float32), False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))

        # --- consistency digest on a desynced bucket -------------------
        params = {"w": jnp.arange(24.0, dtype=jnp.float32),
                  "s": jnp.ones((5,), jnp.bfloat16)}
        ok = hvd.check_consistency(params, tag="sync")
        assert ok["ok"] is True, ok
        if pid == 1:
            w = np.asarray(params["w"]).copy()
            w[7] += 1e-3  # one element, one process: desync
            params["w"] = jnp.asarray(w)
        bad = hvd.check_consistency(params, tag="desync", step=42)
        assert bad["ok"] is False, bad
        assert sorted(bad["mismatch"]) == ["float32"], bad
        # Two controllers, 4 chips each: a digest disagreement is a
        # 4-vs-4 TIE — no strict majority exists, so the report honestly
        # names BOTH processes and marks the ambiguity (a vote that
        # crowned either side could blame the healthy one). Identical
        # report on every process is the cross-rank contract.
        assert bad["processes"] == [0, 1], bad
        assert bad.get("ambiguous") is True, bad
        v2 = numx.report()["verdicts"]["diverged"]
        assert v2["processes"] == [0, 1] and v2["buckets"] == ["float32"]
        assert v2["step"] == 42 and v2["tag"] == "desync"
        print(f"proc {pid}: DIVERGED tie names both processes, "
              "bucket float32", flush=True)
        dumps = _glob.glob(
            os.path.join(fdir, f"hvd_flight.rank{pid}.*.json"))
        assert len(dumps) >= 2, dumps  # one per verdict kind, this rank
        reasons = sorted(_json.load(open(d))["reason"] for d in dumps)
        assert any("nonfinite" in r for r in reasons), reasons
        assert any("diverged" in r for r in reasons), reasons
        print(f"proc {pid}: FLIGHT dumps {len(dumps)}", flush=True)
    elif scenario == "fleet":
        # Fleet observability plane end-to-end: both ranks publish
        # latency snapshots over the KV plane (HVD_FLEET_DIR set by the
        # test, interval set huge so only explicit beats land — the
        # SIGKILL race below must be deterministic); rank 0 merges. The
        # contract under test: identical instrument vocabularies across
        # ranks, a world p99 that reflects rank 1's injected skew, and a
        # SIGKILLed rank going STALE without wedging rank 0's rollup.
        import signal
        import time
        import json as _json

        from horovod_tpu.core import engine as eng, fleet

        e = eng.get_engine()
        assert fleet._publisher is not None, "fleet publisher not started"

        def _ar(name, **kw):
            h = e.allreduce_async(name, np.ones((4,), np.float32), False,
                                  **kw)
            e.synchronize(h)

        for i in range(8):
            _ar(f"fast{i}")
        for i in range(4):
            if pid == nproc - 1:
                time.sleep(0.12)  # the skew: peers wait on this rank
            _ar(f"slow{i}")
        _ar("deadlined", deadline_ms=30000.0)
        h = e.allgather_async("gather", np.ones((3,), np.float32))
        e.synchronize(h)
        h = e.broadcast_async("bcast", np.ones((2,), np.float32), 0)
        e.synchronize(h)

        fleet._publisher.publish_once()
        _ar("sync")  # barrier: every rank has published its final beat
        if pid == nproc - 1:
            os.kill(os.getpid(), signal.SIGKILL)

        time.sleep(0.5)  # let the victim die
        g, ep = fleet._world_coords()
        mine = fleet.local_snapshot(rank=pid)
        peer = _json.loads(fleet._aggregator._kv.try_get(
            fleet.snapshot_key(g, ep, nproc - 1)))
        # Identical instrument vocabularies (names AND bucket layout are
        # already pinned engine-to-engine by hvdcheck; this pins them
        # rank-to-rank through the actual publish path).
        assert sorted(mine["hists"]) == sorted(peer["hists"]), (
            sorted(mine["hists"]), sorted(peer["hists"]))
        for want in ("engine.latency.allreduce", "engine.latency.allgather",
                     "engine.latency.broadcast", "engine.phase.queue",
                     "engine.deadline.margin"):
            assert want in mine["hists"], sorted(mine["hists"])

        rep = hvd.fleet_report()
        assert rep["size"] == nproc, rep["size"]
        ar = rep["ops"]["allreduce"]
        # Merged exactly across ranks: rank 0's live registry has all 14
        # allreduces; every peer's KV snapshot was published before the
        # "sync" barrier op, so it carries 13.
        assert ar["count"] == 14 + 13 * (nproc - 1), ar
        # The skewed ops put >= 4 observations per survivor rank above
        # 0.12 s: the world p99 must live in the slow tail while the
        # p50 stays fast.
        assert ar["p99_us"] > 50_000, ar
        assert ar["p50_us"] < ar["p99_us"], ar
        print(f"proc {pid}: world p99 {ar['p99_us']}us over "
              f"{ar['count']} ops", flush=True)

        time.sleep(1.5)  # > HVD_FLEET_LEASE_S: the dead rank's seq froze
        t0 = time.monotonic()
        rep = hvd.fleet_report()
        took = time.monotonic() - t0
        assert took < 5.0, f"rollup wedged for {took:.1f}s"
        victim = str(nproc - 1)
        assert rep["ranks"][victim]["state"] == "STALE", rep["ranks"]
        assert int(victim) in rep["stale"], rep["stale"]
        assert rep["ranks"]["0"]["state"] == "OK", rep["ranks"]
        print(f"proc {pid}: rank {victim} STALE after lease, rollup "
              f"in {took * 1e3:.0f}ms", flush=True)
        # Same exit discipline as engine_peer_sigkill: the JAX
        # coordination shutdown barrier can never pass with a SIGKILLed
        # member — skip atexit entirely.
        print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)
        os._exit(0)
    elif scenario == "doctor_withheld":
        # Hang-doctor acceptance (ISSUE 18): process 1's submit of
        # 'held' is withheld through the faultline; process 0 stalls and
        # the stall dump engages the doctor, whose verdict must be
        # missing_submitter naming the EXACT tensor and rank. Process 1
        # — healthy, and the one being blamed — reaches the identical
        # verdict through on-demand hvd.diagnose() over the fleet/KV
        # plane. Both engines via the test parametrization.
        import time

        from horovod_tpu.core import doctor, engine as eng
        from horovod_tpu.core import faultline as flt
        from horovod_tpu.core.engine import EngineError

        e = eng.get_engine()
        h = e.allreduce_async("warm", np.ones((2,), np.float32), False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))

        verdict = None
        if pid == 1:
            # Withhold exactly the next enqueue on THIS rank.
            flt.configure("engine.submit:fail:1")
            try:
                e.allreduce_async("held", np.ones((2,), np.float32),
                                  False)
            except EngineError as err:
                assert "injected fault" in str(err), str(err)
            else:
                raise SystemExit("injected submit fault did not fire")
            flt.reset()
            # Diagnose on demand until the peer's stall snapshot lands
            # on the KV plane (its watchdog fires within ~one 1 s stall
            # interval).
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                v = hvd.diagnose()
                if v.get("kind") == "missing_submitter":
                    verdict = v
                    break
                time.sleep(0.25)
        else:
            h = e.allreduce_async("held", np.ones((2,), np.float32),
                                  False)
            # The stall watchdog dumps kind="stall" each interval; every
            # dump re-runs the doctor, so the verdict appears without
            # this thread doing anything (it is WEDGED in real hangs).
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                v = doctor.last_verdict()
                if v is not None and v.get("kind") == "missing_submitter":
                    verdict = v
                    break
                time.sleep(0.25)
            assert verdict is not None and verdict["trigger"] == "stall", \
                verdict
        assert verdict is not None, doctor.last_verdict()
        # The acceptance bar: identical attribution on EVERY survivor.
        assert verdict["tensor"] == "held", verdict
        assert verdict["ranks"] == [1], verdict
        assert "never announced 'held'" in verdict["detail"], verdict
        print(f"proc {pid}: DOCTOR blames rank 1 tensor 'held' "
              f"(trigger {verdict['trigger']})", flush=True)
        if pid == 1:
            # Release the stalled peer: submit 'held' for real.
            h = e.allreduce_async("held", np.ones((2,), np.float32),
                                  False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))
    elif scenario == "doctor_dead_peer":
        # A SIGKILLed peer must classify as dead_peer (the elastic death
        # note outranks missing_submitter), and the diagnoser must not
        # wedge against the corpse. HVD_ELASTIC=1 + a short lease are
        # set by the test; the survivor's orphaned submit rides the
        # stall + attributed-negotiation-failure dumps, each of which
        # re-runs the doctor with the hardened death note.
        import signal as _signal
        import time

        from horovod_tpu.core import doctor, engine as eng
        from horovod_tpu.core.engine import ShutdownError

        e = eng.get_engine()
        h = e.allreduce_async("warm", np.ones((2,), np.float32), False)
        np.testing.assert_allclose(
            e.synchronize(h), np.full((2,), float(local_devices * nproc)))
        if pid == 1:
            # Let a few elastic heartbeats land first: the beat loop's
            # first publish is one interval (lease/4) after hvd.init,
            # and a victim that never beat is "never heard from" —
            # covered by the startup GRACE, not the lease, so the death
            # note would lag by the full grace window.
            time.sleep(1.5)
            os.kill(os.getpid(), _signal.SIGKILL)
        # Survivor: wait out the victim's lease so the orphaned submit
        # negotiates against a peer the elastic plane has already
        # declared dead (the liveness probe fails the round with the
        # attribution, and the doctor sees both the death note and the
        # still-pending victim on the negotiation-failure dump).
        time.sleep(2.5)
        h = e.allreduce_async("orphan", np.ones((2,), np.float32), False)
        try:
            e.synchronize(h)
        except ShutdownError:
            raise SystemExit("SIGKILL must not look like a clean shutdown")
        except Exception as err:
            print(f"proc {pid}: orphan failed as expected: {err}",
                  flush=True)
        else:
            raise SystemExit("dead peer did not surface")
        verdict = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            v = doctor.last_verdict()
            if v is not None and v.get("kind") == "dead_peer" \
                    and v.get("ranks") == [1]:
                verdict = v
                break
            time.sleep(0.25)
        assert verdict is not None, doctor.last_verdict()
        assert verdict["tensor"] == "orphan", verdict
        assert "dead" in verdict["detail"], verdict
        # The diagnoser itself must stay prompt with a corpse in the
        # world: on-demand diagnosis returns, it does not wedge.
        t0 = time.monotonic()
        hvd.diagnose()
        took = time.monotonic() - t0
        assert took < 10.0, f"diagnoser wedged for {took:.1f}s"
        print(f"proc {pid}: DOCTOR verdict dead_peer names rank 1",
              flush=True)
        # Same exit discipline as engine_peer_sigkill: the JAX
        # coordination shutdown barrier can never pass with a SIGKILLed
        # member — skip interpreter teardown after the PASS line.
        print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)
        os._exit(0)
    elif scenario == "mismatch":
        os.environ["HVD_CONSISTENCY_CHECKS"] = "1"
        from horovod_tpu.common.topology import HorovodInternalError

        # Matching op first: must pass.
        hvd.allreduce(jnp.ones((2,)), average=False)
        # Then a shape mismatch: every process must raise.
        shape = (2,) if pid == 0 else (3,)
        try:
            hvd.allreduce(jnp.ones(shape), average=False)
        except HorovodInternalError:
            print(f"proc {pid}: mismatch detected OK", flush=True)
        else:
            raise SystemExit("consistency check did not fire")
    else:
        raise SystemExit(f"unknown scenario {scenario}")

    print(f"proc {pid}: SCENARIO {scenario} PASSED", flush=True)


if __name__ == "__main__":
    main()
